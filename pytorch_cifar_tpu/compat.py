"""Torch-checkpoint compatibility: import reference ``ckpt.pth`` weights.

The reference saves ``{'net': state_dict, 'acc': ..., 'epoch': ...}``
(main.py:140-147, main_dist.py:239-247). A user switching frameworks can
carry those checkpoints over: :func:`import_torch_state_dict` maps a torch
``state_dict`` (as numpy arrays — no torch dependency here; the
``tools/import_torch_checkpoint.py`` CLI does the ``torch.load``) onto our
flax param/stat trees for any registry model.

Alignment strategy. A ``state_dict`` lists tensors in module DEFINITION
order, while flax param nodes are discovered in CALL order — and the two
diverge (PreActResNet applies the shortcut conv before conv1,
reference models/preact_resnet.py:17-21). The importer therefore records
our model's call order with a module interceptor and pairs each node with
the FIRST unused state_dict module of the same kind and shape (stable
order-preserving matching within each shape class). Distinct-shape
reorderings (the shortcut case) align exactly; identical-shape leaves keep
their relative order in every zoo model, and every pairing is
shape-checked, so drift fails loudly. Across the zoo every state_dict
module matches 1:1 — even the reference's dead expand conv
(expand_ratio==1, models/efficientnet.py:60-67) round-trips, because our
EfficientNet mirrors its construction and (discarded) execution position;
a module that nevertheless finds no home is reported, not silently
dropped (tests/test_compat.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Tuple

import numpy as np

# linears whose input is a flattened feature map need their rows permuted
# from torch's NCHW flatten order to our NHWC one; only LeNet — every other
# zoo model pools to 1x1 before its classifier, where the orders coincide
LINEAR_FLATTEN: Dict[str, Dict[int, Tuple[int, int, int]]] = {
    "LeNet": {0: (16, 5, 5)}
}


def stock_execution_kwargs(name: str) -> Dict[str, Any]:
    """Model kwargs forcing the literal per-branch execution whose CALL
    order matches torch definition order (GoogLeNet's default merged path
    fetches its 1x1 kernels up front; the param tree is identical, so
    weights imported against the stock twin load into the merged model)."""
    return {"merged_1x1": False} if name == "GoogLeNet" else {}


def record_call_order(model, x) -> Tuple[List[Tuple[str, tuple]], Any]:
    """Init ``model`` under an interceptor recording every leaf
    Conv/Dense/BatchNorm scope path in call order.

    Returns ``(order, variables)`` where order entries are
    ``('conv'|'linear'|'bn', path_tuple)``.
    """
    import jax
    from flax import linen as nn

    from pytorch_cifar_tpu.models.common import BatchNorm as OurBatchNorm

    order: List[Tuple[str, tuple]] = []
    seen = set()
    bn_types = (nn.BatchNorm, OurBatchNorm)

    def interceptor(next_fun, args, kwargs, context):
        m = context.module
        if context.method_name == "__call__" and isinstance(
            m, (nn.Conv, nn.Dense) + bn_types
        ):
            kind = (
                "bn"
                if isinstance(m, bn_types)
                else "linear" if isinstance(m, nn.Dense) else "conv"
            )
            path = tuple(m.path)
            if path not in seen:
                seen.add(path)
                order.append((kind, path))
        return next_fun(*args, **kwargs)

    with nn.intercept_methods(interceptor):
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
    return order, variables


def _node_at(tree, path):
    node = tree
    for k in path:
        if node is None or k not in node:
            return None
        node = node[k]
    return node


def normalize_state_dict(obj: Mapping) -> Tuple[Mapping, Dict[str, Any]]:
    """Unwrap the reference's ``{'net': sd, 'acc', 'epoch'}`` envelope and
    strip DataParallel's ``module.`` prefixes. Returns (state_dict, meta).
    """
    meta: Dict[str, Any] = {}
    sd = obj
    if "net" in obj and isinstance(obj["net"], Mapping):
        sd = obj["net"]
        if "acc" in obj:
            meta["acc"] = float(obj["acc"])
        if "epoch" in obj:
            meta["epoch"] = int(obj["epoch"])
    out = {}
    for k, v in sd.items():
        out[k[len("module.") :] if k.startswith("module.") else k] = v
    return out, meta


def _torch_groups(sd: Mapping[str, np.ndarray]):
    """Group flat ``state_dict`` keys by module prefix, preserving
    definition order; classify each group as conv/linear/bn."""
    prefixes: List[str] = []
    by_prefix: Dict[str, Dict[str, np.ndarray]] = {}
    for k, v in sd.items():
        if k.endswith("num_batches_tracked"):
            continue
        prefix, _, leaf = k.rpartition(".")
        if prefix not in by_prefix:
            by_prefix[prefix] = {}
            prefixes.append(prefix)
        by_prefix[prefix][leaf] = np.asarray(v)
    groups = []
    for p in prefixes:
        g = by_prefix[p]
        if "running_mean" in g:
            kind = "bn"
        elif "weight" in g and g["weight"].ndim == 4:
            kind = "conv"
        elif "weight" in g and g["weight"].ndim == 2:
            kind = "linear"
        else:
            raise ValueError(
                f"unrecognized state_dict module {p!r} with leaves "
                f"{sorted(g)} / weight ndim "
                f"{g.get('weight', np.empty(0)).ndim}"
            )
        groups.append((kind, p, g))
    return groups


def _torch_signature(kind: str, g: Mapping[str, np.ndarray]):
    if kind == "conv":
        o, i, kh, kw = g["weight"].shape
        return ("conv", (kh, kw, i, o), "bias" in g)
    if kind == "linear":
        o, i = g["weight"].shape
        return ("linear", (i, o), "bias" in g)
    return ("bn", g["weight"].shape, True)


def _flax_signature(kind: str, p_node):
    if kind == "conv":
        return ("conv", tuple(p_node["kernel"].shape), "bias" in p_node)
    if kind == "linear":
        return ("linear", tuple(p_node["kernel"].shape), "bias" in p_node)
    return ("bn", tuple(p_node["scale"].shape), True)


def _pair_with_groups(
    name: str,
    params,
    groups,
    num_classes: int,
    src_desc: str,
):
    """The ONE pairing loop both directions share: align our ``name``
    model's recorded call order with torch ``groups`` by first-fit within
    each (kind, shape-signature) class. Any change to the alignment
    strategy lives here, so import∘export stays a bijection by
    construction.

    ``params=None`` pairs against a fresh init (the import direction).
    Returns ``(pairs, used, params, stats)``: pairs are
    ``(kind, path, p_node, torch_prefix, group_dict, linear_i)`` in call order
    (``linear_i`` indexes LINEAR_FLATTEN; None for non-linears), ``used``
    masks the consumed groups, and params/stats are the trees the
    p_node references point into. Raises when one of our nodes finds no
    group — ``src_desc`` names the torch side in the error.
    """
    import jax

    from pytorch_cifar_tpu.models import create_model

    model = create_model(
        name, num_classes=num_classes, **stock_execution_kwargs(name)
    )
    x = np.zeros((2, 32, 32, 3), np.float32)
    order, variables = record_call_order(model, x)
    if params is None:
        params = jax.tree_util.tree_map(
            np.asarray, dict(variables["params"])
        )
    stats = jax.tree_util.tree_map(
        np.asarray, dict(variables.get("batch_stats", {}))
    )
    used = [False] * len(groups)
    pairs = []
    linear_i = 0
    for kind, path in order:
        p_node = _node_at(params, path)
        if p_node is None:
            raise ValueError(f"no param node at {path} for recorded {kind}")
        sig = _flax_signature(kind, p_node)
        for gi, (tk, tprefix, g) in enumerate(groups):
            if used[gi] or tk != kind:
                continue
            if _torch_signature(tk, g) != sig:
                continue
            used[gi] = True
            pairs.append(
                (
                    kind,
                    path,
                    p_node,
                    tprefix,
                    g,
                    linear_i if kind == "linear" else None,
                )
            )
            break
        else:
            raise ValueError(
                f"{src_desc} has no unused {kind} of signature {sig} for "
                f"our node {'/'.join(path)} — wrong --model? (Alignment "
                "is only guaranteed for the reference zoo; see "
                "import_torch_state_dict's SCOPE note.)"
            )
        if kind == "linear":
            linear_i += 1
    return pairs, used, params, stats


def import_torch_state_dict(
    name: str,
    state_dict: Mapping[str, np.ndarray],
    num_classes: int = 10,
):
    """Map a reference torch ``state_dict`` onto our ``name`` registry
    model. Returns ``(params, batch_stats, report)``; ``report`` lists the
    unmatched (dead) torch modules, if any. Raises if any of OUR nodes
    finds no matching tensor — that means a wrong --model choice, and a
    silently partial import would be worse than an error.

    SCOPE: the first-fit-within-shape-class alignment is verified for the
    reference zoo only (every zoo model keeps identical-shape leaves in the
    same relative order on both sides — pinned by the transplant parity
    suite, tests/test_torch_parity.py). For a model OUTSIDE the zoo, two
    same-kind same-shape modules called in a different order than torch
    defines them would cross-pair silently: the import stays shape-valid
    but loads the wrong tensors. Validate non-zoo imports with a forward
    cross-check against the donor model's outputs.
    """
    groups = _torch_groups(state_dict)
    pairs, used, params, stats = _pair_with_groups(
        name, None, groups, num_classes, src_desc="state_dict"
    )
    flatten = LINEAR_FLATTEN.get(name, {})
    for kind, path, p_node, _tprefix, g, linear_i in pairs:
        if kind == "conv":
            p_node["kernel"] = np.transpose(g["weight"], (2, 3, 1, 0))
            if "bias" in g:
                p_node["bias"] = g["bias"]
        elif kind == "linear":
            w = g["weight"]
            if linear_i in flatten:
                c, h, wd = flatten[linear_i]
                w = (
                    w.reshape(-1, c, h, wd)
                    .transpose(0, 2, 3, 1)
                    .reshape(w.shape[0], -1)
                )
            p_node["kernel"] = w.T
            if "bias" in g:
                p_node["bias"] = g["bias"]
        else:
            p_node["scale"] = g["weight"]
            p_node["bias"] = g["bias"]
            s_node = _node_at(stats, path)
            if s_node is None:
                raise ValueError(f"no batch_stats node at {path}")
            s_node["mean"] = g["running_mean"]
            s_node["var"] = g["running_var"]

    report = {
        "unmatched_torch_modules": [
            f"{tprefix} ({tk})"
            for (tk, tprefix, _), u in zip(groups, used)
            if not u
        ]
    }
    return params, stats, report


def export_torch_state_dict(
    name: str,
    params,
    batch_stats,
    template_sd: Mapping[str, np.ndarray],
    num_classes: int = 10,
) -> Dict[str, np.ndarray]:
    """Map OUR ``name`` model's trees onto a torch ``state_dict`` — the
    exact inverse of :func:`import_torch_state_dict`, so anything trained
    here becomes loadable by the reference's own ``--resume``
    (main.py:77-84: ``net.load_state_dict(checkpoint['net'])``).

    ``template_sd`` supplies the torch key names, definition order, shapes
    and dtypes (build it from a freshly-constructed reference model's
    ``state_dict()``; values are ignored). The same call-order +
    first-fit-within-shape-class pairing as the importer is used — the
    pairing is a bijection, so export∘import and import∘export are
    identity on the reference zoo (pinned in tests/test_compat.py).
    ``num_batches_tracked`` leaves are emitted as zeros: torch only reads
    them under ``momentum=None``, which no zoo model uses.

    Returns a flat dict in the template's key order (bare keys — the CLI
    adds the reference's DataParallel ``module.`` prefix). Raises if any
    template module finds no source node (a strict ``load_state_dict``
    would be handed an uninitialized tensor) or any of our recorded nodes
    finds no template slot (wrong --model for this template).
    """
    template_sd, _ = normalize_state_dict(template_sd)
    groups = _torch_groups(template_sd)
    pairs, used, _, _ = _pair_with_groups(
        name, params, groups, num_classes, src_desc="template state_dict"
    )
    flatten = LINEAR_FLATTEN.get(name, {})
    by_prefix: Dict[str, Dict[str, np.ndarray]] = {}

    for kind, path, p_node, tprefix, g, linear_i in pairs:
        out: Dict[str, np.ndarray] = {}
        if kind == "conv":
            out["weight"] = np.transpose(
                np.asarray(p_node["kernel"]), (3, 2, 0, 1)
            )
            if "bias" in g:
                out["bias"] = np.asarray(p_node["bias"])
        elif kind == "linear":
            w = np.asarray(p_node["kernel"]).T  # (out, in_nhwc)
            if linear_i in flatten:
                c, h, wd = flatten[linear_i]
                w = (
                    w.reshape(-1, h, wd, c)
                    .transpose(0, 3, 1, 2)
                    .reshape(w.shape[0], -1)
                )
            out["weight"] = w
            if "bias" in g:
                out["bias"] = np.asarray(p_node["bias"])
        else:
            s_node = _node_at(batch_stats, path)
            if s_node is None:
                raise ValueError(f"no batch_stats node at {path}")
            out["weight"] = np.asarray(p_node["scale"])
            out["bias"] = np.asarray(p_node["bias"])
            out["running_mean"] = np.asarray(s_node["mean"])
            out["running_var"] = np.asarray(s_node["var"])
        by_prefix[tprefix] = out

    unused = [
        f"{tprefix} ({tk})"
        for (tk, tprefix, _), u in zip(groups, used)
        if not u
    ]
    if unused:
        raise ValueError(
            "template modules with no source node (strict load_state_dict "
            f"would receive uninitialized tensors): {unused}"
        )

    result: Dict[str, np.ndarray] = {}
    for k, v in template_sd.items():
        if k.endswith("num_batches_tracked"):
            result[k] = np.zeros((), np.asarray(v).dtype)
            continue
        prefix, _, leaf = k.rpartition(".")
        val = by_prefix[prefix][leaf]
        result[k] = np.ascontiguousarray(
            val.astype(np.asarray(v).dtype, copy=False)
        )
    return result
