"""Pallas TPU kernel: fused 3x3 conv + inference BatchNorm + ReLU, NHWC.

The conv-BN-ReLU triple is the model zoo's universal building block (every
architecture, SURVEY.md §2.2); the reference runs it as three cuDNN/ATen
dispatches (e.g. models/resnet.py:132). Under XLA the three ops already fuse
into one conv custom-call, so this kernel is the *optional* hand-written
variant anticipated by SURVEY.md §2.3 — one VMEM-resident pass per image
tile: nine MXU contractions (one per kernel tap, the shifted-slice
formulation of im2col) accumulated in fp32, with the folded BN affine and
ReLU applied in the epilogue before the single write back to HBM.

Stride-1, padding-1 (the zoo's dominant conv shape). The BN is the
inference-mode affine: scale = gamma/sqrt(var+eps), bias = beta - mean*scale
— fold_batchnorm() computes it from the flax `batch_stats`.

Measured (TPU v5e, bf16, n=256, 30-step mean) vs the XLA-fused reference:
32x32x64: 4.59 vs 4.06 ms · 16x16x128: 3.96 vs 3.44 ms · 8x8x256: 3.87 vs
3.35 ms · 4x4x512: 3.48 vs 3.80 ms. XLA wins the large-spatial shapes (its
conv emitter is excellent); the hand kernel wins once feature maps are tiny
and its image-batched contraction keeps the MXU full. The default compute
path stays on XLA; this kernel is the measured, tested alternative.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, w_ref, scale_ref, bias_ref, out_ref, *, ib, h, w, cout):
    # x_ref: (ib, h+2, w+2, cin) padded input tile (ib images per program —
    # small feature maps are batched so each MXU contraction sees >= ~1k rows)
    # w_ref: (3, 3, cin, cout); scale/bias: (1, cout)
    acc = jnp.zeros((ib, h, w, cout), jnp.float32)
    for ky in range(3):
        for kx in range(3):
            patch = x_ref[:, ky : ky + h, kx : kx + w, :]
            acc = acc + jax.lax.dot_general(
                patch,
                w_ref[ky, kx],
                dimension_numbers=(((3,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
    y = acc * scale_ref[0] + bias_ref[0]
    out_ref[:] = jnp.maximum(y, 0.0).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def conv3x3_bn_relu(
    x: jax.Array,
    w: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    interpret: bool = False,
) -> jax.Array:
    """relu(conv3x3(x, w, stride=1, pad=1) * scale + bias), NHWC.

    x: (n, h, w, cin) float; w: (3, 3, cin, cout); scale/bias: (cout,).
    """
    n, h, wd, cin = x.shape
    cout = w.shape[-1]
    xp = jnp.pad(x, [(0, 0), (1, 1), (1, 1), (0, 0)])
    scale2 = scale.reshape(1, cout).astype(jnp.float32)
    bias2 = bias.reshape(1, cout).astype(jnp.float32)

    # images per program: batch small feature maps up to ~2k contraction rows
    ib = 1
    for cand in (16, 8, 4, 2):
        if h * wd * cand <= 2048 and n % cand == 0:
            ib = cand
            break

    kernel = functools.partial(_kernel, ib=ib, h=h, w=wd, cout=cout)
    return pl.pallas_call(
        kernel,
        grid=(n // ib,),
        in_specs=[
            pl.BlockSpec(
                (ib, h + 2, wd + 2, cin),
                lambda i: (i, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (ib, h, wd, cout), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n, h, wd, cout), x.dtype),
        interpret=interpret,
    )(xp, w, scale2, bias2)


def conv3x3_bn_relu_reference(
    x: jax.Array, w: jax.Array, scale: jax.Array, bias: jax.Array
) -> jax.Array:
    """lax reference: what XLA runs for the same fused triple."""
    y = jax.lax.conv_general_dilated(
        x.astype(jnp.float32),
        w.astype(jnp.float32),
        window_strides=(1, 1),
        padding=[(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return jnp.maximum(y, 0.0).astype(x.dtype)


def fold_batchnorm(
    gamma: jax.Array,
    beta: jax.Array,
    mean: jax.Array,
    var: jax.Array,
    eps: float = 1e-5,
) -> Tuple[jax.Array, jax.Array]:
    """Inference BN as a per-channel affine: y = x*scale + bias."""
    scale = gamma / jnp.sqrt(var + eps)
    return scale, beta - mean * scale
