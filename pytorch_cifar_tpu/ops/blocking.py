"""Shared VMEM-blocking helpers for the Pallas kernels in this package.

One home for the grid/block sizing rules so sibling kernels cannot drift
(ops/max_pool.py, ops/bn_stats.py).
"""

from __future__ import annotations

import jax.numpy as jnp


def channel_chunk(c: int) -> int:
    """Channel block: 128 matches the TPU lane width; small channel counts
    run whole."""
    return c if c <= 128 else 128


def batch_chunk(n: int, max_nb: int = 8) -> int:
    """Images per program: the largest divisor of ``n`` up to ``max_nb``.
    8 amortizes grid overhead without stressing VMEM at (8,32,32,128)
    blocks. Kernels with 4-D i1 masks must pass max_nb=1 (Mosaic rejects
    their relayouts — see ops/max_pool.py)."""
    for nb in (8, 4, 2, 1):
        if nb <= max_nb and n % nb == 0:
            return nb
    return 1


def pad_channels(a, cb: int):
    """Zero-pad the channel (last) axis up to a multiple of ``cb``.
    Returns (padded, original_channels)."""
    c = a.shape[-1]
    if c % cb == 0:
        return a, c
    cpad = -(-c // cb) * cb
    return (
        jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, cpad - c)]),
        c,
    )
