"""Pallas TPU kernel: depthwise KxK / stride-1 / SAME conv as a VMEM stencil.

The round-4 experiment closing the depthwise pool named in round 3's
closure (BENCHMARKS.md "PNASNet ... remaining headroom pools (a)"): the
zoo's depthwise-heavy families (PNASNet's 7x7/5x5 SepConvs — reference
models/pnasnet.py:10-22 — and MobileNet's 3x3s, models/mobilenet.py:15)
are VPU-bound, and XLA's native grouped-conv lowering measured 2.12 ms
fwd at (512,32,32,44) k=7 bf16 with an HBM-bytes roofline of ~0.6 ms.

Design: one program holds an (nb, H, W, cb) tile in VMEM, zero-pads it
VMEM-locally (no HBM pre-pad — the max_pool round-2 lesson), and
accumulates the K*K shifted multiply-adds in f32 registers. Channels ride
the 128-lane axis; W rides sublanes, so each dx!=0 tap is a
sublane-misaligned read — the SAME Mosaic constraint isolated for the
max-pool kernel (load+load+funnel-shift per vreg, BENCHMARKS.md round 3).
The measured outcome and the ceiling analysis live in BENCHMARKS.md round
4 (tools/depthwise_bench.py is the A/B harness).

Status: NOT wired into the zoo — kept as the experiment's artifact with
exactness pinned in tests/test_ops.py (interpret mode). See BENCHMARKS.md
round 4 for the measured verdict.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_cifar_tpu.ops.blocking import batch_chunk, channel_chunk, pad_channels


def _kernel(x_ref, w_ref, o_ref, *, h, w, k):
    p = k // 2
    x = x_ref[...].astype(jnp.float32)  # (nb, h, w, cb)
    xp = jnp.pad(x, [(0, 0), (p, p), (p, p), (0, 0)])  # VMEM-local halo
    wv = w_ref[...].astype(jnp.float32)  # (k, k, cb)
    acc = None
    for dy in range(k):
        for dx in range(k):
            t = xp[:, dy : dy + h, dx : dx + w, :] * wv[dy, dx, :]
            acc = t if acc is None else acc + t
    o_ref[...] = acc.astype(o_ref.dtype)


def _spec(shape):
    return pl.BlockSpec(
        shape, lambda i, j: (i, 0, 0, j), memory_space=pltpu.VMEM
    )


@functools.partial(
    jax.jit, static_argnames=("interpret", "max_nb")
)
def depthwise_stencil(x, w, interpret: bool = False, max_nb: int = 4):
    """Depthwise conv, NHWC x: (N,H,W,C), w: (K,K,C), stride 1, SAME.

    Forward only — this is a measurement artifact, not a wired op; the
    A/B against ``lax.conv_general_dilated(feature_group_count=C)`` runs
    in tools/depthwise_bench.py.
    """
    n, h, wd, c = x.shape
    k = w.shape[0]
    assert w.shape == (k, k, c), (w.shape, c)
    cb = channel_chunk(c)
    x, c0 = pad_channels(x, cb)
    w, _ = pad_channels(w, cb)
    cp = x.shape[-1]
    nb = batch_chunk(n, max_nb=max_nb)
    kernel = functools.partial(_kernel, h=h, w=wd, k=k)
    out = pl.pallas_call(
        kernel,
        grid=(n // nb, cp // cb),
        in_specs=[
            _spec((nb, h, wd, cb)),
            pl.BlockSpec(
                (k, k, cb), lambda i, j: (0, 0, j), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=_spec((nb, h, wd, cb)),
        out_shape=jax.ShapeDtypeStruct((n, h, wd, cp), x.dtype),
        interpret=interpret,
    )(x, w)
    return out[..., :c0]


def depthwise_xla(x, w):
    """The native lowering this kernel is racing: grouped conv with
    feature_group_count == C (what flax emits for our depthwise layers)."""
    c = x.shape[-1]
    k = w.shape[0]
    return jax.lax.conv_general_dilated(
        x,
        w.reshape(k, k, 1, c),
        window_strides=(1, 1),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
    )
