"""Pallas TPU kernels: overlapping 3x3/s1/p1 max-pool forward + backward.

Why: XLA lowers the backward of an overlapping max-pool to
``select-and-scatter``, the single most expensive op class in the zoo's
pool-heavy models — profiled at 16.2 ms of GoogLeNet's 102.8 ms step
(BENCHMARKS.md): every Inception cell carries a 3x3/s1 pool branch
(reference models/googlenet.py:44-46). Elementwise reformulations in plain
XLA measure *slower* (33-35 ms — shifted W-axis reads break (8,128) tile
alignment in HBM; BENCHMARKS.md negative results).

The kernel-level fix: the forward records, per window, WHICH of its nine
taps won (first maximum in row-major scan order — the same tie rule as
select-and-scatter and cuDNN's MaxPoolGrad). The backward then becomes nine
masked accumulations over VMEM-resident tiles — shifted reads of a tile
already in VMEM are register traffic, not misaligned HBM loads.

Status: NOT yet wired into the model zoo — ``models.common.max_pool``
still dispatches to ``nn.max_pool`` (XLA select-and-scatter backward,
12.0 ms at the GoogLeNet shape); it switches over only if the on-chip
A/B below lands faster. Correctness is pinned either way by
``tests/test_ops.py`` (interpret-mode exact fp32 gradient equality with
select-and-scatter).

Round-2 rewrite (vs the round-1 version measured at 38.1 ms against XLA's
12.0 ms at (512,32,32,480) bf16 fwd+bwd):
- NO HBM pre-padding: the round-1 version ``jnp.pad``-ed x (and in the
  backward both g and the index map) to (N,34,34,C) in HBM — three extra
  full-tensor copies through the bandwidth roof. Padding now happens on
  the VMEM tile inside the kernel.
- int8 winner map (was int32): 4x less index traffic in both directions.
- native-dtype compare chain (was fp32-widened): bf16 max/compare is
  exact for bf16 inputs; no conversion passes.
- batch-blocked grid (8 images per program instead of 1): fewer grid
  steps, deeper DMA pipelining.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = float("-inf")


def _fwd_kernel(x_ref, out_ref, idx_ref=None, *, h, w):
    # x_ref: (nb, h, w, c) unpadded input tile; out/idx: (nb, h, w, c).
    # idx_ref is None for the forward-only (inference) variant — the winner
    # map is only needed to route gradients.
    x = x_ref[...]
    xp = jnp.pad(
        x, [(0, 0), (1, 1), (1, 1), (0, 0)], constant_values=_NEG
    )  # VMEM-local halo, not an HBM copy
    best = xp[:, 0:h, 0:w, :]
    idx = jnp.zeros(best.shape, jnp.int8) if idx_ref is not None else None
    for k in range(1, 9):
        ky, kx = divmod(k, 3)
        cur = xp[:, ky : ky + h, kx : kx + w, :]
        m = cur > best  # strict: earlier (row-major) tap keeps ties
        if idx_ref is not None:
            idx = jnp.where(m, jnp.int8(k), idx)
        best = jnp.where(m, cur, best)
    out_ref[...] = best.astype(out_ref.dtype)
    if idx_ref is not None:
        idx_ref[...] = idx


def _bwd_kernel(g_ref, i_ref, gi_ref, *, h, w):
    # g/i: (nb, h, w, c) unpadded window-grad and winner-index tiles.
    # Input position p receives window (p - k + 1)'s gradient iff that
    # window's winner index equals k: gi[p] = sum_k [i'[k] == k] * g'[k]
    # with the shifted slice [2-ky : 2-ky+h, 2-kx : 2-kx+w] of the
    # VMEM-padded tiles (pad value 9 can never match a real tap index).
    gp = jnp.pad(g_ref[...], [(0, 0), (1, 1), (1, 1), (0, 0)])
    ip = jnp.pad(
        i_ref[...], [(0, 0), (1, 1), (1, 1), (0, 0)],
        constant_values=jnp.int8(9),
    )
    nb = gp.shape[0]
    acc = jnp.zeros((nb, h, w, gi_ref.shape[-1]), jnp.float32)
    for k in range(9):
        ky, kx = divmod(k, 3)
        sl_h = slice(2 - ky, 2 - ky + h)
        sl_w = slice(2 - kx, 2 - kx + w)
        hit = ip[:, sl_h, sl_w, :] == k
        acc = acc + jnp.where(hit, gp[:, sl_h, sl_w, :], 0).astype(
            jnp.float32
        )
    gi_ref[...] = acc.astype(gi_ref.dtype)


def _spec(shape):
    return pl.BlockSpec(
        shape, lambda i, j: (i, 0, 0, j), memory_space=pltpu.VMEM
    )


def _chunk(c: int) -> int:
    """Channel block: 128 matches the lane width; small channel counts run
    whole."""
    return c if c <= 128 else 128


def _batch_chunk(n: int) -> int:
    """Images per program: 8 amortizes grid/DMA overhead; VMEM per block at
    (8,32,32,128) is in+out+idx ~= 5 MB of the 16 MB budget."""
    for nb in (8, 4, 2, 1):
        if n % nb == 0:
            return nb
    return 1


def _pad_channels(a, cb):
    c = a.shape[-1]
    if c % cb == 0:
        return a, c
    cpad = -(-c // cb) * cb
    return jnp.pad(a, [(0, 0)] * 3 + [(0, cpad - c)]), c


@functools.partial(jax.jit, static_argnames=("interpret", "emit_idx"))
def _max_pool3x3_fwd(x, interpret=False, emit_idx=True):
    n, h, w, _ = x.shape
    cb = _chunk(x.shape[-1])
    x, c = _pad_channels(x, cb)
    cp = x.shape[-1]
    nb = _batch_chunk(n)
    kernel = functools.partial(_fwd_kernel, h=h, w=w)
    grid = (n // nb, cp // cb)
    out_spec = _spec((nb, h, w, cb))
    out_shape = jax.ShapeDtypeStruct((n, h, w, cp), x.dtype)
    if emit_idx:
        out, idx = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[_spec((nb, h, w, cb))],
            out_specs=(out_spec, _spec((nb, h, w, cb))),
            out_shape=(
                out_shape,
                jax.ShapeDtypeStruct((n, h, w, cp), jnp.int8),
            ),
            interpret=interpret,
        )(x)
        return out[..., :c], idx[..., :c]
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[_spec((nb, h, w, cb))],
        out_specs=out_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(x)
    return out[..., :c], None


@functools.partial(jax.jit, static_argnames=("interpret",))
def _max_pool3x3_bwd(g, idx, interpret=False):
    n, h, w, _ = g.shape
    cb = _chunk(g.shape[-1])
    g, c = _pad_channels(g, cb)
    idx, _ = _pad_channels(idx, cb)
    cp = g.shape[-1]
    nb = _batch_chunk(n)
    kernel = functools.partial(_bwd_kernel, h=h, w=w)
    out = pl.pallas_call(
        kernel,
        grid=(n // nb, cp // cb),
        in_specs=[_spec((nb, h, w, cb)), _spec((nb, h, w, cb))],
        out_specs=_spec((nb, h, w, cb)),
        out_shape=jax.ShapeDtypeStruct((n, h, w, cp), g.dtype),
        interpret=interpret,
    )(g, idx)
    return out[..., :c]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def max_pool3x3_s1(x, interpret: bool = False):
    """3x3/stride-1/pad-1 max pool, NHWC, Pallas fwd+bwd."""
    # primal-only call (no differentiation): skip the winner-index output
    out, _ = _max_pool3x3_fwd(x, interpret=interpret, emit_idx=False)
    return out


def _vjp_fwd(x, interpret):
    out, idx = _max_pool3x3_fwd(x, interpret=interpret)
    return out, idx


def _vjp_bwd(interpret, idx, g):
    return (_max_pool3x3_bwd(g, idx, interpret=interpret),)


max_pool3x3_s1.defvjp(_vjp_fwd, _vjp_bwd)
