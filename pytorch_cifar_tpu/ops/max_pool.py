"""Pallas TPU kernels: overlapping 3x3/s1/p1 max-pool forward + backward.

Why: XLA lowers the backward of an overlapping max-pool to
``select-and-scatter``, the single most expensive op class in the zoo's
pool-heavy models — profiled at 16.2 ms of GoogLeNet's 102.8 ms step
(BENCHMARKS.md): every Inception cell carries a 3x3/s1 pool branch
(reference models/googlenet.py:44-46). Elementwise reformulations in plain
XLA measure *slower* (33-35 ms — shifted W-axis reads break (8,128) tile
alignment in HBM; BENCHMARKS.md negative results).

The kernel-level fix: the forward records, per window, WHICH of its nine
taps won (first maximum in row-major scan order — the same tie rule as
select-and-scatter and cuDNN's MaxPoolGrad). The backward then becomes nine
masked accumulations over VMEM-resident tiles — shifted reads of a tile
already in VMEM are register traffic, not misaligned HBM loads.

Status: NOT wired into the model zoo — ``models.common.max_pool`` stays
on ``nn.max_pool``. Round-2 A/B on the v5e (``tools/pool_bench.py``,
chained-call + D2H-sync protocol, (512,32,32,480) bf16 fwd+bwd):
**Pallas 22.2 ms vs XLA select-and-scatter 11.0 ms** — the rewrite
recovered 16 ms over round 1's 38.1 ms (HBM pre-pads + int32 map
eliminated) but the body is VPU-bound: every shifted W-slice of the
VMEM-padded (34,34) tile is a sublane-misaligned read, and Mosaic
rejects both bf16 compares ("Target does not support this comparison")
and mixed-dtype masks, forcing f32 widening. Channel-block sweep
128/256/512 is within noise, confirming compute-bound. Correctness is
pinned by ``tests/test_ops.py`` (interpret-mode exact fp32 gradient
equality with select-and-scatter) so future Mosaic work starts from a
correct 22 ms baseline, 2x from parity.

Round-2 rewrite (vs the round-1 version measured at 38.1 ms against XLA's
12.0 ms at (512,32,32,480) bf16 fwd+bwd):
- NO HBM pre-padding: the round-1 version ``jnp.pad``-ed x (and in the
  backward both g and the index map) to (N,34,34,C) in HBM — three extra
  full-tensor copies through the bandwidth roof. Padding now happens on
  the VMEM tile inside the kernel.
- input-dtype winner map (was int32): 2x less index traffic in bf16, and
  — the real constraint — a SINGLE dtype family inside the kernel. Mixed
  families (bf16 compares feeding int8 selects) die in Mosaic with
  "Invalid relayout ... xi1: (16,128) -> (32,128)"; int8 would need its
  own (32,128) mask layout.
- f32 compute stays (Mosaic rejects bf16 compares on this target), but
  only in registers — HBM loads/stores remain in the input dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_cifar_tpu.ops.blocking import batch_chunk, channel_chunk, pad_channels

_NEG = float("-inf")


def _fwd_kernel(x_ref, out_ref, idx_ref=None, *, h, w):
    # x_ref: (nb, h, w, c) unpadded input tile; out/idx: (nb, h, w, c).
    # idx_ref is None for the forward-only (inference) variant — the winner
    # map is only needed to route gradients.
    #
    # The winner map is kept in the INPUT dtype (0..8 are exact in bf16):
    # mixing dtype families inside the kernel (bf16 compares driving int8
    # selects) produces i1 masks in incompatible Mosaic layouts —
    # "Invalid relayout ... xi1: (16,128) -> (32,128)" — while a single
    # dtype family keeps every mask/select in one layout.
    # f32 in-register compute: Mosaic rejects bf16 compares on this target
    # ("Target does not support this comparison"); the conversions are VPU
    # register traffic, while loads/stores stay in the input dtype so the
    # HBM side keeps the bandwidth win.
    x = x_ref[...].astype(jnp.float32)
    xp = jnp.pad(
        x, [(0, 0), (1, 1), (1, 1), (0, 0)], constant_values=_NEG
    )  # VMEM-local halo, not an HBM copy
    best = xp[:, 0:h, 0:w, :]
    idx = (
        jnp.zeros(best.shape, jnp.float32) if idx_ref is not None else None
    )
    for k in range(1, 9):
        ky, kx = divmod(k, 3)
        cur = xp[:, ky : ky + h, kx : kx + w, :]
        m = cur > best  # strict: earlier (row-major) tap keeps ties
        if idx_ref is not None:
            idx = jnp.where(m, jnp.float32(k), idx)
        best = jnp.where(m, cur, best)
    out_ref[...] = best.astype(out_ref.dtype)
    if idx_ref is not None:
        idx_ref[...] = idx.astype(idx_ref.dtype)


def _bwd_kernel(g_ref, i_ref, gi_ref, *, h, w):
    # g/i: (nb, h, w, c) unpadded window-grad and winner-index tiles.
    # Input position p receives window (p - k + 1)'s gradient iff that
    # window's winner index equals k: gi[p] = sum_k [i'[k] == k] * g'[k]
    # with the shifted slice [2-ky : 2-ky+h, 2-kx : 2-kx+w] of the
    # VMEM-padded tiles (pad value 9 can never match a real tap index).
    g = g_ref[...].astype(jnp.float32)
    gp = jnp.pad(g, [(0, 0), (1, 1), (1, 1), (0, 0)])
    ip = jnp.pad(
        i_ref[...].astype(jnp.float32),
        [(0, 0), (1, 1), (1, 1), (0, 0)],
        constant_values=9.0,
    )
    acc = None
    for k in range(9):
        ky, kx = divmod(k, 3)
        sl_h = slice(2 - ky, 2 - ky + h)
        sl_w = slice(2 - kx, 2 - kx + w)
        hit = ip[:, sl_h, sl_w, :] == jnp.float32(k)
        term = jnp.where(hit, gp[:, sl_h, sl_w, :], jnp.float32(0))
        acc = term if acc is None else acc + term
    gi_ref[...] = acc.astype(gi_ref.dtype)


def _spec(shape):
    return pl.BlockSpec(
        shape, lambda i, j: (i, 0, 0, j), memory_space=pltpu.VMEM
    )


def _chunk(c: int) -> int:
    """Channel block (shared rule, ops/blocking.py). Swept 128/256/512 on
    the v5e: within noise (21.8-22.3 ms at the GoogLeNet shape) — the
    kernel is VPU-bound, not grid-bound."""
    return channel_chunk(c)


def _batch_chunk(n: int) -> int:
    """Images per program: pinned to 1 — batch-blocks > 1 trip a Mosaic i1
    relayout on 4-D masks ("Invalid relayout ... vector<8x32x32x128xi1>");
    the grid still pipelines DMAs across programs."""
    return batch_chunk(n, max_nb=1)


_pad_channels = pad_channels


@functools.partial(jax.jit, static_argnames=("interpret", "emit_idx"))
def _max_pool3x3_fwd(x, interpret=False, emit_idx=True):
    n, h, w, _ = x.shape
    cb = _chunk(x.shape[-1])
    x, c = _pad_channels(x, cb)
    cp = x.shape[-1]
    nb = _batch_chunk(n)
    kernel = functools.partial(_fwd_kernel, h=h, w=w)
    grid = (n // nb, cp // cb)
    out_spec = _spec((nb, h, w, cb))
    out_shape = jax.ShapeDtypeStruct((n, h, w, cp), x.dtype)
    if emit_idx:
        out, idx = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[_spec((nb, h, w, cb))],
            out_specs=(out_spec, _spec((nb, h, w, cb))),
            out_shape=(
                out_shape,
                jax.ShapeDtypeStruct((n, h, w, cp), x.dtype),
            ),
            interpret=interpret,
        )(x)
        return out[..., :c], idx[..., :c]
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[_spec((nb, h, w, cb))],
        out_specs=out_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(x)
    return out[..., :c], None


@functools.partial(jax.jit, static_argnames=("interpret",))
def _max_pool3x3_bwd(g, idx, interpret=False):
    n, h, w, _ = g.shape
    cb = _chunk(g.shape[-1])
    g, c = _pad_channels(g, cb)
    idx, _ = _pad_channels(idx, cb)
    cp = g.shape[-1]
    nb = _batch_chunk(n)
    kernel = functools.partial(_bwd_kernel, h=h, w=w)
    out = pl.pallas_call(
        kernel,
        grid=(n // nb, cp // cb),
        in_specs=[_spec((nb, h, w, cb)), _spec((nb, h, w, cb))],
        out_specs=_spec((nb, h, w, cb)),
        out_shape=jax.ShapeDtypeStruct((n, h, w, cp), g.dtype),
        interpret=interpret,
    )(g, idx)
    return out[..., :c]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def max_pool3x3_s1(x, interpret: bool = False):
    """3x3/stride-1/pad-1 max pool, NHWC, Pallas fwd+bwd."""
    # primal-only call (no differentiation): skip the winner-index output
    out, _ = _max_pool3x3_fwd(x, interpret=interpret, emit_idx=False)
    return out


def _vjp_fwd(x, interpret):
    out, idx = _max_pool3x3_fwd(x, interpret=interpret)
    return out, idx


def _vjp_bwd(interpret, idx, g):
    return (_max_pool3x3_bwd(g, idx, interpret=interpret),)


max_pool3x3_s1.defvjp(_vjp_fwd, _vjp_bwd)
