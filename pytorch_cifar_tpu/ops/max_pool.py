"""Pallas TPU kernels: overlapping 3x3/s1/p1 max-pool forward + backward.

Why: XLA lowers the backward of an overlapping max-pool to
``select-and-scatter``, the single most expensive op class in the zoo's
pool-heavy models — profiled at 16.2 ms of GoogLeNet's 102.8 ms step
(BENCHMARKS.md): every Inception cell carries a 3x3/s1 pool branch
(reference models/googlenet.py:44-46). Elementwise reformulations in plain
XLA measure *slower* (33-35 ms — shifted W-axis reads break (8,128) tile
alignment in HBM; BENCHMARKS.md negative results).

The kernel-level approach: a separable forward — max_h(max_w(x)), exact
including ties, see _fwd_kernel — records per position which of each
1-D pass's three taps won (first maximum, the select-and-scatter /
cuDNN MaxPoolGrad tie rule). The backward is then two 3-tap masked
routing passes over VMEM-resident tiles — shifted reads of a tile
already in VMEM are register traffic, not misaligned HBM loads.

Status: NOT wired into the model zoo — ``models.common.max_pool`` stays
on ``nn.max_pool``. Round-3 closure (BENCHMARKS.md round 3 for the full
evidence chain): the kernel was rewritten around an EXACT separable
decomposition — max3x3 = max_h(max_w(x)), and the row-major-first-max
tie rule survives the composition (first winning row, then first
winning column, IS the row-major argmax) — cutting the 9-tap window to
two 3-tap passes. Measured (512,32,32,480) bf16 fwd+bwd: 22.2 -> 21.1 ms
vs XLA select-and-scatter ~11 ms (pool_bench protocol; 8.3 ms
chained-slope). The tap reduction barely moved it, and fp32 (native
compares, no widening) measures WORSE (36.2 ms), which together isolate
the binding constraint: every W-shifted read of a VMEM tile is a
sublane-misaligned vector access that Mosaic lowers as
load+load+funnel-shift per vreg — the cost is per shifted ACCESS, not
per tap mask, and no addressing mode folds the shift into the load.
XLA's fused select-and-scatter keeps a ~2x advantage from specialized
window primitives. Secondary Mosaic walls, still standing from round 2:
bf16 compares rejected ("Target does not support this comparison"),
4-D i1 masks with batch-block > 1 fail relayout. An XLA-level separable
rewrite (two 1-D ``nn.max_pool``s; same exactness proof) was also
measured: 8.13 vs 8.28 ms — a 2% non-win, select-and-scatter cost does
not scale with window size. Model-level context: GoogLeNet's pools are
17.75 ms of its 104.7 ms step (avg-pool-swap ablation), so even free
pools leave it at 5.9k img/s — under the 6k round-1 target; its
remaining wall is low-channel conv MXU utilization, not pools.
Correctness of this kernel is pinned by ``tests/test_ops.py``
(interpret-mode bit-exact routing vs select-and-scatter with
integer cotangents, plus an all-ties tie-rule test).

Round-2 rewrite (vs the round-1 version measured at 38.1 ms against XLA's
12.0 ms at (512,32,32,480) bf16 fwd+bwd):
- NO HBM pre-padding: the round-1 version ``jnp.pad``-ed x (and in the
  backward both g and the index map) to (N,34,34,C) in HBM — three extra
  full-tensor copies through the bandwidth roof. Padding now happens on
  the VMEM tile inside the kernel.
- input-dtype winner map (was int32): 2x less index traffic in bf16, and
  — the real constraint — a SINGLE dtype family inside the kernel. Mixed
  families (bf16 compares feeding int8 selects) die in Mosaic with
  "Invalid relayout ... xi1: (16,128) -> (32,128)"; int8 would need its
  own (32,128) mask layout.
- f32 compute stays (Mosaic rejects bf16 compares on this target), but
  only in registers — HBM loads/stores remain in the input dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_cifar_tpu.ops.blocking import batch_chunk, channel_chunk, pad_channels

_NEG = float("-inf")


def _w_taps_roll(x, w):
    """The three W-axis taps (left-neighbor, center, right-neighbor) via
    hardware sublane rotates instead of misaligned shifted slices — the
    round-3 binding constraint was load+load+funnel-shift per shifted
    vreg access; ``pltpu.roll`` lowers to a single rotate. Wrapped edge
    columns are replaced with -inf by a broadcasted-iota select (pure
    register work)."""
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 2)
    neg = jnp.full(x.shape, _NEG, x.dtype)
    left = jnp.where(col == 0, neg, pltpu.roll(x, 1, 2))  # tap k=0: x[j-1]
    # rotation is modular; pltpu.roll rejects negative shifts, so -1 == w-1
    right = jnp.where(col == w - 1, neg, pltpu.roll(x, w - 1, 2))  # k=2
    return left, x, right


def _fwd_kernel_roll(x_ref, out_ref, ih_ref=None, iw_ref=None, *, h, w):
    # Same separable decomposition and tie rule as _fwd_kernel; the W-pass
    # reads its shifted taps via sublane rotates (_w_taps_roll). The
    # h-pass keeps plain slices — h is outside the (sublane, lane) vreg
    # tile, so its shifted reads are aligned address arithmetic.
    x = x_ref[...].astype(jnp.float32)
    t0, t1, t2 = _w_taps_roll(x, w)
    mh = t0
    iw = jnp.zeros(mh.shape, jnp.float32) if iw_ref is not None else None
    for k, cur in ((1, t1), (2, t2)):
        m = cur > mh  # strict: earlier tap keeps ties
        if iw is not None:
            iw = jnp.where(m, jnp.float32(k), iw)
        mh = jnp.where(m, cur, mh)
    mhp = jnp.pad(
        mh, [(0, 0), (1, 1), (0, 0), (0, 0)], constant_values=_NEG
    )
    best = mhp[:, 0:h, :, :]
    ih = jnp.zeros(best.shape, jnp.float32) if ih_ref is not None else None
    for k in range(1, 3):
        cur = mhp[:, k : k + h, :, :]
        m = cur > best
        if ih is not None:
            ih = jnp.where(m, jnp.float32(k), ih)
        best = jnp.where(m, cur, best)
    out_ref[...] = best.astype(out_ref.dtype)
    if ih_ref is not None:
        ih_ref[...] = ih.astype(ih_ref.dtype)
        iw_ref[...] = iw.astype(iw_ref.dtype)


def _bwd_kernel_roll(g_ref, ih_ref, iw_ref, gi_ref, *, h, w):
    # Mirror of _bwd_kernel with the W-pass shifted reads as rotates.
    # h-pass: plain slices (aligned). w-pass: input column j receives the
    # intermediate gradient of window j+1-k iff that window's w-winner is
    # k; the shifted reads of (gmh, iw) become rotates with edge columns
    # neutralized (iw edge -> 3.0 never matches; gmh edge -> 0).
    g = g_ref[...].astype(jnp.float32)
    gp = jnp.pad(g, [(0, 0), (1, 1), (0, 0), (0, 0)])
    ihp = jnp.pad(
        ih_ref[...].astype(jnp.float32),
        [(0, 0), (1, 1), (0, 0), (0, 0)],
        constant_values=3.0,
    )
    gmh = None
    for k in range(3):
        sl_h = slice(2 - k, 2 - k + h)
        hit = ihp[:, sl_h, :, :] == jnp.float32(k)
        term = jnp.where(hit, gp[:, sl_h, :, :], jnp.float32(0))
        gmh = term if gmh is None else gmh + term
    iw = iw_ref[...].astype(jnp.float32)
    col = jax.lax.broadcasted_iota(jnp.int32, gmh.shape, 2)
    acc = None
    for k in range(3):
        # slice(2-k, 2-k+w) of the pad-1 array reads original j+1-k,
        # i.e. roll(x, k-1)[j] == x[j-(k-1)] == x[j+1-k]
        shift = k - 1
        if shift == 0:
            gm_s, iw_s = gmh, iw
        else:
            edge = w - 1 if shift < 0 else 0
            sh = shift % w  # pltpu.roll rejects negative shifts
            gm_s = jnp.where(
                col == edge, jnp.float32(0), pltpu.roll(gmh, sh, 2)
            )
            iw_s = jnp.where(
                col == edge, jnp.float32(3), pltpu.roll(iw, sh, 2)
            )
        hit = iw_s == jnp.float32(k)
        term = jnp.where(hit, gm_s, jnp.float32(0))
        acc = term if acc is None else acc + term
    gi_ref[...] = acc.astype(gi_ref.dtype)


def _fwd_kernel(x_ref, out_ref, ih_ref=None, iw_ref=None, *, h, w):
    # x_ref: (nb, h, w, c) unpadded input tile; out/ih/iw: (nb, h, w, c).
    # ih/iw are None for the forward-only (inference) variant — the winner
    # maps are only needed to route gradients.
    #
    # SEPARABLE decomposition (round 3): a 3x3/s1 max pool is
    # max_h(max_w(x)), and the select-and-scatter tie rule (row-major
    # FIRST maximum — cuDNN MaxPoolGrad's rule) survives it exactly: the
    # first row containing the window max, then the first column within
    # that row, IS the row-major argmax. Two 3-tap passes replace the
    # 9-tap window: 2/3 fewer masked ops, and only the w-pass touches
    # sublane-misaligned shifted reads (the round-2 kernel's measured VPU
    # bound — all six off-column taps were misaligned).
    #
    # The winner maps stay in the INPUT dtype (0..2 exact in bf16):
    # mixing dtype families inside the kernel (bf16 compares driving int8
    # selects) produces i1 masks in incompatible Mosaic layouts —
    # "Invalid relayout ... xi1: (16,128) -> (32,128)".
    # f32 in-register compute: Mosaic rejects bf16 compares on this target
    # ("Target does not support this comparison"); the conversions are VPU
    # register traffic, while loads/stores stay in the input dtype so the
    # HBM side keeps the bandwidth win.
    x = x_ref[...].astype(jnp.float32)
    xpw = jnp.pad(
        x, [(0, 0), (0, 0), (1, 1), (0, 0)], constant_values=_NEG
    )  # VMEM-local halo, not an HBM copy
    # w-pass: mh[i,j] = max over x[i, j-1..j+1], iw = first winning tap
    mh = xpw[:, :, 0:w, :]
    iw = jnp.zeros(mh.shape, jnp.float32) if iw_ref is not None else None
    for k in range(1, 3):
        cur = xpw[:, :, k : k + w, :]
        m = cur > mh  # strict: earlier tap keeps ties
        if iw is not None:
            iw = jnp.where(m, jnp.float32(k), iw)
        mh = jnp.where(m, cur, mh)
    # h-pass over the intermediate: out[i,j] = max over mh[i-1..i+1, j]
    mhp = jnp.pad(
        mh, [(0, 0), (1, 1), (0, 0), (0, 0)], constant_values=_NEG
    )
    best = mhp[:, 0:h, :, :]
    ih = jnp.zeros(best.shape, jnp.float32) if ih_ref is not None else None
    for k in range(1, 3):
        cur = mhp[:, k : k + h, :, :]
        m = cur > best
        if ih is not None:
            ih = jnp.where(m, jnp.float32(k), ih)
        best = jnp.where(m, cur, best)
    out_ref[...] = best.astype(out_ref.dtype)
    if ih_ref is not None:
        ih_ref[...] = ih.astype(ih_ref.dtype)
        iw_ref[...] = iw.astype(iw_ref.dtype)


def _bwd_kernel(g_ref, ih_ref, iw_ref, gi_ref, *, h, w):
    # Two 3-tap routing passes, mirroring the separable forward.
    # h-pass: intermediate position (i',j) receives window (i'-k+1, j)'s
    # gradient iff that window's h-winner equals k (pad value 3 can never
    # match a real tap). Then the w-pass routes the intermediate to the
    # input column the w-winner picked. Only the w-pass reads shifted
    # (sublane-misaligned) slices.
    g = g_ref[...].astype(jnp.float32)
    gp = jnp.pad(g, [(0, 0), (1, 1), (0, 0), (0, 0)])
    ihp = jnp.pad(
        ih_ref[...].astype(jnp.float32),
        [(0, 0), (1, 1), (0, 0), (0, 0)],
        constant_values=3.0,
    )
    gmh = None
    for k in range(3):
        sl_h = slice(2 - k, 2 - k + h)
        hit = ihp[:, sl_h, :, :] == jnp.float32(k)
        term = jnp.where(hit, gp[:, sl_h, :, :], jnp.float32(0))
        gmh = term if gmh is None else gmh + term
    gmhp = jnp.pad(gmh, [(0, 0), (0, 0), (1, 1), (0, 0)])
    iwp = jnp.pad(
        iw_ref[...].astype(jnp.float32),
        [(0, 0), (0, 0), (1, 1), (0, 0)],
        constant_values=3.0,
    )
    acc = None
    for k in range(3):
        sl_w = slice(2 - k, 2 - k + w)
        hit = iwp[:, :, sl_w, :] == jnp.float32(k)
        term = jnp.where(hit, gmhp[:, :, sl_w, :], jnp.float32(0))
        acc = term if acc is None else acc + term
    gi_ref[...] = acc.astype(gi_ref.dtype)


def _spec(shape):
    return pl.BlockSpec(
        shape, lambda i, j: (i, 0, 0, j), memory_space=pltpu.VMEM
    )


def _chunk(c: int) -> int:
    """Channel block (shared rule, ops/blocking.py). Swept 128/256/512 on
    the v5e: within noise (21.8-22.3 ms at the GoogLeNet shape) — the
    kernel is VPU-bound, not grid-bound."""
    return channel_chunk(c)


def _batch_chunk(n: int) -> int:
    """Images per program: pinned to 1 — batch-blocks > 1 trip a Mosaic i1
    relayout on 4-D masks ("Invalid relayout ... vector<8x32x32x128xi1>");
    the grid still pipelines DMAs across programs."""
    return batch_chunk(n, max_nb=1)


_pad_channels = pad_channels


@functools.partial(
    jax.jit, static_argnames=("interpret", "emit_idx", "use_roll")
)
def _max_pool3x3_fwd(x, interpret=False, emit_idx=True, use_roll=False):
    n, h, w, _ = x.shape
    cb = _chunk(x.shape[-1])
    x, c = _pad_channels(x, cb)
    cp = x.shape[-1]
    nb = _batch_chunk(n)
    kernel = functools.partial(
        _fwd_kernel_roll if use_roll else _fwd_kernel, h=h, w=w
    )
    grid = (n // nb, cp // cb)
    out_spec = _spec((nb, h, w, cb))
    out_shape = jax.ShapeDtypeStruct((n, h, w, cp), x.dtype)
    if emit_idx:
        out, ih, iw = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[_spec((nb, h, w, cb))],
            out_specs=(out_spec, _spec((nb, h, w, cb)), _spec((nb, h, w, cb))),
            out_shape=(
                out_shape,
                jax.ShapeDtypeStruct((n, h, w, cp), x.dtype),
                jax.ShapeDtypeStruct((n, h, w, cp), x.dtype),
            ),
            interpret=interpret,
        )(x)
        return out[..., :c], (ih[..., :c], iw[..., :c])
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[_spec((nb, h, w, cb))],
        out_specs=out_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(x)
    return out[..., :c], None


@functools.partial(jax.jit, static_argnames=("interpret", "use_roll"))
def _max_pool3x3_bwd(g, ih, iw, interpret=False, use_roll=False):
    n, h, w, _ = g.shape
    cb = _chunk(g.shape[-1])
    g, c = _pad_channels(g, cb)
    ih, _ = _pad_channels(ih, cb)
    iw, _ = _pad_channels(iw, cb)
    cp = g.shape[-1]
    nb = _batch_chunk(n)
    kernel = functools.partial(
        _bwd_kernel_roll if use_roll else _bwd_kernel, h=h, w=w
    )
    out = pl.pallas_call(
        kernel,
        grid=(n // nb, cp // cb),
        in_specs=[
            _spec((nb, h, w, cb)),
            _spec((nb, h, w, cb)),
            _spec((nb, h, w, cb)),
        ],
        out_specs=_spec((nb, h, w, cb)),
        out_shape=jax.ShapeDtypeStruct((n, h, w, cp), g.dtype),
        interpret=interpret,
    )(g, ih, iw)
    return out[..., :c]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def max_pool3x3_s1(x, interpret: bool = False, use_roll: bool = False):
    """3x3/stride-1/pad-1 max pool, NHWC, Pallas fwd+bwd.

    ``use_roll``: W-axis shifted taps via hardware sublane rotates
    (pltpu.roll) instead of misaligned shifted slices — see
    _w_taps_roll. Measured on the v5e (tools/pool_bench.py benches all
    three arms): 20.33 ms vs the slice kernel's 20.43 — a measured
    non-win, so the default stays False and nn.max_pool stays shipped
    (BENCHMARKS.md round 5).
    """
    # primal-only call (no differentiation): skip the winner-index output
    out, _ = _max_pool3x3_fwd(
        x, interpret=interpret, emit_idx=False, use_roll=use_roll
    )
    return out


def _vjp_fwd(x, interpret, use_roll):
    out, idx = _max_pool3x3_fwd(x, interpret=interpret, use_roll=use_roll)
    return out, idx


def _vjp_bwd(interpret, use_roll, idx, g):
    ih, iw = idx
    return (
        _max_pool3x3_bwd(g, ih, iw, interpret=interpret, use_roll=use_roll),
    )


max_pool3x3_s1.defvjp(_vjp_fwd, _vjp_bwd)
