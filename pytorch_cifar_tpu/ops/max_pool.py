"""Pallas TPU kernels: overlapping 3x3/s1/p1 max-pool forward + backward.

Why: XLA lowers the backward of an overlapping max-pool to
``select-and-scatter``, the single most expensive op class in the zoo's
pool-heavy models — profiled at 16.2 ms of GoogLeNet's 102.8 ms step
(BENCHMARKS.md): every Inception cell carries a 3x3/s1 pool branch
(reference models/googlenet.py:44-46). Elementwise reformulations in plain
XLA measure *slower* (33-35 ms — shifted W-axis reads break (8,128) tile
alignment in HBM; BENCHMARKS.md negative results).

The kernel-level fix: the forward records, per window, WHICH of its nine
taps won (first maximum in row-major scan order — the same tie rule as
select-and-scatter and cuDNN's MaxPoolGrad). The backward then becomes nine
masked accumulations over VMEM-resident tiles — shifted reads of a tile
already in VMEM are register traffic, not misaligned HBM loads. Memory
traffic: read g + idx, write grad (3 passes) instead of the
select-and-scatter's windowed rescan.

Status: NOT wired into the model zoo. Measured 38.1 ms vs XLA's 12.0 ms at
(512,32,32,480) bf16 fwd+bwd (BENCHMARKS.md) — the fp32 widening in the
9-tap scan and the int32 index map's extra HBM traffic outweigh the
scheduling win, so ``models.common.max_pool`` stays on ``nn.max_pool``.
Kept fully tested (``tests/test_ops.py``, interpret mode incl. exact fp32
gradient equality with select-and-scatter) as the baseline for future
Mosaic tuning; the roofline allows ~0.6 ms.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = float("-inf")


def _fwd_kernel(xp_ref, out_ref, idx_ref=None, *, h, w):
    # xp_ref: (1, h+2, w+2, c) padded input; out/idx: (1, h, w, c).
    # idx_ref is None for the forward-only (inference) variant — the winner
    # map is only needed to route gradients.
    best = xp_ref[0, 0:h, 0:w, :].astype(jnp.float32)
    idx = jnp.zeros(best.shape, jnp.int32) if idx_ref is not None else None
    for k in range(1, 9):
        ky, kx = divmod(k, 3)
        cur = xp_ref[0, ky : ky + h, kx : kx + w, :].astype(jnp.float32)
        m = cur > best  # strict: earlier (row-major) tap keeps ties
        if idx_ref is not None:
            idx = jnp.where(m, k, idx)
        best = jnp.where(m, cur, best)
    out_ref[0] = best.astype(out_ref.dtype)
    if idx_ref is not None:
        idx_ref[0] = idx


def _bwd_kernel(gp_ref, ip_ref, gi_ref, *, h, w):
    # gp/ip: (1, h+2, w+2, c) zero/9-padded grad and winner-index maps.
    # Input position p receives window (p - k + 1)'s gradient iff that
    # window's winner index equals k: gi[p] = sum_k [ip'[k] == k] * gp'[k]
    # with the shifted slice [2-ky : 2-ky+h, 2-kx : 2-kx+w].
    acc = jnp.zeros((h, w, gi_ref.shape[-1]), jnp.float32)
    for k in range(9):
        ky, kx = divmod(k, 3)
        sl_h = slice(2 - ky, 2 - ky + h)
        sl_w = slice(2 - kx, 2 - kx + w)
        hit = ip_ref[0, sl_h, sl_w, :] == k
        acc = acc + jnp.where(hit, gp_ref[0, sl_h, sl_w, :], 0.0).astype(
            jnp.float32
        )
    gi_ref[0] = acc.astype(gi_ref.dtype)


def _spec(shape):
    return pl.BlockSpec(
        shape, lambda i, j: (i, 0, 0, j), memory_space=pltpu.VMEM
    )


def _chunk(c: int) -> int:
    """Channel block: full-image blocks VMEM-OOM past ~256 channels
    (measured: 480ch fwd wants 17.5 MB scoped vs the 16 MB limit), so the
    grid tiles channels; 128 matches the lane width."""
    return c if c <= 128 else 128


def _pad_channels(a, cb):
    c = a.shape[-1]
    if c % cb == 0:
        return a, c
    cpad = -(-c // cb) * cb
    return jnp.pad(a, [(0, 0)] * 3 + [(0, cpad - c)]), c


@functools.partial(jax.jit, static_argnames=("interpret", "emit_idx"))
def _max_pool3x3_fwd(x, interpret=False, emit_idx=True):
    n, h, w, _ = x.shape
    cb = _chunk(x.shape[-1])
    x, c = _pad_channels(x, cb)
    cp = x.shape[-1]
    xp = jnp.pad(
        x, [(0, 0), (1, 1), (1, 1), (0, 0)], constant_values=_NEG
    )
    kernel = functools.partial(_fwd_kernel, h=h, w=w)
    out_spec = _spec((1, h, w, cb))
    out_shape = jax.ShapeDtypeStruct((n, h, w, cp), x.dtype)
    if emit_idx:
        out, idx = pl.pallas_call(
            kernel,
            grid=(n, cp // cb),
            in_specs=[_spec((1, h + 2, w + 2, cb))],
            out_specs=(out_spec, _spec((1, h, w, cb))),
            out_shape=(
                out_shape,
                jax.ShapeDtypeStruct((n, h, w, cp), jnp.int32),
            ),
            interpret=interpret,
        )(xp)
        return out[..., :c], idx[..., :c]
    out = pl.pallas_call(
        kernel,
        grid=(n, cp // cb),
        in_specs=[_spec((1, h + 2, w + 2, cb))],
        out_specs=out_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(xp)
    return out[..., :c], None


@functools.partial(jax.jit, static_argnames=("interpret",))
def _max_pool3x3_bwd(g, idx, interpret=False):
    n, h, w, _ = g.shape
    cb = _chunk(g.shape[-1])
    g, c = _pad_channels(g, cb)
    idx, _ = _pad_channels(idx, cb)
    cp = g.shape[-1]
    gp = jnp.pad(g, [(0, 0), (1, 1), (1, 1), (0, 0)])
    ip = jnp.pad(
        idx, [(0, 0), (1, 1), (1, 1), (0, 0)], constant_values=9
    )
    kernel = functools.partial(_bwd_kernel, h=h, w=w)
    out = pl.pallas_call(
        kernel,
        grid=(n, cp // cb),
        in_specs=[
            _spec((1, h + 2, w + 2, cb)),
            _spec((1, h + 2, w + 2, cb)),
        ],
        out_specs=_spec((1, h, w, cb)),
        out_shape=jax.ShapeDtypeStruct((n, h, w, cp), g.dtype),
        interpret=interpret,
    )(gp, ip)
    return out[..., :c]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def max_pool3x3_s1(x, interpret: bool = False):
    """3x3/stride-1/pad-1 max pool, NHWC, Pallas fwd+bwd."""
    # primal-only call (no differentiation): skip the winner-index output
    out, _ = _max_pool3x3_fwd(x, interpret=interpret, emit_idx=False)
    return out


def _vjp_fwd(x, interpret):
    out, idx = _max_pool3x3_fwd(x, interpret=interpret)
    return out, idx


def _vjp_bwd(interpret, idx, g):
    return (_max_pool3x3_bwd(g, idx, interpret=interpret),)


max_pool3x3_s1.defvjp(_vjp_fwd, _vjp_bwd)
