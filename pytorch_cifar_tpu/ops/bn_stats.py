"""Pallas TPU kernel: one-pass per-channel batch moments (E[x], E[x^2]).

Target: the ResNet18 step's largest non-conv cost — BatchNorm batch-
statistics and BN-gradient reductions, profiled at ~35% of the step
(multiply_reduce fusions, BENCHMARKS.md). The forward moments are two
full reads of every activation tensor if XLA materializes them as separate
reductions; this kernel computes both sums in ONE pass (read x once, emit
(sum, sum_sq) per channel), with an elementwise custom VJP
(d/dx [a.sum(x) + b.sum(x^2)] = a + 2 b x) that fuses into neighboring
elementwise work.

Wired into models.common.BatchNorm only if the on-chip A/B
(tools/bn_bench.py) beats XLA's twin-reduce — see BENCHMARKS.md for the
measured verdict.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_cifar_tpu.ops.blocking import batch_chunk, channel_chunk, pad_channels


def _moments_kernel(x_ref, out_ref):
    # x_ref: (nb, h, w, cb) block; out_ref: (2, cb) running (sum, sum_sq).
    # The batch dimension is the INNERMOST grid dim: Pallas only preserves
    # a revisited output block's contents across CONSECUTIVE grid steps,
    # so the accumulation dim must iterate fastest. (With it outermost,
    # c > 2 blocks cycles the double buffers and the accumulator reads
    # stale data — exactly the wrong-answer-at-c=512 bug this had.)
    i = pl.program_id(1)
    xf = x_ref[...].astype(jnp.float32)
    flat = xf.reshape(-1, xf.shape[-1])
    s1 = jnp.sum(flat, axis=0)
    s2 = jnp.sum(jnp.square(flat), axis=0)
    block = jnp.stack([s1, s2])

    @pl.when(i == 0)
    def _init():
        out_ref[...] = block

    @pl.when(i > 0)
    def _acc():
        out_ref[...] = out_ref[...] + block


@functools.partial(jax.jit, static_argnames=("interpret",))
def _moments_sums(x, interpret=False):
    n, h, w, c = x.shape
    cb = channel_chunk(c)
    x, c = pad_channels(x, cb)
    cp = x.shape[-1]
    nb = batch_chunk(n)
    out = pl.pallas_call(
        _moments_kernel,
        grid=(cp // cb, n // nb),  # batch innermost: see _moments_kernel
        in_specs=[
            pl.BlockSpec(
                (nb, h, w, cb),
                lambda j, i: (i, 0, 0, j),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (2, cb), lambda j, i: (0, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((2, cp), jnp.float32),
        interpret=interpret,
    )(x)
    return out[:, :c]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def fused_moments(x, interpret: bool = False):
    """(E[x], E[x^2]) over all but the channel axis, fp32, one pass."""
    sums = _moments_sums(x, interpret=interpret)
    n = x.shape[0] * x.shape[1] * x.shape[2]
    return sums[0] / n, sums[1] / n


def _vjp_fwd(x, interpret):
    return fused_moments(x, interpret), x


def _vjp_bwd(interpret, x, cts):
    a, b = cts  # cotangents of (mean, mean_sq)
    n = x.shape[0] * x.shape[1] * x.shape[2]
    # d mean/dx = 1/n ; d mean_sq/dx = 2x/n — a per-channel FMA that XLA
    # fuses into adjacent elementwise work (no reduction in the backward)
    dx = (a / n) + x.astype(jnp.float32) * (2.0 * b / n)
    return (dx.astype(x.dtype),)


fused_moments.defvjp(_vjp_fwd, _vjp_bwd)
