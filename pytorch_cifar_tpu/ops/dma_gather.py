"""Pipelined row-gather via raw HBM-to-HBM DMAs (Pallas).

Why this exists: the device data plane shuffles the HBM-resident dataset
once per epoch — a gather of ~50k rows of 3 KB each. XLA:TPU lowers that
gather to what behaves like one synchronous descriptor per row: measured
129 ms for 154 MB (1.2 GB/s, ~2.6 us/row) on the v5e, invariant to index
order (sorted indices measure 163 ms) and element type (int32-viewed
gather identical) — i.e. descriptor-latency bound, not bandwidth bound
(BENCHMARKS.md round 3). That one op was ~9% of the training epoch.

The fix is depth, not locality: this kernel issues the same per-row DMAs
but keeps a ring of ``_INFLIGHT`` copies in flight, so row latencies
overlap instead of serializing. The DMAs are HBM->HBM (no VMEM staging,
no compute units involved); indices stream through SMEM in grid blocks.

Semantics: exactly ``jnp.take(images, idx, axis=0)`` for in-range indices
(the data plane's indices are in-range by construction; like
``jnp.take``'s default clip mode, out-of-range behavior is not relied
upon). Exactness is pinned by tests/test_ops.py against jnp.take, in
interpret mode on CPU and compiled on TPU.

No reference counterpart: torch shuffles host-side in the DataLoader
(reference main.py:50); a device-resident data plane is a TPU-native
design with a TPU-native cost model.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.5 spells the unconstrained-HBM memory space ANY; the HBM alias
# arrived with the MemorySpace rename. One name here, both jax versions.
_HBM = getattr(pltpu, "HBM", None) or pltpu.ANY

# DMA pipeline depth: enough to cover ~2.6 us completion latency at the
# observed ~0.1-0.2 us issue rate; deeper rings add no throughput.
_INFLIGHT = 32


def _gather_kernel(idx_ref, img_ref, out_ref, sems):
    """One grid step: gather ``block`` rows whose indices sit in SMEM.

    Ring discipline: DMA j signals sems[j % K]; before reusing the slot we
    wait the copy issued K steps earlier (reconstructing its descriptor —
    the wait needs the byte count, which is the same for every row). The
    tail drain waits the last min(K, block) copies so the semaphores are
    clean when the next grid step reuses them.
    """
    block = idx_ref.shape[0]
    k = sems.shape[0]
    base = pl.program_id(0) * block

    def copy(j, slot):
        return pltpu.make_async_copy(
            img_ref.at[idx_ref[j]], out_ref.at[base + j], sems.at[slot]
        )

    # Mosaic's fori_loop cannot partially unroll; unroll by hand — U DMA
    # issues per loop iteration amortize the scalar-loop overhead (the
    # measured bound: ~2 us/row at U=1 is issue rate, not DMA bandwidth).
    u = 8 if block % 8 == 0 else 1

    def body(i, carry):
        for t in range(u):
            j = i * u + t
            slot = jax.lax.rem(j, k)

            @pl.when(j >= k)
            def _wait_prev(j=j, slot=slot):
                copy(j - k, slot).wait()

            copy(j, slot).start()
        return carry

    jax.lax.fori_loop(0, block // u, body, 0, unroll=False)

    # block and k are static shape ints: keep the loop bound a Python int
    # so fori_loop sees static bounds (required for `unroll` on older jax;
    # a jnp.minimum here would trace to a dynamic bound for no gain)
    tail = min(block, k)

    def drain(t, carry):
        j = block - tail + t

        @pl.when(j < block)
        def _wait_tail():
            copy(j, jax.lax.rem(j, k)).wait()

        return carry

    jax.lax.fori_loop(0, tail, drain, 0, unroll=False)


def rows_dma_tileable(row_shape) -> bool:
    """True when rows of this trailing shape satisfy the kernel's layout
    precondition ((k*8, 128) view — see dma_row_gather). Callers that
    auto-enable the kernel must check this and fall back to jnp.take."""
    elems = 1
    for d in row_shape:
        elems *= int(d)
    return elems % 128 == 0 and (elems // 128) % 8 == 0


@partial(jax.jit, static_argnames=("block", "interpret"))
def dma_row_gather(
    images: jax.Array,
    idx: jax.Array,
    *,
    block: int = 2048,
    interpret: bool = False,
) -> jax.Array:
    """``jnp.take(images, idx, axis=0)`` as pipelined HBM->HBM row DMAs.

    images: (N, ...) — any dtype/trailing shape; rows move as raw bytes.
    idx:    (M,) int32, values in [0, N).
    block:  target indices staged into SMEM per grid step; rounded down
            to the largest divisor of M (the SMEM cost is 4 bytes/index,
            so any value in the hundreds-to-thousands is fine).
    """
    n = images.shape[0]
    m = idx.shape[0]
    row_shape = images.shape[1:]
    # SMEM 1-D operands tile at 1024: a partial block must be a multiple
    # of 1024 that divides M ("matches the full shape" is the other
    # allowed case, used when M itself is small)
    if m <= block:
        block = m
    else:
        block = (min(block, m) // 1024) * 1024
        while block and m % block:
            block -= 1024
        if not block:
            block = m  # no 1024-multiple divisor: single grid step
    grid = m // block
    # Mosaic tiles the two minor dims of a memref — even in HBM — so the
    # sliced (row) dim must be a leading UNtiled dim and the tiled dims
    # must be aligned: rows are viewed as (sublanes, 128 lanes) with the
    # sublane count a multiple of the dtype's sublane tiling. A 2-D
    # (M, bytes) view fails ("slice along dimension 0 must be aligned to
    # tiling (8)"), as does (N,32,32,3) (minor dim 3 vs 128 lanes).
    elems = 1
    for d in row_shape:
        elems *= d
    # Mosaic's slice-alignment requirement: (8 sublanes, 128 lanes)
    if not rows_dma_tileable(row_shape):
        raise ValueError(
            f"row of {elems} elems cannot tile as (k*8, 128); use jnp.take"
        )
    flat = images.reshape(n, elems // 128, 128)
    out = pl.pallas_call(
        _gather_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block,), lambda g: (g,), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=_HBM),
        ],
        out_specs=pl.BlockSpec(memory_space=_HBM),
        out_shape=jax.ShapeDtypeStruct((m,) + flat.shape[1:], images.dtype),
        scratch_shapes=[pltpu.SemaphoreType.DMA((_INFLIGHT,))],
        interpret=interpret,
    )(idx.astype(jnp.int32), flat)
    return out.reshape((m,) + row_shape)
