"""Custom TPU kernels (Pallas) for hot ops.

The compute path defaults to XLA-generated kernels — on TPU the compiler's
conv/BN/ReLU fusion is already strong, and hand-scheduling what XLA does
well is an anti-pattern. This package holds the exceptions: kernels where
explicit VMEM control or fusion beyond XLA's scope pays, each shipped with
an equivalence test against the lax reference and an honest benchmark.
"""

from pytorch_cifar_tpu.ops.conv_bn_relu import (
    conv3x3_bn_relu,
    conv3x3_bn_relu_reference,
    fold_batchnorm,
)
from pytorch_cifar_tpu.ops.depthwise_stencil import (
    depthwise_stencil,
    depthwise_xla,
)

__all__ = [
    "conv3x3_bn_relu",
    "conv3x3_bn_relu_reference",
    "fold_batchnorm",
    "depthwise_stencil",
    "depthwise_xla",
]
