// Native host-side data plane for the TPU training framework.
//
// The reference delegates its host data path to torch's C++ DataLoader
// worker pool + torchvision transforms (num_workers, main.py:45,
// main_dist.py:121-127 — SURVEY.md §2.3 "DataLoader C++ worker pool").
// This is the TPU-native equivalent: the per-batch host work (index gather,
// CIFAR binary record decode, optional CPU-mode augmentation) implemented in
// C++ with OpenMP, exposed to Python over a flat C ABI consumed via ctypes
// (no pybind11 in the image). Device-side augmentation (data/augment.py)
// remains the default on TPU; these paths feed it uint8 batches and serve
// CPU-only training.
//
// Built on demand by __init__.py:_build() (g++ -O3 -fopenmp -shared -fPIC).

#include <cstdint>
#include <cstring>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// Gather `batch` images of `image_bytes` bytes each from `images` at
// `idx[0..batch)` into contiguous `out`. Parallel memcpy — the hot host op
// feeding every training step.
void gather_batch(const uint8_t* images, const int32_t* idx, int64_t batch,
                  int64_t image_bytes, uint8_t* out) {
#pragma omp parallel for schedule(static)
  for (int64_t b = 0; b < batch; ++b) {
    std::memcpy(out + b * image_bytes,
                images + static_cast<int64_t>(idx[b]) * image_bytes,
                static_cast<size_t>(image_bytes));
  }
}

// Gather labels (int32) — trivial, but keeps the whole batch assembly in one
// native pass when called alongside gather_batch.
void gather_labels(const int32_t* labels, const int32_t* idx, int64_t batch,
                   int32_t* out) {
  for (int64_t b = 0; b < batch; ++b) out[b] = labels[idx[b]];
}

// Decode CIFAR-10 binary records (the cifar-10-binary.tar.gz layout:
// 1 label byte + 3072 planar CHW bytes per record) into NHWC uint8 images
// + int32 labels. The planar->interleaved transpose is the real decode work
// torchvision does per sample in Python/PIL.
void decode_cifar_records(const uint8_t* records, int64_t n, uint8_t* images,
                          int32_t* labels) {
  const int64_t kRecord = 3073;  // 1 + 3*32*32
  const int64_t kPlane = 1024;   // 32*32
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* rec = records + i * kRecord;
    labels[i] = rec[0];
    const uint8_t* px = rec + 1;
    uint8_t* out = images + i * 3 * kPlane;
    for (int64_t p = 0; p < kPlane; ++p) {
      out[p * 3 + 0] = px[p];
      out[p * 3 + 1] = px[kPlane + p];
      out[p * 3 + 2] = px[2 * kPlane + p];
    }
  }
}

// CPU-mode augmentation: zero-pad by `padding`, crop at per-image offsets
// (off_h, off_w), optional horizontal flip. uint8 in/out, NHWC. Mirrors
// data/augment.py's device path for hosts training without an accelerator.
void augment_batch_u8(const uint8_t* in, int64_t n, int64_t h, int64_t w,
                      int64_t c, int64_t padding, const int32_t* off_h,
                      const int32_t* off_w, const uint8_t* flip,
                      uint8_t* out) {
#pragma omp parallel for schedule(static)
  for (int64_t b = 0; b < n; ++b) {
    const uint8_t* img = in + b * h * w * c;
    uint8_t* dst = out + b * h * w * c;
    const int64_t dy = off_h[b] - padding;  // source row of output row 0
    const int64_t dx = off_w[b] - padding;
    const bool fl = flip[b] != 0;
    for (int64_t y = 0; y < h; ++y) {
      const int64_t sy = y + dy;
      if (sy < 0 || sy >= h) {
        std::memset(dst + y * w * c, 0, static_cast<size_t>(w * c));
        continue;
      }
      for (int64_t x = 0; x < w; ++x) {
        const int64_t ox = fl ? (w - 1 - x) : x;
        const int64_t sx = x + dx;
        uint8_t* px = dst + (y * w + ox) * c;
        if (sx < 0 || sx >= w) {
          std::memset(px, 0, static_cast<size_t>(c));
        } else {
          std::memcpy(px, img + (sy * w + sx) * c, static_cast<size_t>(c));
        }
      }
    }
  }
}

int native_num_threads() {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
