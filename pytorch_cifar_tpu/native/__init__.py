"""ctypes bindings for the native host-side data plane (cifar_native.cpp).

The shared library is built on demand with g++ (cached next to the source);
every entry point has a pure-numpy fallback so the framework runs unchanged
where no toolchain exists. ``native_available()`` reports which path is live.

Python<->C++ binding is ctypes over a flat C ABI — the image has no pybind11
(environment constraint); ctypes releases the GIL during calls, so the
OpenMP gather/decode/augment overlap with device dispatch from the training
thread.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "cifar_native.cpp")
_SO = os.path.join(_DIR, "cifar_native.so")

_lib: Optional[ctypes.CDLL] = None
_lib_lock = threading.Lock()
_build_failed = False


def _build() -> bool:
    # unique temp per process: concurrent builders (multi-process launch,
    # parallel pytest) must not interleave writes into one file
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-fopenmp",
        _SRC, "-o", tmp,
    ]
    try:
        # graftcheck: noqa[blocking-under-lock] -- one-time lazy build: _lib_lock SHOULD serialize concurrent loaders behind the single g++ compile (racing builders is the bug), and timeout=120 bounds the stall
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=120
        )
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        fresh = not os.path.isfile(_SO) or os.path.getmtime(
            _SO
        ) < os.path.getmtime(_SRC)
        if fresh and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _build_failed = True
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64 = ctypes.c_int64
        lib.gather_batch.argtypes = [u8p, i32p, i64, i64, u8p]
        lib.gather_labels.argtypes = [i32p, i32p, i64, i32p]
        lib.decode_cifar_records.argtypes = [u8p, i64, u8p, i32p]
        lib.augment_batch_u8.argtypes = [
            u8p, i64, i64, i64, i64, i64, i32p, i32p, u8p, u8p,
        ]
        lib.native_num_threads.restype = ctypes.c_int
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def gather_batch(
    images: np.ndarray, labels: np.ndarray, idx: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Contiguous (images[idx], labels[idx]); native parallel memcpy when
    available, numpy fancy indexing otherwise."""
    lib = _load()
    if (
        lib is None
        or images.dtype != np.uint8
        or not images.flags["C_CONTIGUOUS"]
        or labels.dtype != np.int32
        or not labels.flags["C_CONTIGUOUS"]
    ):
        # don't silently copy/convert whole datasets per call — numpy
        # indexing is the right tool for non-canonical inputs (Dataloader
        # normalizes once at construction)
        return images[idx], labels[idx]
    idx = np.ascontiguousarray(idx, np.int32)
    if idx.size and (idx.min() < 0 or idx.max() >= images.shape[0]):
        # preserve numpy fancy-indexing's bounds contract; the C path
        # would memcpy from out-of-range addresses
        raise IndexError(
            f"index out of range [0, {images.shape[0]}) in gather_batch"
        )
    batch = idx.shape[0]
    image_bytes = int(np.prod(images.shape[1:]))
    out_x = np.empty((batch,) + images.shape[1:], np.uint8)
    out_y = np.empty((batch,), np.int32)
    lib.gather_batch(_u8(images), _i32(idx), batch, image_bytes, _u8(out_x))
    lib.gather_labels(_i32(labels), _i32(idx), batch, _i32(out_y))
    return out_x, out_y


def decode_cifar_records(records: bytes | np.ndarray):
    """CIFAR-10 binary records (3073 B each, planar CHW) -> NHWC uint8 +
    int32 labels."""
    buf = np.frombuffer(records, np.uint8) if isinstance(records, bytes) else records
    buf = np.ascontiguousarray(buf, np.uint8)
    n = buf.size // 3073
    lib = _load()
    if lib is None:
        recs = buf[: n * 3073].reshape(n, 3073)
        labels = recs[:, 0].astype(np.int32)
        images = (
            recs[:, 1:].reshape(n, 3, 32, 32).transpose(0, 2, 3, 1).copy()
        )
        return images, labels
    images = np.empty((n, 32, 32, 3), np.uint8)
    labels = np.empty((n,), np.int32)
    lib.decode_cifar_records(_u8(buf), n, _u8(images), _i32(labels))
    return images, labels


def augment_batch_u8(
    images: np.ndarray,
    off_h: np.ndarray,
    off_w: np.ndarray,
    flip: np.ndarray,
    padding: int = 4,
) -> np.ndarray:
    """Host-side crop+flip (uint8): the CPU-mode analogue of the on-device
    augmentation; offsets in [0, 2*padding], flip is a 0/1 mask."""
    images = np.ascontiguousarray(images, np.uint8)
    n, h, w, c = images.shape
    lib = _load()
    off_h = np.ascontiguousarray(off_h, np.int32)
    off_w = np.ascontiguousarray(off_w, np.int32)
    flip = np.ascontiguousarray(flip, np.uint8)
    if lib is None:
        padded = np.zeros((n, h + 2 * padding, w + 2 * padding, c), np.uint8)
        padded[:, padding : padding + h, padding : padding + w] = images
        out = np.empty_like(images)
        for b in range(n):
            img = padded[b, off_h[b] : off_h[b] + h, off_w[b] : off_w[b] + w]
            out[b] = img[:, ::-1] if flip[b] else img
        return out
    out = np.empty_like(images)
    lib.augment_batch_u8(
        _u8(images), n, h, w, c, padding, _i32(off_h), _i32(off_w),
        _u8(flip), _u8(out),
    )
    return out


def native_num_threads() -> int:
    lib = _load()
    return int(lib.native_num_threads()) if lib is not None else 0
