"""DLA (paper version) for CIFAR-10 (reference: models/dla.py:11-123).

Differs from SimpleDLA in the Tree: a level-N tree aggregates
(level+2)*out_channels at its root — a ``prev_root`` block on the raw input,
the chain of level-i subtrees, and the left/right nodes
(models/dla.py:62-82). Level-1 trees match SimpleDLA's binary form. Stage
layout and stems are identical to SimpleDLA (models/dla.py:88-110).

Golden param count: 16,291,386.
"""

from __future__ import annotations

from typing import Any, Optional

from flax import linen as nn

from pytorch_cifar_tpu.models.common import BatchNorm, Conv, Dense, avg_pool
from pytorch_cifar_tpu.models.dla_simple import BasicBlock, Root


class Tree(nn.Module):
    """Paper aggregation tree (models/dla.py:53-82); levels <= 2 in this net,
    so the recursion unrolls statically at trace time."""

    out_channels: int
    level: int = 1
    stride: int = 1
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        if self.level == 1:
            out1 = BasicBlock(self.out_channels, self.stride, dtype=self.dtype)(
                x, train
            )
            out2 = BasicBlock(self.out_channels, 1, dtype=self.dtype)(out1, train)
            return Root(self.out_channels, dtype=self.dtype)([out1, out2], train)

        xs = [
            BasicBlock(self.out_channels, self.stride, dtype=self.dtype)(x, train)
        ]  # prev_root
        for i in reversed(range(1, self.level)):
            x = Tree(self.out_channels, i, self.stride, dtype=self.dtype)(x, train)
            xs.append(x)
        x = BasicBlock(self.out_channels, 1, dtype=self.dtype)(x, train)
        xs.append(x)
        x = BasicBlock(self.out_channels, 1, dtype=self.dtype)(x, train)
        xs.append(x)
        return Root(self.out_channels, dtype=self.dtype)(xs, train)


class DLA(nn.Module):
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        for width in (16, 16, 32):  # base, layer1, layer2
            x = Conv(width, 3, padding=1, use_bias=False, dtype=self.dtype)(x)
            x = nn.relu(
                BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
            )
        for out_ch, level, stride in (
            (64, 1, 1), (128, 2, 2), (256, 2, 2), (512, 1, 2)
        ):
            x = Tree(out_ch, level, stride, dtype=self.dtype)(x, train)
        x = avg_pool(x, 4)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)
