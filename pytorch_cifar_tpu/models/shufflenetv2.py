"""ShuffleNetV2 for CIFAR-10 (reference: models/shufflenetv2.py:10-152).

Basic block: split channels 50/50 (models/shufflenetv2.py:27-29), transform
the *second* half (1x1 -> depthwise 3x3 (no relu after) -> 1x1), concat with
the untouched first half, then channel-shuffle with g=2
(models/shufflenetv2.py:48-55). Down block: two stride-2 branches (depthwise
then 1x1 / 1x1 then depthwise then 1x1), concat + shuffle
(models/shufflenetv2.py:82-93). Stem conv3x3(3->24) with the ImageNet
maxpool removed (models/shufflenetv2.py:123); final 1x1 expand then avg-pool
4 + linear (models/shufflenetv2.py:109-112,127-130).

Golden param counts: 0.5x 352,042 · 1x 1,263,854 · 1.5x 2,488,874 ·
2x 5,338,026.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from pytorch_cifar_tpu.models.common import (
    BatchNorm,
    Conv,
    Dense,
    avg_pool,
    channel_shuffle,
)


class BasicBlock(nn.Module):
    split_ratio: float = 0.5
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        bn = partial(BatchNorm, use_running_average=not train, dtype=self.dtype)
        c = int(x.shape[-1] * self.split_ratio)
        x1, x2 = x[..., :c], x[..., c:]
        ch = x2.shape[-1]

        out = Conv(ch, 1, use_bias=False, dtype=self.dtype)(x2)
        out = nn.relu(bn()(out))
        out = Conv(ch, 3, padding=1, groups=ch, use_bias=False,
                   dtype=self.dtype)(out)
        out = bn()(out)  # no relu after depthwise (models/shufflenetv2.py:51)
        out = Conv(ch, 1, use_bias=False, dtype=self.dtype)(out)
        out = nn.relu(bn()(out))

        out = jnp.concatenate([x1, out], axis=-1)
        return channel_shuffle(out, 2)


class DownBlock(nn.Module):
    out_channels: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        bn = partial(BatchNorm, use_running_average=not train, dtype=self.dtype)
        in_ch = x.shape[-1]
        mid = self.out_channels // 2

        # left: depthwise s2 -> 1x1
        left = Conv(in_ch, 3, strides=2, padding=1, groups=in_ch,
                    use_bias=False, dtype=self.dtype)(x)
        left = bn()(left)
        left = Conv(mid, 1, use_bias=False, dtype=self.dtype)(left)
        left = nn.relu(bn()(left))

        # right: 1x1 -> depthwise s2 -> 1x1
        right = Conv(mid, 1, use_bias=False, dtype=self.dtype)(x)
        right = nn.relu(bn()(right))
        right = Conv(mid, 3, strides=2, padding=1, groups=mid,
                     use_bias=False, dtype=self.dtype)(right)
        right = bn()(right)
        right = Conv(mid, 1, use_bias=False, dtype=self.dtype)(right)
        right = nn.relu(bn()(right))

        out = jnp.concatenate([left, right], axis=-1)
        return channel_shuffle(out, 2)


_CONFIGS = {
    0.5: {"out_channels": (48, 96, 192, 1024), "num_blocks": (3, 7, 3)},
    1: {"out_channels": (116, 232, 464, 1024), "num_blocks": (3, 7, 3)},
    1.5: {"out_channels": (176, 352, 704, 1024), "num_blocks": (3, 7, 3)},
    2: {"out_channels": (224, 488, 976, 2048), "num_blocks": (3, 7, 3)},
}


class ShuffleNetV2(nn.Module):
    net_size: float = 1
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = _CONFIGS[self.net_size]
        x = Conv(24, 3, padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        for out_ch, nblocks in zip(cfg["out_channels"][:3], cfg["num_blocks"]):
            x = DownBlock(out_ch, dtype=self.dtype)(x, train)
            for _ in range(nblocks):
                x = BasicBlock(dtype=self.dtype)(x, train)
        x = Conv(cfg["out_channels"][3], 1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        x = avg_pool(x, 4)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)
