"""ResNeXt-29 for CIFAR-10 (reference: models/resnext.py:10-87).

Grouped-conv bottleneck (1x1 -> grouped 3x3 -> 1x1 expand x2) with projection
shortcut on stride/width change (models/resnext.py:24-29). Three stages only
(layer4 commented out in the reference, models/resnext.py:52) with strides
1,2,2; bottleneck width doubles per stage (models/resnext.py:62). Stem is a
1x1 conv (models/resnext.py:47). Head: 8x8 avg-pool + linear from
cardinality*width*8 (models/resnext.py:53).

Golden param counts: 2x64d 9,128,778 · 4x64d 27,104,586 · 8x64d 89,598,282 ·
32x4d 4,774,218.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence

from flax import linen as nn

from pytorch_cifar_tpu.models.common import (
    BatchNorm,
    Conv,
    Dense,
    avg_pool,
)

_EXPANSION = 2


class ResNeXtBlock(nn.Module):
    cardinality: int
    bottleneck_width: int
    stride: int = 1
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        bn = partial(BatchNorm, use_running_average=not train, dtype=self.dtype)
        group_width = self.cardinality * self.bottleneck_width
        out_width = _EXPANSION * group_width

        out = Conv(group_width, 1, use_bias=False, dtype=self.dtype)(x)
        out = nn.relu(bn()(out))
        out = Conv(group_width, 3, strides=self.stride, padding=1,
                   groups=self.cardinality, use_bias=False, dtype=self.dtype)(out)
        out = nn.relu(bn()(out))
        out = Conv(out_width, 1, use_bias=False, dtype=self.dtype)(out)
        out = bn()(out)

        if self.stride != 1 or x.shape[-1] != out_width:
            x = Conv(out_width, 1, strides=self.stride, use_bias=False,
                     dtype=self.dtype)(x)
            x = bn()(x)
        return nn.relu(out + x)


class ResNeXt(nn.Module):
    num_blocks: Sequence[int]
    cardinality: int
    bottleneck_width: int
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = Conv(64, 1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        width = self.bottleneck_width
        for stage, nblocks in enumerate(self.num_blocks):
            for i in range(nblocks):
                stride = (1 if stage == 0 else 2) if i == 0 else 1
                x = ResNeXtBlock(self.cardinality, width, stride,
                                 dtype=self.dtype)(x, train)
            width *= 2
        x = avg_pool(x, 8)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)


def ResNeXt29_2x64d(num_classes: int = 10, dtype=None, **kw):
    return ResNeXt((3, 3, 3), 2, 64, num_classes=num_classes, dtype=dtype, **kw)


def ResNeXt29_4x64d(num_classes: int = 10, dtype=None, **kw):
    return ResNeXt((3, 3, 3), 4, 64, num_classes=num_classes, dtype=dtype, **kw)


def ResNeXt29_8x64d(num_classes: int = 10, dtype=None, **kw):
    return ResNeXt((3, 3, 3), 8, 64, num_classes=num_classes, dtype=dtype, **kw)


def ResNeXt29_32x4d(num_classes: int = 10, dtype=None, **kw):
    return ResNeXt((3, 3, 3), 32, 4, num_classes=num_classes, dtype=dtype, **kw)
