"""GoogLeNet for CIFAR-10 (reference: models/googlenet.py:7-98).

Inception block with four parallel branches concatenated on channels
(models/googlenet.py:48-53): 1x1 / 1x1->3x3 / 1x1->3x3->3x3 (the 5x5 branch
implemented as two 3x3s, models/googlenet.py:28-38) / maxpool3->1x1. All
branch convs keep their bias (torch default). Stem is conv3x3(3->192)+BN+ReLU
(models/googlenet.py:59-63); stage transitions are maxpool 3/s2/p1
(models/googlenet.py:68); head is 8x8 avg-pool + 1024->10 linear
(models/googlenet.py:79-80).

Golden param count: 6,166,250.

``merged_1x1`` (DEFAULT ON) executes the three same-input 1x1 convs of
each cell (the 1x1 branch and the two reduce convs) as ONE conv of width
``n1x1+n3x3red+n5x5red``, with one BN-moments reduce over the merged
output. Exact, not approximate: each conv output channel is an
independent dot product, and BN statistics are per-channel, so the merged
activations/moments are the concatenation of the per-branch ones. The
param tree is bit-identical to the stock path (ConvParams twins +
explicit module names), so checkpoints, golden counts, and torch
transplants are unaffected; ``merged_1x1=False`` restores the literal
per-branch execution. Motivation: the narrow reduce convs (16-48
channels) starve the 128-wide MXU lanes — the same structural waste
class as ResNeXt's narrow groups (BENCHMARKS.md round 3).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from pytorch_cifar_tpu.models.common import (
    BatchNorm,
    Conv,
    ConvParams,
    Dense,
    avg_pool,
    bn_batch_moments,
    max_pool,
)


class Inception(nn.Module):
    """Four-branch inception cell; output channels = sum of branch widths."""

    n1x1: int
    n3x3red: int
    n3x3: int
    n5x5red: int
    n5x5: int
    pool_planes: int
    dtype: Optional[Any] = None
    merged_1x1: bool = True
    merged_3x3: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        def cbr(h, features, kernel, conv_name, bn_name, padding=0):
            h = Conv(
                features, kernel, padding=padding, dtype=self.dtype,
                name=conv_name,
            )(h)
            h = BatchNorm(
                use_running_average=not train, dtype=self.dtype, name=bn_name
            )(h)
            return nn.relu(h)

        if self.merged_3x3 and not self.merged_1x1:
            # merged_3x3 consumes the split points the merged-heads path
            # produces; silently running stock here would ignore the flag
            raise ValueError(
                "merged_3x3=True requires merged_1x1=True (the mid-level "
                "merge operates on the merged heads' outputs)"
            )
        # explicit names == the stock path's auto-assigned ones, so both
        # modes build the same param tree; the stock path keeps the full
        # per-branch CALL order (y1, y2, y3, y4 — torch definition order,
        # which tests/test_torch_parity.py aligns against)
        if self.merged_1x1:
            y1, y2, y3 = self._merged_heads(x, train)
            if self.merged_3x3:
                y2, y3 = self._merged_mid(y2, y3, train)
            else:
                y2 = cbr(y2, self.n3x3, 3, "Conv_2", "BatchNorm_2", padding=1)
                y3 = cbr(y3, self.n5x5, 3, "Conv_4", "BatchNorm_4", padding=1)
            y3 = cbr(y3, self.n5x5, 3, "Conv_5", "BatchNorm_5", padding=1)
        else:
            y1 = cbr(x, self.n1x1, 1, "Conv_0", "BatchNorm_0")
            y2 = cbr(x, self.n3x3red, 1, "Conv_1", "BatchNorm_1")
            y2 = cbr(y2, self.n3x3, 3, "Conv_2", "BatchNorm_2", padding=1)
            y3 = cbr(x, self.n5x5red, 1, "Conv_3", "BatchNorm_3")
            y3 = cbr(y3, self.n5x5, 3, "Conv_4", "BatchNorm_4", padding=1)
            y3 = cbr(y3, self.n5x5, 3, "Conv_5", "BatchNorm_5", padding=1)

        y4 = max_pool(x, 3, stride=1, padding=1)
        y4 = cbr(y4, self.pool_planes, 1, "Conv_6", "BatchNorm_6")

        return jnp.concatenate([y1, y2, y3, y4], axis=-1)

    def _merged_conv_bn(self, x, kernel, bias, widths, bn_names, pad, train):
        """One conv over the merged kernel, one BN-moments reduce, then
        per-branch slice + BatchNorm + relu. Shared tail of both merged
        paths so their moments/BN wiring cannot drift."""
        x, kernel, bias = nn.dtypes.promote_dtype(
            x, kernel, bias, dtype=self.dtype
        )
        h = (
            jax.lax.conv_general_dilated(
                x,
                kernel,
                window_strides=(1, 1),
                padding=[(pad, pad), (pad, pad)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            + bias
        )
        moments = None
        if train and not self.is_initializing():
            moments = bn_batch_moments(h)
        outs = []
        offset = 0
        for feats, bn_name in zip(widths, bn_names):
            m = None
            if moments is not None:
                m = (
                    moments[0][offset : offset + feats],
                    moments[1][offset : offset + feats],
                )
            outs.append(
                nn.relu(
                    BatchNorm(
                        use_running_average=not train,
                        dtype=self.dtype,
                        name=bn_name,
                    )(h[..., offset : offset + feats], moments=m)
                )
            )
            offset += feats
        return tuple(outs)

    def _merged_heads(self, x, train: bool):
        """The three same-input 1x1 conv+BN+relu heads as one conv + one
        moments reduce, sliced back apart for their per-branch BNs."""
        widths = (self.n1x1, self.n3x3red, self.n5x5red)
        cin = x.shape[-1]
        parts = [
            ConvParams(f, 1, cin, name=n)()
            for f, n in zip(widths, ("Conv_0", "Conv_1", "Conv_3"))
        ]
        kernel = jnp.concatenate([k for k, _ in parts], axis=-1)
        bias = jnp.concatenate([b for _, b in parts])
        return self._merged_conv_bn(
            x, kernel, bias, widths,
            ("BatchNorm_0", "BatchNorm_1", "BatchNorm_3"), 0, train,
        )

    def _merged_mid(self, y2, y3, train: bool):
        """The y2 3x3 (n3x3red->n3x3) and y3 first 3x3 (n5x5red->n5x5) as
        ONE block-diagonal dense conv over their concatenated inputs.

        The off-diagonal kernel blocks are exact zeros, so the extra
        accumulation terms are exact zeros — numerics unchanged (the same
        argument as common.py's dense grouped-conv expansion). Spends
        ~1.4-1.6x the FLOPs of the two separate convs to put the narrow
        n5x5 outputs (32-128 channels) on full 128-wide MXU lanes."""
        r1, r2 = self.n3x3red, self.n5x5red
        o1, o2 = self.n3x3, self.n5x5
        k2, b2 = ConvParams(o1, 3, r1, name="Conv_2")()
        k4, b4 = ConvParams(o2, 3, r2, name="Conv_4")()
        top = jnp.concatenate(
            [k2, jnp.zeros((3, 3, r1, o2), k2.dtype)], axis=-1
        )
        bot = jnp.concatenate(
            [jnp.zeros((3, 3, r2, o1), k4.dtype), k4], axis=-1
        )
        kernel = jnp.concatenate([top, bot], axis=2)
        bias = jnp.concatenate([b2, b4])
        z = jnp.concatenate([y2, y3], axis=-1)
        return self._merged_conv_bn(
            z, kernel, bias, (o1, o2),
            ("BatchNorm_2", "BatchNorm_4"), 1, train,
        )


# (n1x1, n3x3red, n3x3, n5x5red, n5x5, pool_planes) per cell, in call order;
# None marks a maxpool 3/s2/p1 transition (models/googlenet.py:65-77,82-94)
_CELLS: Tuple = (
    (64, 96, 128, 16, 32, 32),     # a3
    (128, 128, 192, 32, 96, 64),   # b3
    None,
    (192, 96, 208, 16, 48, 64),    # a4
    (160, 112, 224, 24, 64, 64),   # b4
    (128, 128, 256, 24, 64, 64),   # c4
    (112, 144, 288, 32, 64, 64),   # d4
    (256, 160, 320, 32, 128, 128), # e4
    None,
    (256, 160, 320, 32, 128, 128), # a5
    (384, 192, 384, 48, 128, 128), # b5
)


class GoogLeNet(nn.Module):
    num_classes: int = 10
    dtype: Optional[Any] = None
    merged_1x1: bool = True
    merged_3x3: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = Conv(192, 3, padding=1, dtype=self.dtype)(x)
        x = nn.relu(BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        for cell in _CELLS:
            if cell is None:
                x = max_pool(x, 3, stride=2, padding=1)
            else:
                x = Inception(
                    *cell,
                    dtype=self.dtype,
                    merged_1x1=self.merged_1x1,
                    merged_3x3=self.merged_3x3,
                )(x, train)
        x = avg_pool(x, 8, stride=1)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)
