"""GoogLeNet for CIFAR-10 (reference: models/googlenet.py:7-98).

Inception block with four parallel branches concatenated on channels
(models/googlenet.py:48-53): 1x1 / 1x1->3x3 / 1x1->3x3->3x3 (the 5x5 branch
implemented as two 3x3s, models/googlenet.py:28-38) / maxpool3->1x1. All
branch convs keep their bias (torch default). Stem is conv3x3(3->192)+BN+ReLU
(models/googlenet.py:59-63); stage transitions are maxpool 3/s2/p1
(models/googlenet.py:68); head is 8x8 avg-pool + 1024->10 linear
(models/googlenet.py:79-80).

Golden param count: 6,166,250.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax.numpy as jnp
from flax import linen as nn

from pytorch_cifar_tpu.models.common import (
    BatchNorm,
    Conv,
    Dense,
    avg_pool,
    max_pool,
)


class Inception(nn.Module):
    """Four-branch inception cell; output channels = sum of branch widths."""

    n1x1: int
    n3x3red: int
    n3x3: int
    n5x5red: int
    n5x5: int
    pool_planes: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        def cbr(h, features, kernel, padding=0):
            h = Conv(features, kernel, padding=padding, dtype=self.dtype)(h)
            h = BatchNorm(use_running_average=not train, dtype=self.dtype)(h)
            return nn.relu(h)

        y1 = cbr(x, self.n1x1, 1)

        y2 = cbr(x, self.n3x3red, 1)
        y2 = cbr(y2, self.n3x3, 3, padding=1)

        y3 = cbr(x, self.n5x5red, 1)
        y3 = cbr(y3, self.n5x5, 3, padding=1)
        y3 = cbr(y3, self.n5x5, 3, padding=1)

        y4 = max_pool(x, 3, stride=1, padding=1)
        y4 = cbr(y4, self.pool_planes, 1)

        return jnp.concatenate([y1, y2, y3, y4], axis=-1)


# (n1x1, n3x3red, n3x3, n5x5red, n5x5, pool_planes) per cell, in call order;
# None marks a maxpool 3/s2/p1 transition (models/googlenet.py:65-77,82-94)
_CELLS: Tuple = (
    (64, 96, 128, 16, 32, 32),     # a3
    (128, 128, 192, 32, 96, 64),   # b3
    None,
    (192, 96, 208, 16, 48, 64),    # a4
    (160, 112, 224, 24, 64, 64),   # b4
    (128, 128, 256, 24, 64, 64),   # c4
    (112, 144, 288, 32, 64, 64),   # d4
    (256, 160, 320, 32, 128, 128), # e4
    None,
    (256, 160, 320, 32, 128, 128), # a5
    (384, 192, 384, 48, 128, 128), # b5
)


class GoogLeNet(nn.Module):
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = Conv(192, 3, padding=1, dtype=self.dtype)(x)
        x = nn.relu(BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        for cell in _CELLS:
            if cell is None:
                x = max_pool(x, 3, stride=2, padding=1)
            else:
                x = Inception(*cell, dtype=self.dtype)(x, train)
        x = avg_pool(x, 8, stride=1)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)
