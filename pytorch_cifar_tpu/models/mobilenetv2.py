"""MobileNetV2 for CIFAR-10 (reference: models/mobilenetv2.py:11-80).

Inverted residual blocks: 1x1 expand -> 3x3 depthwise -> 1x1 linear project
(models/mobilenetv2.py:20-27). Residual add only when stride==1
(models/mobilenetv2.py:36), with a 1x1 conv+BN projection shortcut when the
channel count changes (models/mobilenetv2.py:26-30) — note the reference
keeps the expand conv even for expansion=1 in stage one, unlike the paper.
CIFAR adaptations preserved: stem stride 1 and stage-2 stride lowered 2->1
(comments models/mobilenetv2.py:43,52); 4x4 avg-pool head; 320->1280 1x1
conv before the classifier (models/mobilenetv2.py:56-58,73-77).

Golden param count: 2,296,922.
"""

from __future__ import annotations

from typing import Any, Optional

from flax import linen as nn

from pytorch_cifar_tpu.models.common import BatchNorm, Conv, Dense, avg_pool

# (expansion, out_planes, num_blocks, stride) per stage
_CFG = (
    (1, 16, 1, 1),
    (6, 24, 2, 1),  # stride 2 -> 1 for CIFAR (reference models/mobilenetv2.py:43)
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


class InvertedResidual(nn.Module):
    """expand 1x1 -> depthwise 3x3 -> project 1x1 (linear), residual if s==1."""

    planes: int
    expansion: int
    stride: int = 1
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        in_ch = x.shape[-1]
        mid = self.expansion * in_ch
        bn = lambda: BatchNorm(use_running_average=not train, dtype=self.dtype)

        out = Conv(mid, 1, use_bias=False, dtype=self.dtype)(x)
        out = nn.relu(bn()(out))
        out = Conv(mid, 3, strides=self.stride, padding=1, groups=mid,
                   use_bias=False, dtype=self.dtype)(out)
        out = nn.relu(bn()(out))
        out = Conv(self.planes, 1, use_bias=False, dtype=self.dtype)(out)
        out = bn()(out)

        if self.stride == 1:
            if in_ch != self.planes:
                x = Conv(self.planes, 1, use_bias=False, dtype=self.dtype)(x)
                x = bn()(x)
            out = out + x
        return out


class MobileNetV2(nn.Module):
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        bn = lambda: BatchNorm(use_running_average=not train, dtype=self.dtype)
        x = Conv(32, 3, padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(bn()(x))
        for expansion, planes, num_blocks, stride in _CFG:
            for i in range(num_blocks):
                x = InvertedResidual(
                    planes, expansion, stride if i == 0 else 1, dtype=self.dtype
                )(x, train)
        x = Conv(1280, 1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(bn()(x))
        x = avg_pool(x, 4)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)
