"""Model zoo registry.

The reference selects models by editing source (main.py:57-71 hardcodes
SimpleDLA; main_dist.py:136 hardcodes ResNet152 — SURVEY.md §2.5.11). Here
every architecture is a named factory in ``MODEL_REGISTRY`` and selectable
via ``--model``. Factories take ``(num_classes=10, dtype=None)`` and return
a flax Module with signature ``module(x_nhwc, train: bool)``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from flax import linen as nn

from pytorch_cifar_tpu.models.lenet import LeNet
from pytorch_cifar_tpu.models.preact_resnet import (
    PreActResNet18,
    PreActResNet34,
    PreActResNet50,
    PreActResNet101,
    PreActResNet152,
)
from pytorch_cifar_tpu.models.resnet import (
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from pytorch_cifar_tpu.models.vgg import VGG11, VGG13, VGG16, VGG19
from pytorch_cifar_tpu.models.mobilenet import MobileNet
from pytorch_cifar_tpu.models.mobilenetv2 import MobileNetV2
from pytorch_cifar_tpu.models.senet import SENet18

MODEL_REGISTRY: Dict[str, Callable[..., nn.Module]] = {}


def register(name: str, factory: Callable[..., nn.Module]) -> None:
    MODEL_REGISTRY[name] = factory


def create_model(
    name: str, num_classes: int = 10, dtype: Optional[Any] = None, **kwargs
) -> nn.Module:
    if name not in MODEL_REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; available: {sorted(MODEL_REGISTRY)}"
        )
    return MODEL_REGISTRY[name](num_classes=num_classes, dtype=dtype, **kwargs)


def available_models():
    return sorted(MODEL_REGISTRY)


register("LeNet", LeNet)
register("ResNet18", ResNet18)
register("ResNet34", ResNet34)
register("ResNet50", ResNet50)
register("ResNet101", ResNet101)
register("ResNet152", ResNet152)
register("PreActResNet18", PreActResNet18)
register("PreActResNet34", PreActResNet34)
register("PreActResNet50", PreActResNet50)
register("PreActResNet101", PreActResNet101)
register("PreActResNet152", PreActResNet152)
register("VGG11", VGG11)
register("VGG13", VGG13)
register("VGG16", VGG16)
register("VGG19", VGG19)
register("MobileNet", MobileNet)
register("MobileNetV2", MobileNetV2)
register("SENet18", SENet18)
