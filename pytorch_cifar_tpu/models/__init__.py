"""Model zoo registry.

The reference selects models by editing source (main.py:57-71 hardcodes
SimpleDLA; main_dist.py:136 hardcodes ResNet152 — SURVEY.md §2.5.11). Here
every architecture is a named factory in ``MODEL_REGISTRY`` and selectable
via ``--model``. Factories take ``(num_classes=10, dtype=None)`` and return
a flax Module with signature ``module(x_nhwc, train: bool)``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from flax import linen as nn

from pytorch_cifar_tpu.models.lenet import LeNet

MODEL_REGISTRY: Dict[str, Callable[..., nn.Module]] = {}


def register(name: str, factory: Callable[..., nn.Module]) -> None:
    MODEL_REGISTRY[name] = factory


def create_model(
    name: str, num_classes: int = 10, dtype: Optional[Any] = None, **kwargs
) -> nn.Module:
    if name not in MODEL_REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; available: {sorted(MODEL_REGISTRY)}"
        )
    return MODEL_REGISTRY[name](num_classes=num_classes, dtype=dtype, **kwargs)


def available_models():
    return sorted(MODEL_REGISTRY)


register("LeNet", LeNet)
