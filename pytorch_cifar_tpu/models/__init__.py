"""Model zoo registry.

The reference selects models by editing source (main.py:57-71 hardcodes
SimpleDLA; main_dist.py:136 hardcodes ResNet152 — SURVEY.md §2.5.11). Here
every architecture is a named factory in ``MODEL_REGISTRY`` and selectable
via ``--model``. Factories take ``(num_classes=10, dtype=None)`` and return
a flax Module with signature ``module(x_nhwc, train: bool)``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from flax import linen as nn

from pytorch_cifar_tpu.models.lenet import LeNet
from pytorch_cifar_tpu.models.preact_resnet import (
    PreActResNet18,
    PreActResNet34,
    PreActResNet50,
    PreActResNet101,
    PreActResNet152,
)
from pytorch_cifar_tpu.models.resnet import (
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from pytorch_cifar_tpu.models.vgg import VGG11, VGG13, VGG16, VGG19
from pytorch_cifar_tpu.models.mobilenet import MobileNet
from pytorch_cifar_tpu.models.mobilenetv2 import MobileNetV2
from pytorch_cifar_tpu.models.senet import SENet18
from pytorch_cifar_tpu.models.googlenet import GoogLeNet
from pytorch_cifar_tpu.models.densenet import (
    DenseNet121,
    DenseNet161,
    DenseNet169,
    DenseNet201,
    DenseNetCifar,
)
from pytorch_cifar_tpu.models.resnext import (
    ResNeXt29_2x64d,
    ResNeXt29_4x64d,
    ResNeXt29_8x64d,
    ResNeXt29_32x4d,
)
from pytorch_cifar_tpu.models.regnet import (
    RegNetX_200MF,
    RegNetX_400MF,
    RegNetY_400MF,
)
from pytorch_cifar_tpu.models.dpn import DPN26, DPN92
from pytorch_cifar_tpu.models.shufflenet import ShuffleNetG2, ShuffleNetG3
from pytorch_cifar_tpu.models.shufflenetv2 import ShuffleNetV2
from pytorch_cifar_tpu.models.efficientnet import EfficientNetB0
from pytorch_cifar_tpu.models.pnasnet import PNASNetA, PNASNetB
from pytorch_cifar_tpu.models.dla_simple import SimpleDLA
from pytorch_cifar_tpu.models.dla import DLA

MODEL_REGISTRY: Dict[str, Callable[..., nn.Module]] = {}


def register(name: str, factory: Callable[..., nn.Module]) -> None:
    MODEL_REGISTRY[name] = factory


def create_model(
    name: str, num_classes: int = 10, dtype: Optional[Any] = None, **kwargs
) -> nn.Module:
    if name not in MODEL_REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; available: {sorted(MODEL_REGISTRY)}"
        )
    return MODEL_REGISTRY[name](num_classes=num_classes, dtype=dtype, **kwargs)


def available_models():
    return sorted(MODEL_REGISTRY)


register("LeNet", LeNet)
register("ResNet18", ResNet18)
register("ResNet34", ResNet34)
register("ResNet50", ResNet50)
register("ResNet101", ResNet101)
register("ResNet152", ResNet152)
register("PreActResNet18", PreActResNet18)
register("PreActResNet34", PreActResNet34)
register("PreActResNet50", PreActResNet50)
register("PreActResNet101", PreActResNet101)
register("PreActResNet152", PreActResNet152)
register("VGG11", VGG11)
register("VGG13", VGG13)
register("VGG16", VGG16)
register("VGG19", VGG19)
register("MobileNet", MobileNet)
register("MobileNetV2", MobileNetV2)
register("SENet18", SENet18)
register("GoogLeNet", GoogLeNet)
register("DenseNet121", DenseNet121)
register("DenseNet169", DenseNet169)
register("DenseNet201", DenseNet201)
register("DenseNet161", DenseNet161)
register("DenseNetCifar", DenseNetCifar)
register("ResNeXt29_2x64d", ResNeXt29_2x64d)
register("ResNeXt29_4x64d", ResNeXt29_4x64d)
register("ResNeXt29_8x64d", ResNeXt29_8x64d)
register("ResNeXt29_32x4d", ResNeXt29_32x4d)
register("RegNetX_200MF", RegNetX_200MF)
register("RegNetX_400MF", RegNetX_400MF)
register("RegNetY_400MF", RegNetY_400MF)
register("DPN26", DPN26)
register("DPN92", DPN92)
register("ShuffleNetG2", ShuffleNetG2)
register("ShuffleNetG3", ShuffleNetG3)
register(
    "ShuffleNetV2_0.5",
    lambda num_classes=10, dtype=None, **kw: ShuffleNetV2(
        0.5, num_classes=num_classes, dtype=dtype, **kw
    ),
)
register(
    "ShuffleNetV2_1",
    lambda num_classes=10, dtype=None, **kw: ShuffleNetV2(
        1, num_classes=num_classes, dtype=dtype, **kw
    ),
)
register(
    "ShuffleNetV2_1.5",
    lambda num_classes=10, dtype=None, **kw: ShuffleNetV2(
        1.5, num_classes=num_classes, dtype=dtype, **kw
    ),
)
register(
    "ShuffleNetV2_2",
    lambda num_classes=10, dtype=None, **kw: ShuffleNetV2(
        2, num_classes=num_classes, dtype=dtype, **kw
    ),
)
register("EfficientNetB0", EfficientNetB0)
register("PNASNetA", PNASNetA)
register("PNASNetB", PNASNetB)
register("SimpleDLA", SimpleDLA)
register("DLA", DLA)
