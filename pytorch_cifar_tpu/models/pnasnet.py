"""PNASNet A/B for CIFAR-10 (reference: models/pnasnet.py:10-116).

Separable conv = depthwise conv with channel multiplier (groups=in_planes,
out_planes a multiple of in_planes) + BN, no pointwise stage and no
activation (models/pnasnet.py:10-21 — an intentional simplification of the
paper kept for parity). CellA: sep7x7 + maxpool3 branches, added
(models/pnasnet.py:33-38). CellB: (sep7x7+sep3x3) and (maxpool+sep5x5)
branch pairs, relu'd, concatenated, then 1x1-reduced
(models/pnasnet.py:56-69). Stride-2 cells add a 1x1+BN after the maxpool.
Layout: 6 cells / downsample x2 / 6 / downsample x4 / 6, then avg-pool 8 +
linear (models/pnasnet.py:80-86,100-108).

Golden param counts: PNASNetA 130,646 · PNASNetB 451,626.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Type

import jax.numpy as jnp
from flax import linen as nn

from pytorch_cifar_tpu.models.common import (
    BatchNorm,
    Conv,
    Dense,
    avg_pool,
    max_pool,
)


class SepConv(nn.Module):
    """Depthwise conv (channel multiplier out/in) + BN."""

    out_planes: int
    kernel_size: int
    stride: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        x = Conv(
            self.out_planes,
            self.kernel_size,
            strides=self.stride,
            padding=(self.kernel_size - 1) // 2,
            groups=x.shape[-1],
            use_bias=False,
            dtype=self.dtype,
        )(x)
        return BatchNorm(use_running_average=not train, dtype=self.dtype)(x)


class CellA(nn.Module):
    out_planes: int
    stride: int = 1
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        y1 = SepConv(self.out_planes, 7, self.stride, dtype=self.dtype)(x, train)
        y2 = max_pool(x, 3, stride=self.stride, padding=1)
        if self.stride == 2:
            y2 = Conv(self.out_planes, 1, use_bias=False, dtype=self.dtype)(y2)
            y2 = BatchNorm(use_running_average=not train, dtype=self.dtype)(y2)
        return nn.relu(y1 + y2)


class CellB(nn.Module):
    out_planes: int
    stride: int = 1
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        y1 = SepConv(self.out_planes, 7, self.stride, dtype=self.dtype)(x, train)
        y2 = SepConv(self.out_planes, 3, self.stride, dtype=self.dtype)(x, train)
        y3 = max_pool(x, 3, stride=self.stride, padding=1)
        if self.stride == 2:
            y3 = Conv(self.out_planes, 1, use_bias=False, dtype=self.dtype)(y3)
            y3 = BatchNorm(use_running_average=not train, dtype=self.dtype)(y3)
        y4 = SepConv(self.out_planes, 5, self.stride, dtype=self.dtype)(x, train)
        y = jnp.concatenate([nn.relu(y1 + y2), nn.relu(y3 + y4)], axis=-1)
        y = Conv(self.out_planes, 1, use_bias=False, dtype=self.dtype)(y)
        return nn.relu(BatchNorm(use_running_average=not train, dtype=self.dtype)(y))


class PNASNet(nn.Module):
    cell_type: Type[nn.Module]
    num_planes: int
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        p = self.num_planes
        x = Conv(p, 3, padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        for planes, downsample in (
            (p, False), (2 * p, True), (2 * p, False),
            (4 * p, True), (4 * p, False),
        ):
            if downsample:
                x = self.cell_type(planes, stride=2, dtype=self.dtype)(x, train)
            else:
                for _ in range(6):
                    x = self.cell_type(planes, stride=1, dtype=self.dtype)(x, train)
        x = avg_pool(x, 8)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)


def PNASNetA(num_classes: int = 10, dtype=None, **kw):
    return PNASNet(CellA, 44, num_classes=num_classes, dtype=dtype, **kw)


def PNASNetB(num_classes: int = 10, dtype=None, **kw):
    return PNASNet(CellB, 32, num_classes=num_classes, dtype=dtype, **kw)
