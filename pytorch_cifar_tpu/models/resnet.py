"""ResNet family for CIFAR-10 (reference: models/resnet.py:16-160).

CIFAR adaptations carried over from the reference contract: 3x3 stride-1
stem (no maxpool, models/resnet.py:102), stage widths 64/128/256/512 with
strides 1/2/2/2 (models/resnet.py:105-108), 4x4 average pool head
(models/resnet.py:127), single linear classifier.

TPU-first differences: NHWC layout; the reference's per-block ``autocast``
branches (models/resnet.py:38-51 — AMP plumbing duplicated through every
forward) collapse into the module-level ``dtype`` policy: pass
``dtype=jnp.bfloat16`` and every conv/BN computes in bf16 with fp32 params
and fp32 BN statistics. Golden param counts (BASELINE.md): ResNet18
11,173,962 · ResNet50 23,520,842 · ResNet152 58,156,618.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence

from flax import linen as nn

from pytorch_cifar_tpu.models.common import (
    BatchNorm,
    Conv,
    Dense,
    avg_pool,
)


class BasicBlock(nn.Module):
    """conv3x3-BN-ReLU-conv3x3-BN + projection shortcut, post-activation."""

    planes: int
    stride: int = 1
    dtype: Optional[Any] = None
    expansion = 1

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(Conv, use_bias=False, dtype=self.dtype)
        bn = partial(BatchNorm, use_running_average=not train, dtype=self.dtype)

        out = conv(self.planes, 3, strides=self.stride, padding=1)(x)
        out = nn.relu(bn()(out))
        out = conv(self.planes, 3, padding=1)(out)
        out = bn()(out)

        if self.stride != 1 or x.shape[-1] != self.expansion * self.planes:
            x = conv(self.expansion * self.planes, 1, strides=self.stride)(x)
            x = bn()(x)
        return nn.relu(out + x)


class Bottleneck(nn.Module):
    """1x1 reduce - 3x3 - 1x1 expand (x4), post-activation."""

    planes: int
    stride: int = 1
    dtype: Optional[Any] = None
    expansion = 4

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(Conv, use_bias=False, dtype=self.dtype)
        bn = partial(BatchNorm, use_running_average=not train, dtype=self.dtype)

        out = nn.relu(bn()(conv(self.planes, 1)(x)))
        out = nn.relu(bn()(conv(self.planes, 3, strides=self.stride, padding=1)(out)))
        out = bn()(conv(self.expansion * self.planes, 1)(out))

        if self.stride != 1 or x.shape[-1] != self.expansion * self.planes:
            x = conv(self.expansion * self.planes, 1, strides=self.stride)(x)
            x = bn()(x)
        return nn.relu(out + x)


class ResNet(nn.Module):
    block: Any
    num_blocks: Sequence[int]
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = Conv(64, 3, padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(
            BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
        )
        for planes, stride, n in zip(
            (64, 128, 256, 512), (1, 2, 2, 2), self.num_blocks
        ):
            for i in range(n):
                x = self.block(
                    planes, stride=stride if i == 0 else 1, dtype=self.dtype
                )(x, train)
        x = avg_pool(x, 4)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)


def ResNet18(num_classes=10, dtype=None):
    return ResNet(BasicBlock, (2, 2, 2, 2), num_classes, dtype)


def ResNet34(num_classes=10, dtype=None):
    return ResNet(BasicBlock, (3, 4, 6, 3), num_classes, dtype)


def ResNet50(num_classes=10, dtype=None):
    return ResNet(Bottleneck, (3, 4, 6, 3), num_classes, dtype)


def ResNet101(num_classes=10, dtype=None):
    return ResNet(Bottleneck, (3, 4, 23, 3), num_classes, dtype)


def ResNet152(num_classes=10, dtype=None):
    return ResNet(Bottleneck, (3, 8, 36, 3), num_classes, dtype)
