"""RegNet X/Y for CIFAR-10 (reference: models/regnet.py:12-143).

Residual bottleneck (ratio 1) with grouped 3x3 (groups = width/group_width,
models/regnet.py:36-38), optional SE between the grouped conv and projection
(Y variants, se width = round(w_in * 0.25), models/regnet.py:41-44 — note SE
width derives from the block *input* width, not the bottleneck width).
Projection shortcut on stride/width change (models/regnet.py:49-55). Stem
conv3x3(3->64); head adaptive avg-pool + linear (models/regnet.py:73-80,104).

Golden param counts: X_200MF 2,321,946 · X_400MF 4,779,338 · Y_400MF 5,714,362.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Mapping, Optional

import jax.numpy as jnp
from flax import linen as nn

from pytorch_cifar_tpu.models.common import (
    BatchNorm,
    Conv,
    Dense,
    global_avg_pool,
)


class SE(nn.Module):
    """Squeeze-excitation: global pool -> 1x1 reduce -> 1x1 expand -> sigmoid gate."""

    se_planes: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x):
        w = jnp.mean(x, axis=(1, 2), keepdims=True)
        w = nn.relu(Conv(self.se_planes, 1, dtype=self.dtype)(w))
        w = nn.sigmoid(Conv(x.shape[-1], 1, dtype=self.dtype)(w))
        return x * w


class RegNetBlock(nn.Module):
    w_out: int
    stride: int
    group_width: int
    bottleneck_ratio: float
    se_planes: int  # 0 disables SE
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        bn = partial(BatchNorm, use_running_average=not train, dtype=self.dtype)
        w_b = int(round(self.w_out * self.bottleneck_ratio))
        groups = w_b // self.group_width

        out = Conv(w_b, 1, use_bias=False, dtype=self.dtype)(x)
        out = nn.relu(bn()(out))
        out = Conv(w_b, 3, strides=self.stride, padding=1, groups=groups,
                   use_bias=False, dtype=self.dtype)(out)
        out = nn.relu(bn()(out))
        if self.se_planes > 0:
            out = SE(self.se_planes, dtype=self.dtype)(out)
        out = Conv(self.w_out, 1, use_bias=False, dtype=self.dtype)(out)
        out = bn()(out)

        if self.stride != 1 or x.shape[-1] != self.w_out:
            x = Conv(self.w_out, 1, strides=self.stride, use_bias=False,
                     dtype=self.dtype)(x)
            x = bn()(x)
        return nn.relu(out + x)


class RegNet(nn.Module):
    cfg: Mapping[str, Any]
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.cfg
        x = Conv(64, 3, padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        for depth, width, stride in zip(
            cfg["depths"], cfg["widths"], cfg["strides"]
        ):
            for i in range(depth):
                se_planes = (
                    int(round(x.shape[-1] * cfg["se_ratio"]))
                    if cfg["se_ratio"] > 0
                    else 0
                )
                x = RegNetBlock(
                    width,
                    stride if i == 0 else 1,
                    cfg["group_width"],
                    cfg["bottleneck_ratio"],
                    se_planes,
                    dtype=self.dtype,
                )(x, train)
        x = global_avg_pool(x)
        return Dense(self.num_classes, dtype=self.dtype)(x)


def RegNetX_200MF(num_classes: int = 10, dtype=None, **kw):
    cfg = {
        "depths": (1, 1, 4, 7),
        "widths": (24, 56, 152, 368),
        "strides": (1, 1, 2, 2),
        "group_width": 8,
        "bottleneck_ratio": 1,
        "se_ratio": 0,
    }
    return RegNet(cfg, num_classes=num_classes, dtype=dtype, **kw)


def RegNetX_400MF(num_classes: int = 10, dtype=None, **kw):
    cfg = {
        "depths": (1, 2, 7, 12),
        "widths": (32, 64, 160, 384),
        "strides": (1, 1, 2, 2),
        "group_width": 16,
        "bottleneck_ratio": 1,
        "se_ratio": 0,
    }
    return RegNet(cfg, num_classes=num_classes, dtype=dtype, **kw)


def RegNetY_400MF(num_classes: int = 10, dtype=None, **kw):
    cfg = {
        "depths": (1, 2, 7, 12),
        "widths": (32, 64, 160, 384),
        "strides": (1, 1, 2, 2),
        "group_width": 16,
        "bottleneck_ratio": 1,
        "se_ratio": 0.25,
    }
    return RegNet(cfg, num_classes=num_classes, dtype=dtype, **kw)
