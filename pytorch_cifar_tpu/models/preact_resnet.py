"""Pre-activation ResNet family (reference: models/preact_resnet.py:12-110).

BN-ReLU-conv ordering; the projection shortcut branches off the
*pre-activated* tensor and — unlike plain ResNet — has no BN of its own
(models/preact_resnet.py:23-26). The reference creates the shortcut
conditionally via ``hasattr`` (SURVEY.md §2.2); here the same condition is a
plain shape check at trace time. No final BN/ReLU before the head, matching
the reference forward (models/preact_resnet.py:85-94).

Golden param counts (BASELINE.md): PreActResNet18 11,171,146 ·
PreActResNet50 23.51M.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence

from flax import linen as nn

from pytorch_cifar_tpu.models.common import BatchNorm, Conv, Dense, avg_pool


class PreActBlock(nn.Module):
    planes: int
    stride: int = 1
    dtype: Optional[Any] = None
    expansion = 1

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(Conv, use_bias=False, dtype=self.dtype)
        bn = partial(BatchNorm, use_running_average=not train, dtype=self.dtype)

        pre = nn.relu(bn()(x))
        needs_proj = self.stride != 1 or x.shape[-1] != self.expansion * self.planes
        shortcut = (
            conv(self.expansion * self.planes, 1, strides=self.stride)(pre)
            if needs_proj
            else x
        )
        out = conv(self.planes, 3, strides=self.stride, padding=1)(pre)
        out = conv(self.planes, 3, padding=1)(nn.relu(bn()(out)))
        return out + shortcut


class PreActBottleneck(nn.Module):
    planes: int
    stride: int = 1
    dtype: Optional[Any] = None
    expansion = 4

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(Conv, use_bias=False, dtype=self.dtype)
        bn = partial(BatchNorm, use_running_average=not train, dtype=self.dtype)

        pre = nn.relu(bn()(x))
        needs_proj = self.stride != 1 or x.shape[-1] != self.expansion * self.planes
        shortcut = (
            conv(self.expansion * self.planes, 1, strides=self.stride)(pre)
            if needs_proj
            else x
        )
        out = conv(self.planes, 1)(pre)
        out = conv(self.planes, 3, strides=self.stride, padding=1)(
            nn.relu(bn()(out))
        )
        out = conv(self.expansion * self.planes, 1)(nn.relu(bn()(out)))
        return out + shortcut


class PreActResNet(nn.Module):
    block: Any
    num_blocks: Sequence[int]
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = Conv(64, 3, padding=1, use_bias=False, dtype=self.dtype)(x)
        for planes, stride, n in zip(
            (64, 128, 256, 512), (1, 2, 2, 2), self.num_blocks
        ):
            for i in range(n):
                x = self.block(
                    planes, stride=stride if i == 0 else 1, dtype=self.dtype
                )(x, train)
        x = avg_pool(x, 4)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)


def PreActResNet18(num_classes=10, dtype=None):
    return PreActResNet(PreActBlock, (2, 2, 2, 2), num_classes, dtype)


def PreActResNet34(num_classes=10, dtype=None):
    return PreActResNet(PreActBlock, (3, 4, 6, 3), num_classes, dtype)


def PreActResNet50(num_classes=10, dtype=None):
    return PreActResNet(PreActBottleneck, (3, 4, 6, 3), num_classes, dtype)


def PreActResNet101(num_classes=10, dtype=None):
    return PreActResNet(PreActBottleneck, (3, 4, 23, 3), num_classes, dtype)


def PreActResNet152(num_classes=10, dtype=None):
    return PreActResNet(PreActBottleneck, (3, 8, 36, 3), num_classes, dtype)
