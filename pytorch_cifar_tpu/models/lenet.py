"""LeNet-5 for CIFAR-10 (reference: models/lenet.py:5-23).

The only zoo model with no BatchNorm: 2 valid-padding 5x5 convs with bias,
each followed by ReLU + 2x2 maxpool, then three fully-connected layers
(400-120-84-10). 62,006 params (BASELINE.md golden).
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from pytorch_cifar_tpu.models.common import Conv, Dense, max_pool


class LeNet(nn.Module):
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(Conv(6, 5, dtype=self.dtype)(x))
        x = max_pool(x, 2)
        x = nn.relu(Conv(16, 5, dtype=self.dtype)(x))
        x = max_pool(x, 2)
        # NHWC flatten ordering differs from torch's NCHW, but the fc1 weight
        # is learned from scratch either way — only the 400-dim size matters.
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(Dense(120, dtype=self.dtype)(x))
        x = nn.relu(Dense(84, dtype=self.dtype)(x))
        return Dense(self.num_classes, dtype=self.dtype)(x)
