"""ShuffleNet V1 for CIFAR-10 (reference: models/shufflenet.py:10-100).

Grouped 1x1 -> channel shuffle -> depthwise 3x3 -> grouped 1x1 bottleneck
(models/shufflenet.py:41-48). Stride-2 blocks concat an avg-pool(3/s2/p1)
shortcut; stride-1 blocks add (models/shufflenet.py:37-39,47). Each stage's
first block therefore emits out_planes - in_planes channels
(models/shufflenet.py:70-71). The first bottleneck's 1x1s use groups=1
because the 24-channel stem width is not group-divisible
(models/shufflenet.py:28). Stem conv1x1(3->24); head avg-pool 4 + linear.

The reference is broken under Python 3 — ``mid_planes = out_planes/4`` is a
float (models/shufflenet.py:27, SURVEY.md §2.5.1); fixed here with integer
division. Golden param counts (measured with that fix): G2 887,582 ·
G3 862,768.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Mapping, Optional

import jax.numpy as jnp
from flax import linen as nn

from pytorch_cifar_tpu.models.common import (
    BatchNorm,
    Conv,
    Dense,
    avg_pool,
    channel_shuffle,
)


class ShuffleBottleneck(nn.Module):
    out_planes: int
    stride: int
    groups: int
    first_groups: int  # groups for the 1x1s; 1 on the stem-fed block
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        bn = partial(BatchNorm, use_running_average=not train, dtype=self.dtype)
        mid = self.out_planes // 4  # int division: the reference's Py3 fix
        g = self.first_groups

        out = Conv(mid, 1, groups=g, use_bias=False, dtype=self.dtype)(x)
        out = nn.relu(bn()(out))
        out = channel_shuffle(out, g)
        out = Conv(mid, 3, strides=self.stride, padding=1, groups=mid,
                   use_bias=False, dtype=self.dtype)(out)
        out = nn.relu(bn()(out))
        out = Conv(self.out_planes, 1, groups=self.groups, use_bias=False,
                   dtype=self.dtype)(out)
        out = bn()(out)

        if self.stride == 2:
            res = avg_pool(x, 3, stride=2, padding=1)
            return nn.relu(jnp.concatenate([out, res], axis=-1))
        return nn.relu(out + x)


class ShuffleNet(nn.Module):
    cfg: Mapping[str, Any]
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.cfg
        groups = cfg["groups"]
        x = Conv(24, 1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        in_planes = 24
        for out_planes, num_blocks in zip(cfg["out_planes"], cfg["num_blocks"]):
            for i in range(num_blocks):
                cat_planes = in_planes if i == 0 else 0
                x = ShuffleBottleneck(
                    out_planes - cat_planes,
                    stride=2 if i == 0 else 1,
                    groups=groups,
                    first_groups=1 if in_planes == 24 else groups,
                    dtype=self.dtype,
                )(x, train)
                in_planes = out_planes
        x = avg_pool(x, 4)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)


def ShuffleNetG2(num_classes: int = 10, dtype=None, **kw):
    cfg = {"out_planes": (200, 400, 800), "num_blocks": (4, 8, 4), "groups": 2}
    return ShuffleNet(cfg, num_classes=num_classes, dtype=dtype, **kw)


def ShuffleNetG3(num_classes: int = 10, dtype=None, **kw):
    cfg = {"out_planes": (240, 480, 960), "num_blocks": (4, 8, 4), "groups": 3}
    return ShuffleNet(cfg, num_classes=num_classes, dtype=dtype, **kw)
