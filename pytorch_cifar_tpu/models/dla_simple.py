"""SimpleDLA for CIFAR-10 (reference: models/dla_simple.py:16-116) — the
default model of the reference's single-node trainer (main.py:71).

Deep-layer aggregation with a binary Tree: left subtree at the stage stride,
right subtree fed from the left's output, aggregated by a Root
(concat + 1x1 conv + BN + ReLU, models/dla_simple.py:44-55,71-75). Blocks
are ResNet BasicBlocks. Stages: three conv3x3+BN+ReLU stems (16,16,32), then
Trees 64/l1/s1, 128/l2/s2, 256/l2/s2, 512/l1/s2, avg-pool 4 + linear
(models/dla_simple.py:81-103).

Golden param count: 15,142,970.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from pytorch_cifar_tpu.models.common import (
    BatchNorm,
    Conv,
    Dense,
    avg_pool,
)


class BasicBlock(nn.Module):
    """conv3x3-BN-ReLU-conv3x3-BN + projection shortcut (dla_simple.py:16-41)."""

    planes: int
    stride: int = 1
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        bn = partial(BatchNorm, use_running_average=not train, dtype=self.dtype)
        out = Conv(self.planes, 3, strides=self.stride, padding=1,
                   use_bias=False, dtype=self.dtype)(x)
        out = nn.relu(bn()(out))
        out = Conv(self.planes, 3, padding=1, use_bias=False, dtype=self.dtype)(out)
        out = bn()(out)
        if self.stride != 1 or x.shape[-1] != self.planes:
            x = Conv(self.planes, 1, strides=self.stride, use_bias=False,
                     dtype=self.dtype)(x)
            x = bn()(x)
        return nn.relu(out + x)


class Root(nn.Module):
    """concat -> 1x1 conv -> BN -> ReLU (dla_simple.py:44-55)."""

    out_channels: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, xs, train: bool):
        x = jnp.concatenate(xs, axis=-1)
        x = Conv(self.out_channels, 1, use_bias=False, dtype=self.dtype)(x)
        x = BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
        return nn.relu(x)


class Tree(nn.Module):
    """Binary aggregation tree (dla_simple.py:58-75), statically unrolled —
    levels are <= 2 so recursion depth is fixed at trace time."""

    out_channels: int
    level: int = 1
    stride: int = 1
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        if self.level == 1:
            out1 = BasicBlock(self.out_channels, self.stride, dtype=self.dtype)(
                x, train
            )
            out2 = BasicBlock(self.out_channels, 1, dtype=self.dtype)(out1, train)
        else:
            out1 = Tree(
                self.out_channels, self.level - 1, self.stride, dtype=self.dtype
            )(x, train)
            out2 = Tree(self.out_channels, self.level - 1, 1, dtype=self.dtype)(
                out1, train
            )
        return Root(self.out_channels, dtype=self.dtype)([out1, out2], train)


class SimpleDLA(nn.Module):
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        for width in (16, 16, 32):  # base, layer1, layer2
            x = Conv(width, 3, padding=1, use_bias=False, dtype=self.dtype)(x)
            x = nn.relu(
                BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
            )
        for out_ch, level, stride in (
            (64, 1, 1), (128, 2, 2), (256, 2, 2), (512, 1, 2)
        ):
            x = Tree(out_ch, level, stride, dtype=self.dtype)(x, train)
        x = avg_pool(x, 4)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)
