"""Dual Path Networks for CIFAR-10 (reference: models/dpn.py:7-89).

Each bottleneck emits out_planes+dense_depth channels; the first out_planes
are a residual path (added to the shortcut's first out_planes) and the tail
is a dense path concatenated onto both stacks
(torch.cat([x[:d]+out[:d], x[d:], out[d:]]), models/dpn.py:32-34). The
projection shortcut exists only on each stage's first block
(models/dpn.py:20-25); grouped 3x3 uses groups=32 everywhere
(models/dpn.py:15). Stem conv3x3(3->64)+BN+ReLU; head avg-pool 4 + linear
from out_planes[3]+(num_blocks[3]+1)*dense_depth[3] (models/dpn.py:44-51,67).

Golden param counts: DPN26 11,574,842 · DPN92 34,236,634.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Mapping, Optional

import jax.numpy as jnp
from flax import linen as nn

from pytorch_cifar_tpu.models.common import (
    BatchNorm,
    Conv,
    Dense,
    avg_pool,
)


class DualPathBlock(nn.Module):
    in_planes: int
    out_planes: int
    dense_depth: int
    stride: int
    first_layer: bool
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        bn = partial(BatchNorm, use_running_average=not train, dtype=self.dtype)
        out = Conv(self.in_planes, 1, use_bias=False, dtype=self.dtype)(x)
        out = nn.relu(bn()(out))
        out = Conv(self.in_planes, 3, strides=self.stride, padding=1,
                   groups=32, use_bias=False, dtype=self.dtype)(out)
        out = nn.relu(bn()(out))
        out = Conv(self.out_planes + self.dense_depth, 1, use_bias=False,
                   dtype=self.dtype)(out)
        out = bn()(out)

        if self.first_layer:
            x = Conv(self.out_planes + self.dense_depth, 1,
                     strides=self.stride, use_bias=False, dtype=self.dtype)(x)
            x = bn()(x)
        d = self.out_planes
        out = jnp.concatenate(
            [x[..., :d] + out[..., :d], x[..., d:], out[..., d:]], axis=-1
        )
        return nn.relu(out)


class DPN(nn.Module):
    cfg: Mapping[str, Any]
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.cfg
        x = Conv(64, 3, padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        for stage in range(4):
            stride = 1 if stage == 0 else 2
            for i in range(cfg["num_blocks"][stage]):
                x = DualPathBlock(
                    cfg["in_planes"][stage],
                    cfg["out_planes"][stage],
                    cfg["dense_depth"][stage],
                    stride if i == 0 else 1,
                    first_layer=i == 0,
                    dtype=self.dtype,
                )(x, train)
        x = avg_pool(x, 4)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)


_CFG_BASE = {
    "in_planes": (96, 192, 384, 768),
    "out_planes": (256, 512, 1024, 2048),
    "dense_depth": (16, 32, 24, 128),
}


def DPN26(num_classes: int = 10, dtype=None, **kw):
    cfg = dict(_CFG_BASE, num_blocks=(2, 2, 2, 2))
    return DPN(cfg, num_classes=num_classes, dtype=dtype, **kw)


def DPN92(num_classes: int = 10, dtype=None, **kw):
    cfg = dict(_CFG_BASE, num_blocks=(3, 4, 20, 3))
    return DPN(cfg, num_classes=num_classes, dtype=dtype, **kw)
