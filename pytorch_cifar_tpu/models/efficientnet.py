"""EfficientNet-B0 for CIFAR-10 (reference: models/efficientnet.py:12-164).

MBConv blocks: 1x1 expand (skipped when expand_ratio==1,
models/efficientnet.py:96) -> depthwise 3x3/5x5 -> SE (width = block *input*
channels * 0.25, models/efficientnet.py:80) -> 1x1 project, swish
activations. Skip connection when stride==1 and channels match, with
per-block stochastic depth whose rate scales linearly with block index
(drop_connect_rate * b / blocks, models/efficientnet.py:130). Head: global
avg-pool + dropout(0.2) + linear (models/efficientnet.py:145-150).

The reference's in-place ``drop_connect`` (models/efficientnet.py:16-22,
SURVEY.md §2.5.15) becomes a pure function drawing from the ``stochastic``
PRNG collection — plumbed by the train step (train/steps.py); eval and
init need no key. Golden param count: 3,599,686.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Mapping, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from pytorch_cifar_tpu.models.common import (
    BatchNorm,
    Conv,
    Dense,
    global_avg_pool,
)


def swish(x):
    return x * nn.sigmoid(x)


def drop_connect(rng, x, drop_rate: float):
    """Per-sample stochastic depth: keep with p=1-drop_rate, rescale kept."""
    keep = 1.0 - drop_rate
    mask = jax.random.bernoulli(rng, keep, shape=(x.shape[0], 1, 1, 1))
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class SE(nn.Module):
    """Squeeze-excitation with swish on the reduce conv."""

    se_channels: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x):
        w = jnp.mean(x, axis=(1, 2), keepdims=True)
        w = swish(Conv(self.se_channels, 1, dtype=self.dtype)(w))
        w = nn.sigmoid(Conv(x.shape[-1], 1, dtype=self.dtype)(w))
        return x * w


class MBConv(nn.Module):
    out_channels: int
    kernel_size: int
    stride: int
    expand_ratio: int
    se_ratio: float
    drop_rate: float
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        bn = partial(BatchNorm, use_running_average=not train, dtype=self.dtype)
        in_ch = x.shape[-1]
        channels = self.expand_ratio * in_ch

        # The reference *constructs* conv1/bn1 even when expand_ratio==1 but
        # skips them in forward (models/efficientnet.py:60-67 vs :96) — 1,088
        # dead params in block 0. Mirror that so golden counts match.
        if self.expand_ratio != 1:
            out = swish(bn()(Conv(channels, 1, use_bias=False, dtype=self.dtype)(x)))
        else:
            # pinned to running-average mode: no batch_stats mutation, and the
            # unused output is dead-code-eliminated by XLA
            dead = BatchNorm(use_running_average=True, dtype=self.dtype)
            _ = dead(Conv(channels, 1, use_bias=False, dtype=self.dtype)(x))
            out = x
        out = Conv(
            channels,
            self.kernel_size,
            strides=self.stride,
            padding=1 if self.kernel_size == 3 else 2,
            groups=channels,
            use_bias=False,
            dtype=self.dtype,
        )(out)
        out = swish(bn()(out))
        out = SE(int(in_ch * self.se_ratio), dtype=self.dtype)(out)
        out = Conv(self.out_channels, 1, use_bias=False, dtype=self.dtype)(out)
        out = bn()(out)

        if self.stride == 1 and in_ch == self.out_channels:
            if train and self.drop_rate > 0:
                out = drop_connect(
                    self.make_rng("stochastic"), out, self.drop_rate
                )
            out = out + x
        return out


class EfficientNet(nn.Module):
    cfg: Mapping[str, Any]
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.cfg
        x = Conv(32, 3, padding=1, use_bias=False, dtype=self.dtype)(x)
        x = swish(BatchNorm(use_running_average=not train, dtype=self.dtype)(x))

        b, blocks = 0, sum(cfg["num_blocks"])
        for expansion, out_ch, nblocks, ks, stride in zip(
            cfg["expansion"],
            cfg["out_channels"],
            cfg["num_blocks"],
            cfg["kernel_size"],
            cfg["stride"],
        ):
            for i in range(nblocks):
                x = MBConv(
                    out_ch,
                    ks,
                    stride if i == 0 else 1,
                    expansion,
                    se_ratio=0.25,
                    drop_rate=cfg["drop_connect_rate"] * b / blocks,
                    dtype=self.dtype,
                )(x, train)
                b += 1

        x = global_avg_pool(x)
        if train and cfg["dropout_rate"] > 0:
            x = nn.Dropout(rate=cfg["dropout_rate"], deterministic=False,
                           rng_collection="stochastic")(x)
        return Dense(self.num_classes, dtype=self.dtype)(x)


def EfficientNetB0(num_classes: int = 10, dtype=None, **kw):
    cfg = {
        "num_blocks": (1, 2, 2, 3, 3, 4, 1),
        "expansion": (1, 6, 6, 6, 6, 6, 6),
        "out_channels": (16, 24, 40, 80, 112, 192, 320),
        "kernel_size": (3, 3, 5, 3, 5, 5, 3),
        "stride": (1, 2, 2, 2, 1, 2, 1),
        "dropout_rate": 0.2,
        "drop_connect_rate": 0.2,
    }
    return EfficientNet(cfg, num_classes=num_classes, dtype=dtype, **kw)
