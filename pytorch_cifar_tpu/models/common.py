"""Shared building blocks for the model zoo.

All models are flax.linen Modules in **NHWC** layout (XLA:TPU's preferred
layout; the reference is NCHW but layout is free to change — SURVEY.md §7.6).
Every model maps ``(N, 32, 32, 3) float -> (N, 10)`` logits, the NHWC
equivalent of the reference contract (SURVEY.md §1 L2).

Initializers reproduce PyTorch *defaults* (the reference relies on them —
its own ``init_params`` helper is dead code, utils.py:30-43 / SURVEY.md
§2.5.3), so accuracy curves are comparable:

- Conv2d default: kaiming_uniform(a=sqrt(5)) == U(-b, b), b = 1/sqrt(fan_in),
  fan_in = kh*kw*in_ch/groups; bias U(-b, b) with the same fan_in.
- Linear default: U(-b, b), b = 1/sqrt(in_features) for weight and bias.
- BatchNorm: scale=1, bias=0, running stats (0, 1).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from flax import linen as nn

Dtype = Any

# ---------------------------------------------------------------------------
# Cross-replica (Sync) BatchNorm context
# ---------------------------------------------------------------------------
#
# The reference has no SyncBN anywhere — under DDP each rank normalizes its
# local shard (SURVEY.md §7.2), and that stays our default for parity. This
# context enables the cross-replica extension the survey anticipates: inside
# ``with sync_batchnorm(axis)``, every BatchNorm in the traced model pmeans
# its batch moments over the mesh axis, so normalization uses GLOBAL batch
# statistics (equivalent to single-device BN over the full global batch).
# A trace-time context instead of a module attribute so none of the 19 model
# files change; the flag is baked into the jitted step at trace time
# (make_train_step(sync_bn=True)).

import contextlib
import contextvars

_SYNC_BN_AXIS: contextvars.ContextVar = contextvars.ContextVar(
    "sync_bn_axis", default=None
)


@contextlib.contextmanager
def sync_batchnorm(axis_name: Optional[str]):
    """Trace-time context: BatchNorms psum batch moments over ``axis_name``.

    The contextvar is process-global trace-time state: tracing two models
    concurrently from different threads while one holds this context could
    leak the axis into the other trace. Fine here — the framework traces
    single-threaded (one jitted step per Trainer) — but keep it in mind if
    embedding these modules in a multi-threaded tracing harness.
    """
    token = _SYNC_BN_AXIS.set(axis_name)
    try:
        yield
    finally:
        _SYNC_BN_AXIS.reset(token)


# ---------------------------------------------------------------------------
# PyTorch-default initializers
# ---------------------------------------------------------------------------


def torch_conv_kernel_init(key, shape, dtype=jnp.float32):
    """U(-1/sqrt(fan_in), 1/sqrt(fan_in)); flax kernel shape (kh, kw, cin/g, cout)."""
    fan_in = shape[0] * shape[1] * shape[2]
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, -bound, bound)


def torch_conv_bias_init(fan_in: int):
    bound = 1.0 / math.sqrt(fan_in)

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


def torch_linear_kernel_init(key, shape, dtype=jnp.float32):
    """U(-1/sqrt(in_features), ...); flax dense kernel shape (in, out)."""
    bound = 1.0 / math.sqrt(shape[0])
    return jax.random.uniform(key, shape, dtype, -bound, bound)


def torch_linear_bias_init(in_features: int):
    bound = 1.0 / math.sqrt(in_features)

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


# ---------------------------------------------------------------------------
# Layers
# ---------------------------------------------------------------------------


# Trace-time switch: compute grouped convs as block-diagonal DENSE convs.
# Narrow channel groups (ResNeXt's 32 groups of 4-16 channels) starve the
# 128-wide MXU lanes under the native grouped lowering; expanding the
# kernel to a zero-padded dense one spends redundant FLOPs to reclaim
# lanes. Numerically identical (the extra terms are exact zeros).
# Measured on the v5e (BENCHMARKS.md round 2): ResNeXt29_32x4d
# 6.9k -> 7.4k img/s (+6%); DEPTHWISE convs (channels-per-group 1,
# PNASNet/MobileNet) are 14x WORSE dense (12.7k -> 0.9k) — the FLOP
# explosion dwarfs the lane recovery — so the gate below excludes them.
_DENSE_GROUPED: contextvars.ContextVar = contextvars.ContextVar(
    "dense_grouped_conv", default=False
)


@contextlib.contextmanager
def dense_grouped_conv(enable: bool = True):
    token = _DENSE_GROUPED.set(enable)
    try:
        yield
    finally:
        _DENSE_GROUPED.reset(token)


def set_dense_grouped_conv(enable: bool) -> None:
    """Non-scoped setter for long-lived processes (the Trainer sets this
    from --dense_grouped_conv BEFORE any step is traced; jit traces lazily
    at first call, so a with-block around step construction would not
    cover the actual trace)."""
    _DENSE_GROUPED.set(enable)


class _TorchGroupedConv(nn.Conv):
    """nn.Conv whose grouped path can expand to a block-diagonal dense conv.

    Same parameter name/shape/init as nn.Conv (the module is instantiated
    with an explicit ``name`` so the param tree is identical either way);
    only the computation changes under ``dense_grouped_conv()``.
    """

    @nn.compact
    def __call__(self, x):
        # This override implements only the slice of nn.Conv's surface the
        # zoo uses; anything else must fail LOUDLY here rather than be
        # silently ignored (e.g. a dilation computing an undilated conv).
        if not (
            isinstance(self.padding, (list, tuple))
            and all(
                isinstance(p, (list, tuple)) and len(p) == 2
                for p in self.padding
            )
        ):
            raise NotImplementedError(
                "_TorchGroupedConv requires explicit [(low, high), ...] "
                f"padding, got {self.padding!r} (string modes like 'SAME' "
                "are not handled by this override)"
            )
        def unit(d):
            if d is None or d == 1:
                return True
            try:
                return all(int(v) == 1 for v in d)
            except TypeError:
                return False

        if (
            not unit(self.kernel_dilation)
            or not unit(self.input_dilation)
            or self.mask is not None
        ):
            raise NotImplementedError(
                "_TorchGroupedConv does not implement dilation or masking"
            )
        g = self.feature_group_count
        cin = x.shape[-1]
        cpg = cin // g
        kh, kw = self.kernel_size
        kernel = self.param(
            "kernel",
            self.kernel_init,
            (kh, kw, cpg, self.features),
            self.param_dtype,
        )
        bias = (
            self.param(
                "bias", self.bias_init, (self.features,), self.param_dtype
            )
            if self.use_bias
            else None
        )
        x, kernel, bias = nn.dtypes.promote_dtype(
            x, kernel, bias, dtype=self.dtype
        )
        if _DENSE_GROUPED.get() and g > 1 and 1 < cpg <= 16:
            # block-diagonal expansion: dense[ky,kx, h*cpg+r, j*opg+o] =
            # kernel[ky,kx,r,j*opg+o] iff h == j (torch group layout:
            # group-major channel order on both sides)
            opg = self.features // g
            w5 = kernel.reshape(kh, kw, cpg, g, opg)
            eye = jnp.eye(g, dtype=kernel.dtype)
            dense = jnp.einsum("xyrgo,hg->xyhrgo", w5, eye)
            kernel = dense.reshape(kh, kw, cin, g * opg)
            g = 1
        out = jax.lax.conv_general_dilated(
            x,
            kernel,
            window_strides=self.strides,
            padding=list(self.padding),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=g,
            precision=self.precision,
        )
        if bias is not None:
            out = out + bias
        return out


class Conv(nn.Module):
    """2D conv with PyTorch-default init and PyTorch-style int padding.

    ``padding=p`` means p pixels of zero padding on every side (torch
    semantics), not SAME/VALID.
    """

    features: int
    kernel_size: Union[int, Tuple[int, int]]
    strides: int = 1
    padding: int = 0
    groups: int = 1
    use_bias: bool = True
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x):
        ks = (
            (self.kernel_size, self.kernel_size)
            if isinstance(self.kernel_size, int)
            else tuple(self.kernel_size)
        )
        in_ch = x.shape[-1]
        fan_in = ks[0] * ks[1] * (in_ch // self.groups)
        return _TorchGroupedConv(
            features=self.features,
            kernel_size=ks,
            strides=(self.strides, self.strides),
            padding=[(self.padding, self.padding)] * 2,
            feature_group_count=self.groups,
            use_bias=self.use_bias,
            kernel_init=torch_conv_kernel_init,
            bias_init=torch_conv_bias_init(fan_in),
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="Conv_0",  # keep the nn.Conv param path: .../Conv_0/kernel
        )(x)


class _ConvParamLeaf(nn.Module):
    """Creates nn.Conv-identical ``kernel``/``bias`` params without
    convolving; the innermost half of :class:`ConvParams`."""

    features: int
    ks: Tuple[int, int]
    in_ch: int
    use_bias: bool = True

    @nn.compact
    def __call__(self):
        fan_in = self.ks[0] * self.ks[1] * self.in_ch
        kernel = self.param(
            "kernel",
            torch_conv_kernel_init,
            (self.ks[0], self.ks[1], self.in_ch, self.features),
            jnp.float32,
        )
        bias = (
            self.param(
                "bias", torch_conv_bias_init(fan_in), (self.features,),
                jnp.float32,
            )
            if self.use_bias
            else None
        )
        return kernel, bias


class ConvParams(nn.Module):
    """Param-path twin of :class:`Conv` (ungrouped): creates
    ``.../<name>/Conv_0/{kernel,bias}`` with identical shapes and init but
    returns the arrays instead of convolving.

    Lets a caller execute several same-input convs as ONE wider conv while
    keeping the param tree bit-identical to the stock modules — each output
    channel of a conv is an independent dot product over the input, so
    ``conv(x, concat(k1, k2))`` equals ``concat(conv(x, k1), conv(x, k2))``
    exactly. Used by GoogLeNet's merged-branch Inception path
    (models/googlenet.py); init values match the stock path because flax
    derives param RNG keys from the scope path, which is unchanged.
    """

    features: int
    kernel_size: Union[int, Tuple[int, int]]
    in_ch: int
    use_bias: bool = True

    @nn.compact
    def __call__(self):
        ks = (
            (self.kernel_size, self.kernel_size)
            if isinstance(self.kernel_size, int)
            else tuple(self.kernel_size)
        )
        return _ConvParamLeaf(
            self.features, ks, self.in_ch, self.use_bias, name="Conv_0"
        )()


class Dense(nn.Module):
    """Linear layer with PyTorch-default init."""

    features: int
    use_bias: bool = True
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x):
        return nn.Dense(
            features=self.features,
            use_bias=self.use_bias,
            kernel_init=torch_linear_kernel_init,
            bias_init=torch_linear_bias_init(x.shape[-1]),
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )(x)


# Pluggable batch-moments implementation: fn(x) -> (E[x], E[x^2]) in fp32.
# None -> the inline twin-reduce below. Experiment hook for fused Pallas
# moment kernels (ops/bn_stats.py, tools/bn_bench.py) — a trace-time switch
# like sync_batchnorm, so no model file changes.
_BN_MOMENTS_IMPL: contextvars.ContextVar = contextvars.ContextVar(
    "bn_moments_impl", default=None
)


@contextlib.contextmanager
def bn_moments_impl(fn):
    token = _BN_MOMENTS_IMPL.set(fn)
    try:
        yield
    finally:
        _BN_MOMENTS_IMPL.reset(token)


def bn_batch_moments(x):
    """Per-channel batch ``(E[x], E[x^2])`` in fp32 — the quantities every
    BatchNorm reduces, honoring a ``_BN_MOMENTS_IMPL`` override when one is
    active. The single source for BN moment numerics: BatchNorm's inline
    path and DenseNet's shared-stats chunk moments both call this, so the
    two can never drift."""
    impl = _BN_MOMENTS_IMPL.get()
    if impl is not None:
        return impl(x)
    # at-least-fp32: bf16 inputs accumulate in fp32; f64 inputs (the x64
    # trajectory-parity harness, tests/test_torch_parity.py) stay f64
    xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    axes = tuple(range(x.ndim - 1))
    return jnp.mean(xf, axis=axes), jnp.mean(jnp.square(xf), axis=axes)


class BatchNorm(nn.Module):
    """BatchNorm with torch-exact BatchNorm2d semantics.

    torch (reference, every zoo model): eps=1e-5, momentum=0.1
    (new = 0.9*old + 0.1*batch), affine; normalization uses the **biased**
    batch variance while the running-average update uses the **unbiased**
    one (Bessel n/(n-1)). flax's nn.BatchNorm updates running var with the
    *biased* variance — a systematic (n-1)/n understatement of the running
    stats vs the reference at per-device batch n — so the update is
    implemented inline here instead of delegating.

    Stats live in the ``batch_stats`` collection under the same ``mean`` /
    ``var`` names flax uses. NOTE: the tree is one level flatter than the
    earlier delegating version (``.../BatchNorm_0/{scale,bias}``, no nested
    module) — checkpoints written before this change do not restore.
    Statistics are computed in fp32; the normalization itself is folded into
    a per-channel FMA applied in the compute dtype so XLA fuses it into the
    surrounding convs.
    """

    use_running_average: Optional[bool] = None
    dtype: Optional[Dtype] = None
    momentum: float = 0.1  # torch convention: weight of the NEW batch stat
    epsilon: float = 1e-5

    @nn.compact
    def __call__(
        self,
        x,
        use_running_average: Optional[bool] = None,
        moments=None,
    ):
        """``moments``: optional precomputed ``(E[x], E[x^2])`` per-channel
        fp32 vectors. BN statistics are per-channel, so a caller that
        already knows them — DenseNet's shared-stats path, where the
        growing concat's moments are the concatenation of each chunk's
        moments computed once at creation — can skip this layer's reduce
        over the full input. Semantically identical to computing them
        here (autodiff flows through the provided values); ignored in
        eval mode and during init."""
        ura = nn.merge_param(
            "use_running_average", self.use_running_average, use_running_average
        )
        features = x.shape[-1]
        scale = self.param(
            "scale", nn.initializers.ones, (features,), jnp.float32
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (features,), jnp.float32
        )
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((features,), jnp.float32)
        )

        if ura:
            mean, var = ra_mean.value, ra_var.value
        else:
            axes = tuple(range(x.ndim - 1))
            if moments is not None and not self.is_initializing():
                mean, sq = moments
            elif not self.is_initializing():
                mean, sq = bn_batch_moments(x)
            else:
                xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
                mean = jnp.mean(xf, axis=axes)
                sq = jnp.mean(jnp.square(xf), axis=axes)
            world = 1
            sync_axis = _SYNC_BN_AXIS.get()
            if sync_axis is not None and not self.is_initializing():
                # cross-replica moments: with equal shard sizes the pmean of
                # per-shard E[x], E[x^2] is exactly the global moments
                mean = jax.lax.pmean(mean, sync_axis)
                sq = jax.lax.pmean(sq, sync_axis)
                world = jax.lax.psum(1, sync_axis)
            # one-pass biased variance normalizes the batch (torch
            # F.batch_norm); E[x^2]-E[x]^2 keeps it a single fused reduction
            # clamp: catastrophic cancellation can push the one-pass result
            # a hair negative for high-mean/low-var channels, and rsqrt of
            # (negative + eps) would NaN the step
            var = jnp.maximum(sq - jnp.square(mean), 0.0)
            if not self.is_initializing():
                n = 1
                for d in axes:
                    n *= x.shape[d]
                n = n * world  # global sample count under SyncBN
                unbiased = var * (n / jnp.maximum(n - 1, 1))
                m = self.momentum
                ra_mean.value = (1.0 - m) * ra_mean.value + m * mean
                ra_var.value = (1.0 - m) * ra_var.value + m * unbiased

        # fold normalization + affine into one per-channel FMA: the scalar
        # algebra stays fp32, the elementwise pass runs in the compute dtype
        # (the bf16 policy's activation dtype), so XLA fuses it into the
        # surrounding convs like any other epilogue
        mul = scale * jax.lax.rsqrt(var + self.epsilon)
        add = bias - mean * mul
        out_dtype = self.dtype or x.dtype
        return (
            x.astype(out_dtype) * mul.astype(out_dtype) + add.astype(out_dtype)
        )


def max_pool(x, window: int, stride: Optional[int] = None, padding: int = 0):
    stride = stride or window
    return nn.max_pool(
        x,
        window_shape=(window, window),
        strides=(stride, stride),
        padding=[(padding, padding)] * 2,
    )


def avg_pool(x, window: int, stride: Optional[int] = None, padding: int = 0):
    stride = stride or window
    return nn.avg_pool(
        x,
        window_shape=(window, window),
        strides=(stride, stride),
        padding=[(padding, padding)] * 2,
    )


def global_avg_pool(x):
    """adaptive_avg_pool2d(1) + flatten, NHWC."""
    return jnp.mean(x, axis=(1, 2))


def channel_shuffle(x, groups: int):
    """ShuffleNet channel shuffle, NHWC: C -> (g, C/g) -> transpose -> C.

    Matches the reference's view/permute/reshape on the channel axis
    (models/shufflenet.py:15-19, models/shufflenetv2.py:15-19).
    """
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, groups, c // groups)
    x = jnp.swapaxes(x, -1, -2)
    return x.reshape(n, h, w, c)


def count_params(params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))
