"""Shared building blocks for the model zoo.

All models are flax.linen Modules in **NHWC** layout (XLA:TPU's preferred
layout; the reference is NCHW but layout is free to change — SURVEY.md §7.6).
Every model maps ``(N, 32, 32, 3) float -> (N, 10)`` logits, the NHWC
equivalent of the reference contract (SURVEY.md §1 L2).

Initializers reproduce PyTorch *defaults* (the reference relies on them —
its own ``init_params`` helper is dead code, utils.py:30-43 / SURVEY.md
§2.5.3), so accuracy curves are comparable:

- Conv2d default: kaiming_uniform(a=sqrt(5)) == U(-b, b), b = 1/sqrt(fan_in),
  fan_in = kh*kw*in_ch/groups; bias U(-b, b) with the same fan_in.
- Linear default: U(-b, b), b = 1/sqrt(in_features) for weight and bias.
- BatchNorm: scale=1, bias=0, running stats (0, 1).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from flax import linen as nn

Dtype = Any

# ---------------------------------------------------------------------------
# PyTorch-default initializers
# ---------------------------------------------------------------------------


def torch_conv_kernel_init(key, shape, dtype=jnp.float32):
    """U(-1/sqrt(fan_in), 1/sqrt(fan_in)); flax kernel shape (kh, kw, cin/g, cout)."""
    fan_in = shape[0] * shape[1] * shape[2]
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, -bound, bound)


def torch_conv_bias_init(fan_in: int):
    bound = 1.0 / math.sqrt(fan_in)

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


def torch_linear_kernel_init(key, shape, dtype=jnp.float32):
    """U(-1/sqrt(in_features), ...); flax dense kernel shape (in, out)."""
    bound = 1.0 / math.sqrt(shape[0])
    return jax.random.uniform(key, shape, dtype, -bound, bound)


def torch_linear_bias_init(in_features: int):
    bound = 1.0 / math.sqrt(in_features)

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


# ---------------------------------------------------------------------------
# Layers
# ---------------------------------------------------------------------------


class Conv(nn.Module):
    """2D conv with PyTorch-default init and PyTorch-style int padding.

    ``padding=p`` means p pixels of zero padding on every side (torch
    semantics), not SAME/VALID.
    """

    features: int
    kernel_size: Union[int, Tuple[int, int]]
    strides: int = 1
    padding: int = 0
    groups: int = 1
    use_bias: bool = True
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x):
        ks = (
            (self.kernel_size, self.kernel_size)
            if isinstance(self.kernel_size, int)
            else tuple(self.kernel_size)
        )
        in_ch = x.shape[-1]
        fan_in = ks[0] * ks[1] * (in_ch // self.groups)
        return nn.Conv(
            features=self.features,
            kernel_size=ks,
            strides=(self.strides, self.strides),
            padding=[(self.padding, self.padding)] * 2,
            feature_group_count=self.groups,
            use_bias=self.use_bias,
            kernel_init=torch_conv_kernel_init,
            bias_init=torch_conv_bias_init(fan_in),
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )(x)


class Dense(nn.Module):
    """Linear layer with PyTorch-default init."""

    features: int
    use_bias: bool = True
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x):
        return nn.Dense(
            features=self.features,
            use_bias=self.use_bias,
            kernel_init=torch_linear_kernel_init,
            bias_init=torch_linear_bias_init(x.shape[-1]),
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )(x)


class BatchNorm(nn.Module):
    """BatchNorm matching torch BatchNorm2d defaults.

    torch: eps=1e-5, momentum=0.1 (new = 0.9*old + 0.1*batch), affine, biased
    batch variance for normalization. flax BatchNorm momentum is the *keep*
    factor, so torch momentum 0.1 == flax momentum 0.9.

    Stats live in the ``batch_stats`` collection (the functional equivalent of
    torch running buffers); they are parameters of neither count nor training.
    Stats and normalization run in fp32 regardless of compute dtype.
    """

    use_running_average: Optional[bool] = None
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        ura = nn.merge_param(
            "use_running_average", self.use_running_average, use_running_average
        )
        return nn.BatchNorm(
            use_running_average=ura,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )(x)


def max_pool(x, window: int, stride: Optional[int] = None, padding: int = 0):
    stride = stride or window
    return nn.max_pool(
        x,
        window_shape=(window, window),
        strides=(stride, stride),
        padding=[(padding, padding)] * 2,
    )


def avg_pool(x, window: int, stride: Optional[int] = None, padding: int = 0):
    stride = stride or window
    return nn.avg_pool(
        x,
        window_shape=(window, window),
        strides=(stride, stride),
        padding=[(padding, padding)] * 2,
    )


def global_avg_pool(x):
    """adaptive_avg_pool2d(1) + flatten, NHWC."""
    return jnp.mean(x, axis=(1, 2))


def channel_shuffle(x, groups: int):
    """ShuffleNet channel shuffle, NHWC: C -> (g, C/g) -> transpose -> C.

    Matches the reference's view/permute/reshape on the channel axis
    (models/shufflenet.py:15-19, models/shufflenetv2.py:15-19).
    """
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, groups, c // groups)
    x = jnp.swapaxes(x, -1, -2)
    return x.reshape(n, h, w, c)


def count_params(params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))
