"""MobileNetV1 for CIFAR-10 (reference: models/mobilenet.py:11-58).

Depthwise-separable blocks: 3x3 depthwise (groups=channels,
models/mobilenet.py:15) + 1x1 pointwise, each conv-BN-ReLU. Stem conv3x3
stride 1 to 32ch (models/mobilenet.py:33); width/stride plan from the cfg
list (models/mobilenet.py:28); 2x2 average-pool head then 1024->classes
linear (models/mobilenet.py:50-53).

Depthwise convs on TPU use ``feature_group_count`` (SURVEY.md §7.6 hard part
#3); XLA lowers them to vector-unit ops rather than MXU matmuls, which is
the expected behavior for this family. Golden param count: 3,217,226.
"""

from __future__ import annotations

from typing import Any, Optional

from flax import linen as nn

from pytorch_cifar_tpu.models.common import BatchNorm, Conv, Dense, avg_pool

# int = (planes, stride 1); tuple = (planes, stride)
_CFG = (64, (128, 2), 128, (256, 2), 256, (512, 2), 512, 512, 512, 512, 512,
        (1024, 2), 1024)


class DepthwiseSeparable(nn.Module):
    """3x3 depthwise + 1x1 pointwise, each followed by BN-ReLU."""

    planes: int
    stride: int = 1
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        in_ch = x.shape[-1]
        bn = lambda: BatchNorm(use_running_average=not train, dtype=self.dtype)
        x = Conv(in_ch, 3, strides=self.stride, padding=1, groups=in_ch,
                 use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(bn()(x))
        x = Conv(self.planes, 1, use_bias=False, dtype=self.dtype)(x)
        return nn.relu(bn()(x))


class MobileNet(nn.Module):
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = Conv(32, 3, padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        for item in _CFG:
            planes, stride = (item, 1) if isinstance(item, int) else item
            x = DepthwiseSeparable(planes, stride, dtype=self.dtype)(x, train)
        x = avg_pool(x, 2)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)
