"""VGG11/13/16/19 for CIFAR-10 (reference: models/vgg.py:6-40).

Config-list driven conv3x3(+bias)-BN-ReLU stacks; ``'M'`` entries are 2x2
stride-2 max pools (models/vgg.py:29-37); a single 512->num_classes linear
head (models/vgg.py:18). The reference's trailing AvgPool2d(kernel=1,
stride=1) (models/vgg.py:38) is an identity op and is dropped here. NHWC,
module-level dtype policy instead of no mixed-precision support.

Golden param counts: VGG11 9,231,114 · VGG13 9,416,010 · VGG16 14,728,266 ·
VGG19 20,040,522.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

from flax import linen as nn

from pytorch_cifar_tpu.models.common import BatchNorm, Conv, Dense, max_pool

CFG = {
    "VGG11": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "VGG13": (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512,
              512, "M"),
    "VGG16": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
              "M", 512, 512, 512, "M"),
    "VGG19": (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
              512, 512, "M", 512, 512, 512, 512, "M"),
}


class VGG(nn.Module):
    cfg: Sequence[Union[int, str]]
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        for item in self.cfg:
            if item == "M":
                x = max_pool(x, 2)
            else:
                x = Conv(item, 3, padding=1, dtype=self.dtype)(x)
                x = BatchNorm(use_running_average=not train, dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)


def _factory(name):
    def make(num_classes=10, dtype=None):
        return VGG(CFG[name], num_classes, dtype)

    make.__name__ = name
    return make


VGG11 = _factory("VGG11")
VGG13 = _factory("VGG13")
VGG16 = _factory("VGG16")
VGG19 = _factory("VGG19")
