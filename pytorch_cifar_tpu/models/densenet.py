"""DenseNet for CIFAR-10 (reference: models/densenet.py:9-99).

Pre-activation bottleneck layers (BN-ReLU-conv1x1(4g) -> BN-ReLU-conv3x3(g))
whose output is concatenated *in front of* the running feature stack
(torch.cat([out, x]), models/densenet.py:20 — order preserved here so BN
channel statistics line up). Transitions halve channels (floor(planes*0.5),
models/densenet.py:46) and avg-pool 2x. Stem conv3x3 to 2*growth; head
BN-ReLU-avgpool4-linear (models/densenet.py:81-83). All convs bias-free.

Golden param counts: DenseNet121 6,956,298 · DenseNet169 12,493,322 ·
DenseNet201 18,104,330 · DenseNet161 26,482,378 · densenet_cifar 1,000,618.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Optional, Sequence

import jax.numpy as jnp
from flax import linen as nn

from pytorch_cifar_tpu.models.common import (
    BatchNorm,
    Conv,
    Dense,
    avg_pool,
)


def _chunk_moments(x):
    """Per-channel (E[x], E[x^2]) of one produced feature chunk, computed
    ONCE on the shared-stats path and reused by every later BN whose input
    contains the chunk. Delegates to the shared BN moments helper so the
    numerics (and any _BN_MOMENTS_IMPL override) cannot drift from the
    per-layer path."""
    from pytorch_cifar_tpu.models.common import bn_batch_moments

    return bn_batch_moments(x)


class DenseLayer(nn.Module):
    growth_rate: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool, moments=None):
        """``moments``: running per-channel (E[x], E[x^2]) of ``x`` on the
        shared-stats path; returns (concat, updated moments) when given.
        BN stats are per-channel and channels partition into the chunks
        that produced them, so concatenated chunk moments ARE the concat's
        moments — exactly, not approximately."""
        bn = partial(BatchNorm, use_running_average=not train, dtype=self.dtype)
        out = nn.relu(bn()(x, moments=moments))
        out = Conv(4 * self.growth_rate, 1, use_bias=False, dtype=self.dtype)(out)
        out = nn.relu(bn()(out))
        out = Conv(self.growth_rate, 3, padding=1, use_bias=False, dtype=self.dtype)(out)
        if moments is None:
            return jnp.concatenate([out, x], axis=-1)
        m, sq = _chunk_moments(out)
        new_moments = (
            jnp.concatenate([m, moments[0]]),
            jnp.concatenate([sq, moments[1]]),
        )
        return jnp.concatenate([out, x], axis=-1), new_moments


class Transition(nn.Module):
    out_planes: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool, moments=None):
        x = nn.relu(
            BatchNorm(use_running_average=not train, dtype=self.dtype)(
                x, moments=moments
            )
        )
        x = Conv(self.out_planes, 1, use_bias=False, dtype=self.dtype)(x)
        return avg_pool(x, 2)


class DenseNet(nn.Module):
    """``shared_stats`` (train-mode only, DEFAULT ON) computes each
    produced chunk's BN moments once and reuses them in every later layer
    whose BN covers the chunk, eliminating the per-layer reduce over the
    growing prefix — the round-1-profiled dominant HBM cost of this
    family. The parameter/stat tree and the math are unchanged
    (per-channel moments concatenate exactly — outputs, gradients, and
    running-stat updates are pinned equal to the stock path in CI); only
    reduce scheduling differs. Measured on the v5e: DenseNet121 b512 bf16
    79.4 -> 64.6 ms/step (+23%, BENCHMARKS.md round 3). Pass
    ``shared_stats=False`` to restore the literal per-layer reduce."""

    nblocks: Sequence[int]
    growth_rate: int = 12
    reduction: float = 0.5
    num_classes: int = 10
    dtype: Optional[Any] = None
    shared_stats: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        g = self.growth_rate
        planes = 2 * g
        shared = self.shared_stats and train
        x = Conv(planes, 3, padding=1, use_bias=False, dtype=self.dtype)(x)
        moments = _chunk_moments(x) if shared else None
        for stage, nblock in enumerate(self.nblocks):
            for _ in range(nblock):
                if shared:
                    x, moments = DenseLayer(g, dtype=self.dtype)(
                        x, train, moments=moments
                    )
                else:
                    x = DenseLayer(g, dtype=self.dtype)(x, train)
            planes += nblock * g
            if stage < len(self.nblocks) - 1:
                planes = int(math.floor(planes * self.reduction))
                x = Transition(planes, dtype=self.dtype)(
                    x, train, moments=moments
                )
                # the transition's conv+pool output is a fresh tensor: the
                # stack (and its moments) restart from one new chunk
                moments = _chunk_moments(x) if shared else None
        x = nn.relu(
            BatchNorm(use_running_average=not train, dtype=self.dtype)(
                x, moments=moments
            )
        )
        x = avg_pool(x, 4)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)


def DenseNet121(num_classes: int = 10, dtype=None, **kw):
    return DenseNet((6, 12, 24, 16), 32, num_classes=num_classes, dtype=dtype, **kw)


def DenseNet169(num_classes: int = 10, dtype=None, **kw):
    return DenseNet((6, 12, 32, 32), 32, num_classes=num_classes, dtype=dtype, **kw)


def DenseNet201(num_classes: int = 10, dtype=None, **kw):
    return DenseNet((6, 12, 48, 32), 32, num_classes=num_classes, dtype=dtype, **kw)


def DenseNet161(num_classes: int = 10, dtype=None, **kw):
    return DenseNet((6, 12, 36, 24), 48, num_classes=num_classes, dtype=dtype, **kw)


def DenseNetCifar(num_classes: int = 10, dtype=None, **kw):
    return DenseNet((6, 12, 24, 16), 12, num_classes=num_classes, dtype=dtype, **kw)
