"""DenseNet for CIFAR-10 (reference: models/densenet.py:9-99).

Pre-activation bottleneck layers (BN-ReLU-conv1x1(4g) -> BN-ReLU-conv3x3(g))
whose output is concatenated *in front of* the running feature stack
(torch.cat([out, x]), models/densenet.py:20 — order preserved here so BN
channel statistics line up). Transitions halve channels (floor(planes*0.5),
models/densenet.py:46) and avg-pool 2x. Stem conv3x3 to 2*growth; head
BN-ReLU-avgpool4-linear (models/densenet.py:81-83). All convs bias-free.

Golden param counts: DenseNet121 6,956,298 · DenseNet169 12,493,322 ·
DenseNet201 18,104,330 · DenseNet161 26,482,378 · densenet_cifar 1,000,618.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Optional, Sequence

import jax.numpy as jnp
from flax import linen as nn

from pytorch_cifar_tpu.models.common import (
    BatchNorm,
    Conv,
    Dense,
    avg_pool,
)


class DenseLayer(nn.Module):
    growth_rate: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        bn = partial(BatchNorm, use_running_average=not train, dtype=self.dtype)
        out = nn.relu(bn()(x))
        out = Conv(4 * self.growth_rate, 1, use_bias=False, dtype=self.dtype)(out)
        out = nn.relu(bn()(out))
        out = Conv(self.growth_rate, 3, padding=1, use_bias=False, dtype=self.dtype)(out)
        return jnp.concatenate([out, x], axis=-1)


class Transition(nn.Module):
    out_planes: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.relu(BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        x = Conv(self.out_planes, 1, use_bias=False, dtype=self.dtype)(x)
        return avg_pool(x, 2)


class DenseNet(nn.Module):
    nblocks: Sequence[int]
    growth_rate: int = 12
    reduction: float = 0.5
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        g = self.growth_rate
        planes = 2 * g
        x = Conv(planes, 3, padding=1, use_bias=False, dtype=self.dtype)(x)
        for stage, nblock in enumerate(self.nblocks):
            for _ in range(nblock):
                x = DenseLayer(g, dtype=self.dtype)(x, train)
            planes += nblock * g
            if stage < len(self.nblocks) - 1:
                planes = int(math.floor(planes * self.reduction))
                x = Transition(planes, dtype=self.dtype)(x, train)
        x = nn.relu(BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        x = avg_pool(x, 4)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)


def DenseNet121(num_classes: int = 10, dtype=None, **kw):
    return DenseNet((6, 12, 24, 16), 32, num_classes=num_classes, dtype=dtype, **kw)


def DenseNet169(num_classes: int = 10, dtype=None, **kw):
    return DenseNet((6, 12, 32, 32), 32, num_classes=num_classes, dtype=dtype, **kw)


def DenseNet201(num_classes: int = 10, dtype=None, **kw):
    return DenseNet((6, 12, 48, 32), 32, num_classes=num_classes, dtype=dtype, **kw)


def DenseNet161(num_classes: int = 10, dtype=None, **kw):
    return DenseNet((6, 12, 36, 24), 48, num_classes=num_classes, dtype=dtype, **kw)


def DenseNetCifar(num_classes: int = 10, dtype=None, **kw):
    return DenseNet((6, 12, 24, 16), 12, num_classes=num_classes, dtype=dtype, **kw)
