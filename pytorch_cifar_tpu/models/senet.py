"""SENet18 for CIFAR-10 (reference: models/senet.py:45-115).

Pre-activation basic blocks with squeeze-and-excitation channel gating: the
SE branch is global average pool to 1x1 (models/senet.py:64), two 1x1 convs
with bias (reduction 16, models/senet.py:59-60), ReLU then sigmoid, and a
broadcast multiply (models/senet.py:65-69). The conditional projection
shortcut taken from the *pre-activated* input mirrors models/senet.py:53-57,
including the hasattr idiom (here: an explicit condition) and the shortcut
having no BN. Stage plan 64/128/256/512, strides 1/2/2/2, avg_pool(4) head
(models/senet.py:85-106).

Golden param count: SENet18 11,260,354.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax.numpy as jnp
from flax import linen as nn

from pytorch_cifar_tpu.models.common import BatchNorm, Conv, Dense, avg_pool


class SEPreActBlock(nn.Module):
    """BN-ReLU-conv3x3 -> BN-ReLU-conv3x3, SE gate, residual add."""

    planes: int
    stride: int = 1
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool):
        conv = lambda n, k, s=1, p=0: Conv(
            n, k, strides=s, padding=p, use_bias=False, dtype=self.dtype
        )
        bn = lambda: BatchNorm(use_running_average=not train, dtype=self.dtype)

        out = nn.relu(bn()(x))
        shortcut = (
            conv(self.planes, 1, self.stride)(out)
            if self.stride != 1 or x.shape[-1] != self.planes
            else x
        )
        out = conv(self.planes, 3, self.stride, 1)(out)
        out = conv(self.planes, 3, 1, 1)(nn.relu(bn()(out)))

        # Squeeze: global average pool; excitation: 1x1 convs w/ bias.
        w = jnp.mean(out, axis=(1, 2), keepdims=True)
        w = nn.relu(Conv(self.planes // 16, 1, dtype=self.dtype)(w))
        w = nn.sigmoid(Conv(self.planes, 1, dtype=self.dtype)(w))
        return out * w + shortcut


class SENet(nn.Module):
    num_blocks: Sequence[int]
    num_classes: int = 10
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = Conv(64, 3, padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(BatchNorm(use_running_average=not train, dtype=self.dtype)(x))
        for planes, stride, n in zip(
            (64, 128, 256, 512), (1, 2, 2, 2), self.num_blocks
        ):
            for i in range(n):
                x = SEPreActBlock(
                    planes, stride if i == 0 else 1, dtype=self.dtype
                )(x, train)
        x = avg_pool(x, 4)
        x = x.reshape((x.shape[0], -1))
        return Dense(self.num_classes, dtype=self.dtype)(x)


def SENet18(num_classes=10, dtype=None):
    return SENet((2, 2, 2, 2), num_classes, dtype)
