"""pytorch_cifar_tpu — a TPU-native (JAX/XLA) CIFAR-10 training framework.

Brand-new framework with the capability surface of the reference
``aqualovers/pytorch-cifar`` (see SURVEY.md), redesigned TPU-first:

- pure-functional models (flax.linen) in NHWC layout,
- one jitted SPMD train step (``jax.value_and_grad`` + optax) instead of an
  eager autograd loop (reference: main.py:99-113),
- data parallelism via ``jax.sharding.Mesh`` + ``shard_map`` + ``psum``
  instead of DataParallel/DDP+NCCL (reference: main_dist.py:140-144),
- bf16 mixed precision policy instead of CUDA AMP + GradScaler
  (reference: main_dist.py:179-191),
- on-device batched augmentation under explicit PRNG keys instead of
  DataLoader worker processes (reference: main.py:30-35,45).
"""

__version__ = "0.1.0"

from pytorch_cifar_tpu.config import TrainConfig  # noqa: F401


def _xla_supports_flag(flag_name: str) -> bool:
    """True when the installed jaxlib's XLA knows ``flag_name``.

    XLA *aborts the process* (parse_flags_from_env.cc) on any unknown
    flag in XLA_FLAGS, so optional tuning flags must be probed before
    being set — a version of jaxlib that predates a flag turns every
    entry point into an instant crash otherwise (observed with the CPU
    collective-timeout flags on jaxlib 0.4.36). Flag names are embedded
    verbatim in the xla_extension shared object as registration strings;
    a byte scan of that file is the only probe that cannot itself abort.
    The result is cached in the environment so child processes (bench
    captures, multihost workers) skip the scan.
    """
    import glob
    import mmap
    import os

    cache_key = "PYTORCH_CIFAR_TPU_XLAFLAG_" + flag_name.upper()
    cached = os.environ.get(cache_key)
    if cached in ("0", "1"):
        return cached == "1"
    supported = False
    try:
        import jaxlib

        pattern = os.path.join(
            os.path.dirname(jaxlib.__file__), "xla_extension*.so"
        )
        needle = flag_name.encode()
        for so in glob.glob(pattern):
            with open(so, "rb") as f, mmap.mmap(
                f.fileno(), 0, access=mmap.ACCESS_READ
            ) as m:
                if m.find(needle) != -1:
                    supported = True
                    break
    except Exception:
        supported = False  # cannot verify -> never risk the abort
    os.environ[cache_key] = "1" if supported else "0"
    return supported


def xla_collective_timeout_flags() -> str:
    """The CPU collective liveness-timeout flags, or '' when the
    installed XLA does not know them (setting unknown flags aborts; see
    :func:`_xla_supports_flag`). Shared by honor_platform_env and
    tests/conftest.py so the support gate cannot drift."""
    if _xla_supports_flag("xla_cpu_collective_call_terminate_timeout_seconds"):
        return (
            "--xla_cpu_collective_call_warn_stuck_timeout_seconds=60"
            " --xla_cpu_collective_call_terminate_timeout_seconds=300"
        )
    return ""


def honor_platform_env() -> None:
    """Make ``JAX_PLATFORMS=cpu`` effective even when a site-installed TPU
    plugin overrides it at interpreter startup.

    jax reads the env var into ``jax_platforms`` config, but some device
    plugins re-register themselves as the default backend regardless; the
    config-level update (before first backend use) is authoritative. Entry
    points (train.py, bench.py) call this so a CPU-pinned invocation can
    never seize the machine's exclusive TPU chip.
    """
    import os

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # XLA:CPU's in-process collective rendezvous has a 40 s termination
        # timeout that abort()s the process. On an oversubscribed host
        # (this CI VM has ONE core under 8 virtual devices) a straggler
        # partition can legitimately take longer than that to reach an
        # all-reduce while its peers spin-wait. Liveness timeouts, not
        # correctness: raise them before the backend reads XLA_FLAGS —
        # but only when this jaxlib KNOWS the flags (unknown XLA_FLAGS
        # abort the process, strictly worse than the timeout they tune).
        flags = os.environ.get("XLA_FLAGS", "")
        timeout_flags = xla_collective_timeout_flags()
        if timeout_flags and "collective_call_terminate" not in flags:
            os.environ["XLA_FLAGS"] = (flags + " " + timeout_flags).strip()

        import jax

        jax.config.update("jax_platforms", "cpu")
        # concurrent multi-partition executions additionally contend for
        # the same worker threads; serializing CPU dispatch keeps one
        # execution's partitions from starving another's rendezvous.
        # Scoped to MULTI-device CPU (virtual-device meshes): a
        # single-device CPU run has no rendezvous to protect and keeps
        # async dispatch pipelining.
        import re as _re

        # XLA honors the LAST occurrence of a repeated flag
        counts = _re.findall(
            r"--xla_force_host_platform_device_count=(\d+)",
            os.environ.get("XLA_FLAGS", ""),
        )
        n = int(counts[-1]) if counts else 1
        # virtual CPU devices can also be provisioned via JAX_NUM_CPU_DEVICES
        try:
            n = max(n, int(os.environ.get("JAX_NUM_CPU_DEVICES", "1")))
        except ValueError:
            pass
        if n > 1:
            jax.config.update("jax_cpu_enable_async_dispatch", False)


# Measured per-model scoped-VMEM budgets (tools/vmem_ab.py, interleaved
# A/B on the v5e — BENCHMARKS.md round 4). Raising the budget from the
# compiler's 16 MB default to 32 MB buys deeper fusion tiles, which is
# NOT globally good: +3% on ResNet18 but -25% on GoogLeNet (big fused
# tiles hurt its pool/concat-heavy cells), neutral-to-negative on the
# other measured families. Only measured winners are listed; unmeasured
# models get the compiler default.
_VMEM_BUDGET_KIB = {
    "ResNet18": "32768",  # 33.5k -> 34.4k img/s (+3%; epoch path +0.8%)
    "PNASNetA": "32768",  # 12.6k -> 13.0k img/s (+2-3%, confirmed twice)
}


def tpu_compiler_options(device=None, model: str = None):
    """Per-compile XLA options for the jitted steps; None off-TPU.

    ``model``: registry name of the model the step compiles — consulted
    against the measured per-model scoped-VMEM table above (the
    cudnn.benchmark analogue: the reference autotunes per-shape at
    runtime, main.py:75; here the tuning is measured offline with
    tools/vmem_ab.py and checked in). Callers that don't know the model
    (or an unmeasured model) get the compiler default.

    ``device``: the device the jit will actually target (e.g.
    ``mesh.devices.flat[0]``) — the default backend can be a different
    platform than the mesh (a site TPU plugin owns the default while the
    mesh is CPU, or vice versa), and the CPU compiler rejects TPU options.
    """
    import os

    import jax

    if device is None:
        device = jax.devices()[0]
    if device.platform != "tpu":
        return None
    # operator/experiment override: PYTORCH_CIFAR_TPU_VMEM_KIB=<kib> forces
    # one budget for every model; "default" forces the compiler default
    # (how the per-model table entries were measured — tools/vmem_ab.py)
    env = os.environ.get("PYTORCH_CIFAR_TPU_VMEM_KIB")
    if env is not None:
        env = env.strip()
        if env in ("", "default"):
            return None
        if not env.isdigit():
            # fail HERE with the variable named, not deep inside XLA's
            # flag parser on the first jit compile
            raise ValueError(
                "PYTORCH_CIFAR_TPU_VMEM_KIB must be a KiB integer or "
                f"'default', got {env!r}"
            )
        return {"xla_tpu_scoped_vmem_limit_kib": env}
    budget = _VMEM_BUDGET_KIB.get(model)
    return (
        {"xla_tpu_scoped_vmem_limit_kib": budget} if budget else None
    )


def enable_compilation_cache(path: str = None) -> None:
    """Persist XLA compilations across processes.

    TPU compiles of the fused train step are expensive (measured on the
    tunneled v5e: ~40 s for ResNet-18, ~200 s for LeNet — small models are
    not fast to *compile*), and every CLI invocation is a fresh process. The
    on-disk cache turns every repeat compile into a ~1 s deserialization.
    Entry points (train.py, bench.py, tools/) call this; tests do not (CPU
    compiles are fast, and cache writes would race under pytest-xdist).

    Default location is per-user (override with $PYTORCH_CIFAR_TPU_CACHE):
    a world-shared path breaks on multi-user machines — the second user hits
    a permission error on the first user's directory.
    """
    import os
    import tempfile

    import jax

    if os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip().lower() == "cpu":
        # jaxlib 0.4.36's XLA:CPU mis-executes DESERIALIZED cached
        # executables under buffer donation: a warm-cache resume computes
        # garbage metrics (NaN eval on a bit-exact restored state), then
        # dies with glibc heap corruption or a segfault — found by the
        # kill-and-resume chaos drill (ROBUSTNESS.md; deterministic
        # in-process reproducer: warm second run of the pipelined fit).
        # CPU compiles are seconds, so the cache buys nothing there —
        # skip it entirely. TPU (where one compile costs minutes and the
        # serialization path is exercised in production) keeps the cache.
        return

    if path is None:
        # getpass.getuser() raises KeyError under a passwd-less UID (e.g.
        # k8s runAsUser) with no USER/LOGNAME set; fall back to the uid
        user = (
            os.environ.get("USER")
            or os.environ.get("LOGNAME")
            or f"uid{os.getuid()}"
        )
        path = os.environ.get("PYTORCH_CIFAR_TPU_CACHE") or os.path.join(
            tempfile.gettempdir(), f"pytorch_cifar_tpu_jax_cache-{user}"
        )
    jax.config.update("jax_compilation_cache_dir", path)
    # cache everything: the default min-entry-size skips small programs,
    # but on this platform even tiny-model steps take minutes to compile
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    _harden_cache_writes()


def _harden_cache_writes() -> None:
    """Make persistent-cache entry publication atomic (tmp + rename).

    jaxlib 0.4.36's ``LRUCache.put`` writes the executable with a plain
    ``cache_path.write_bytes(val)`` — NOT atomic. A process killed
    mid-write (SIGKILL preemption, OOM-kill, power loss) leaves a torn
    entry under the final name, and every later process deserializes
    those garbage bytes as a valid executable: observed in this PR's
    chaos drills as silently-wrong eval metrics (loss 2.4e7), NaN
    training, and glibc heap aborts ('corrupted size vs. prev_size') —
    the worst failure class there is, because nothing ever errors at the
    cache layer. Wrapping the put with tmp + ``os.replace`` makes an
    entry either absent or complete; a kill mid-write leaves only a
    harmless ``*.tmp.<pid>`` orphan (swept here on the next call).

    Version-gated: only the exact eviction-disabled shape this repo
    configures is rewritten; anything else falls through to the
    original implementation untouched.
    """
    import os
    import time

    try:
        from jax._src import lru_cache
    except ImportError:  # newer jax reworked the cache; nothing to patch
        return
    cls = getattr(lru_cache, "LRUCache", None)
    if cls is None or getattr(cls.put, "_pct_atomic", False):
        return
    cache_suffix = getattr(lru_cache, "_CACHE_SUFFIX", "-cache")
    atime_suffix = getattr(lru_cache, "_ATIME_SUFFIX", "-atime")
    orig_put = cls.put

    def put(self, key: str, val: bytes) -> None:
        if getattr(self, "eviction_enabled", True):
            # size-bounded configs take locks and do eviction accounting;
            # this repo never enables that — don't second-guess it
            return orig_put(self, key, val)
        if not key:
            raise ValueError("key cannot be empty")
        cache_path = self.path / f"{key}{cache_suffix}"
        if cache_path.exists():
            return
        # sweep tmp orphans from previously killed writers (bounded: one
        # dir listing per compile, and compiles are rare by definition)
        for stale in self.path.glob(f"{key}{cache_suffix}.tmp.*"):
            try:
                stale.unlink()
            except OSError:
                pass
        tmp = self.path / f"{key}{cache_suffix}.tmp.{os.getpid()}"
        tmp.write_bytes(val)
        # graftcheck: noqa[atomic-publish] -- compile-cache entry: the rename atomicity is what the SIGKILL drill demanded (no torn entry poisons later processes); a crash-lost entry just recompiles, so per-put fsync would tax every compile for nothing
        os.replace(tmp, cache_path)
        (self.path / f"{key}{atime_suffix}").write_bytes(
            time.time_ns().to_bytes(8, "little")
        )

    put._pct_atomic = True
    cls.put = put
