"""pytorch_cifar_tpu — a TPU-native (JAX/XLA) CIFAR-10 training framework.

Brand-new framework with the capability surface of the reference
``aqualovers/pytorch-cifar`` (see SURVEY.md), redesigned TPU-first:

- pure-functional models (flax.linen) in NHWC layout,
- one jitted SPMD train step (``jax.value_and_grad`` + optax) instead of an
  eager autograd loop (reference: main.py:99-113),
- data parallelism via ``jax.sharding.Mesh`` + ``shard_map`` + ``psum``
  instead of DataParallel/DDP+NCCL (reference: main_dist.py:140-144),
- bf16 mixed precision policy instead of CUDA AMP + GradScaler
  (reference: main_dist.py:179-191),
- on-device batched augmentation under explicit PRNG keys instead of
  DataLoader worker processes (reference: main.py:30-35,45).
"""

__version__ = "0.1.0"

from pytorch_cifar_tpu.config import TrainConfig  # noqa: F401
