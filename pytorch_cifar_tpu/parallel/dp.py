"""Data-parallel train/eval steps via shard_map over a 1-D mesh.

Semantics mirror the reference's DDP contract (main_dist.py:109-147):

- params/opt state replicated on every device (DDP's per-rank replica);
- the global batch laid out over the ``data`` axis, each device computing
  on global_batch/n_devices examples (main_dist.py:111-115);
- gradients averaged across devices each step — ``jax.lax.pmean`` inside
  the step (steps.py), which XLA lowers to an ICI all-reduce, the
  TPU-native version of DDP's bucketed NCCL all-reduce;
- BatchNorm normalizes over the *local* per-device batch (parity with
  torch's non-Sync BN under DDP), while the updated running stats are
  pmean'd so eval is identical on every host — SURVEY.md §7.2;
- eval metrics are psum'd (fixing the reference's per-rank redundant eval,
  SURVEY.md §2.5.7).

shard_map (not pmap) is the current-generation SPMD entry point: it
composes with jit, works on any mesh shape, and extends to multi-host
without code changes.
"""

from __future__ import annotations

import inspect
from functools import partial
from typing import Callable, Optional

import jax

try:
    from jax import shard_map as _shard_map
except ImportError:  # jax < 0.5 ships it pre-stabilization only
    from jax.experimental.shard_map import shard_map as _shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:

    def shard_map(*args, check_vma=None, **kwargs):
        """Older jax spells ``check_vma`` as ``check_rep`` (the varying-
        manual-axes rename landed with the jax.shard_map stabilization);
        translate so every call site can use the current-generation
        keyword. Single chokepoint — callers (here and in tests) import
        shard_map from THIS module, never from jax directly."""
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return _shard_map(*args, **kwargs)

from pytorch_cifar_tpu.parallel.mesh import DATA_AXIS


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Sharding for host batches: batch dim split over the data axis."""
    return NamedSharding(mesh, P(axis))


def replicate(tree, mesh: Mesh):
    """Place a pytree fully-replicated on the mesh (DDP's init-time param
    broadcast, main_dist.py:141-144).

    Multi-process on a fragile-gloo stack (jax 0.4.x CPU — see
    ``mesh.gloo_transport_fragile``): jax's own multi-process
    ``device_put`` onto a non-addressable sharding runs a per-leaf
    ``assert_equal`` — a variable-size ``broadcast_one_to_all`` per leaf
    through gloo's TCP transport, which flakily aborts the whole process
    when two transfers of different sizes pair up (the
    ``op.preamble.length <= op.nbytes`` crash). Every replicate caller
    already guarantees identical values on all processes (same-seed init,
    or a checkpoint broadcast from process 0), so the replicated array is
    assembled from process-local data instead — no collective at all.
    """
    import numpy as np

    from pytorch_cifar_tpu.parallel.mesh import gloo_transport_fragile

    sharding = NamedSharding(mesh, P())
    if jax.process_count() > 1 and gloo_transport_fragile():
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, np.asarray(x)
            ),
            tree,
        )
    return jax.device_put(tree, sharding)


def unreplicate(tree):
    """Pull one logical copy back to host-addressable memory."""
    return jax.device_get(tree)


def data_parallel_train_step(
    step_fn: Callable,
    mesh: Mesh,
    axis: str = DATA_AXIS,
    donate: bool = True,
    model_name: Optional[str] = None,
) -> Callable:
    """Wrap a per-shard train step (built with ``make_train_step(
    axis_name=axis)``) into a jitted SPMD step over ``mesh``.

    step_fn: (state, (images, labels), rng) -> (state, metrics), already
    containing the pmean/psum collectives for grads/stats/metrics.

    ``donate=True`` donates the state AND the per-step batch buffers
    (argnums 0 and 1): the loader hands each device batch to exactly one
    step call and never reads it back, so donating the images/labels
    buffers lets XLA alias them for the step's outputs — free HBM and
    copy savings with the async input pipeline keeping ``prefetch``
    batches in flight (XLA:CPU ignores input donation with a warning).
    graftcheck's donation-misuse rule traces reads-after-donate through
    this wrapper (STATIC_ANALYSIS.md); since the whole-project pass the
    donated positions are DERIVED from this function's own
    ``jax.jit(..., donate_argnums=...)`` expression — change them here
    and the rule follows automatically, aliases and renames included.
    """
    from pytorch_cifar_tpu import tpu_compiler_options

    mapped = shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(P(), (P(axis), P(axis)), P()),
        out_specs=(P(), P()),
        check_vma=False,  # states/metrics are made replicated by pmean/psum
    )
    return jax.jit(
        mapped,
        donate_argnums=(0, 1) if donate else (),
        compiler_options=tpu_compiler_options(mesh.devices.flat[0], model=model_name),
    )


def data_parallel_eval_step(
    step_fn: Callable,
    mesh: Mesh,
    axis: str = DATA_AXIS,
    model_name: Optional[str] = None,
) -> Callable:
    """Wrap a per-shard eval step (``make_eval_step(axis_name=axis)``)."""
    from pytorch_cifar_tpu import tpu_compiler_options

    mapped = shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(P(), (P(axis), P(axis))),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(mapped, compiler_options=tpu_compiler_options(mesh.devices.flat[0], model=model_name))


def data_parallel_train_epoch(
    epoch_fn: Callable,
    mesh: Mesh,
    donate: bool = True,
    model_name: Optional[str] = None,
) -> Callable:
    """SPMD-wrap a whole-epoch scan (``make_train_epoch(axis_name=...)``).

    Every input is replicated (P()): the device-resident dataset and the
    epoch permutation are whole-copies on each device, and each shard
    carves out its own batch rows by ``axis_index`` INSIDE the scan body —
    there is no per-step host involvement at all, which is the point
    (one dispatch per epoch; see make_train_epoch).

    ``donate=True`` donates the state, the zero-metrics totals, and the
    epoch PERMUTATION (argnums 0, 1, 4): ``staged_perm`` materializes a
    fresh permutation per epoch and only this one dispatch ever reads
    it, so its buffer is free for XLA to reuse the moment the gather
    consumes it. The dataset arrays (argnums 2, 3) are deliberately NOT
    donated — they persist across every epoch. graftcheck's
    donation-misuse rule derives all of this from the ``donate_argnums``
    expression below (STATIC_ANALYSIS.md) — no hand-synced table.
    """
    from pytorch_cifar_tpu import tpu_compiler_options

    mapped = shard_map(
        epoch_fn,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(
        mapped,
        donate_argnums=(0, 1, 4) if donate else (),
        compiler_options=tpu_compiler_options(mesh.devices.flat[0], model=model_name),
    )


def data_parallel_eval_epoch(
    epoch_fn: Callable, mesh: Mesh, model_name: Optional[str] = None
) -> Callable:
    """SPMD-wrap a whole-epoch eval scan (``make_eval_epoch``)."""
    from pytorch_cifar_tpu import tpu_compiler_options

    mapped = shard_map(
        epoch_fn,
        mesh=mesh,
        in_specs=(P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(
        mapped, compiler_options=tpu_compiler_options(mesh.devices.flat[0], model=model_name)
    )
