"""Device mesh construction + multi-host initialization.

The reference's topology plumbing — TCP rendezvous URL, node-rank math,
one process per GPU (main_dist.py:39-40,51-76) — is replaced by the JAX
model: the TPU runtime handles rendezvous (`jax.distributed.initialize()`
needs no URL on TPU pods), one process per host drives all local chips,
and the "world" is a named mesh axis that XLA lowers collectives onto
(ICI within a slice, DCN across slices).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"


def _distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` with a jaxlib 0.4.36-era
    fallback: the public predicate landed after 0.4.x, where the only
    signal is the private global client handle. Same version-gap pattern
    as the shard_map shim in parallel/dp.py — an older jax must degrade
    to the equivalent check, never AttributeError (this took down every
    multihost worker in the 0.4.37 container). Must not force backend
    initialization (see initialize_distributed's NB)."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host rendezvous (replaces dist.init_process_group,
    main_dist.py:73-74).

    On TPU pods every argument is discovered from the runtime environment;
    the explicit arguments exist for CPU/GPU multi-process testing. Safe to
    call in single-process runs (no-op if already initialized or
    single-host).

    NB: the already-initialized check must NOT touch ``jax.process_count()``
    or ``jax.devices()`` — those force backend initialization, after which
    ``jax.distributed.initialize`` is permanently too late (the process
    would silently run single-host with its local devices only).
    """
    if _distributed_is_initialized():
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError):
        if coordinator_address is not None:
            # an explicitly requested rendezvous that fails must be loud:
            # swallowing it would silently degrade the job to independent
            # single-host runs with wrong global-batch semantics
            raise
        # auto-detect on a single host: SPMD code below works unchanged on
        # the local devices
        pass


def make_mesh(
    num_devices: int = 0,
    axis: str = DATA_AXIS,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D data-parallel mesh over the first ``num_devices`` devices
    (0 = all addressable devices; the reference's implicit
    ``device_count()`` world, main_dist.py:54)."""
    if devices is None:
        devices = jax.devices()
    if num_devices:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devices)}"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis,))


def is_primary() -> bool:
    """True on the process that owns logging/checkpoint writes (the SPMD
    equivalent of the reference's rank-0 gating, main_dist.py:78-82,243)."""
    return jax.process_index() == 0


# Chunk size for the gloo-safe broadcast below. Uniform 64 KiB transfers:
# small enough to sit far under any gloo TCP unbound-buffer limit, uniform
# so every chunked collective reuses ONE compiled program (and no two
# in-flight transfers can disagree about their length).
_BROADCAST_CHUNK_BYTES = 1 << 16


def gloo_transport_fragile() -> bool:
    """True when large/irregular host-side broadcasts must be avoided:
    jax 0.4.x's CPU cross-process collectives run over gloo's TCP
    transport, which aborts the whole process when two transfers of
    different sizes pair up on a connection (``op.preamble.length <=
    op.nbytes`` check failure inside pair.cc — observed in this container
    on jax 0.4.37 as the ``test_cross_topology_checkpoint_resume`` crash;
    ROADMAP). Two call sites route around it: :func:`broadcast_pytree`
    (uniform chunks instead of one big variable-size broadcast) and
    ``parallel.dp.replicate`` (process-local assembly instead of jax's
    per-leaf ``assert_equal`` broadcast storm inside multi-process
    ``device_put``). Version-gated so newer jaxlib (and every non-CPU
    backend, where collectives never touch gloo) keeps the one-shot fast
    paths."""
    if jax.devices()[0].platform != "cpu":
        return False
    try:
        major, minor = (int(p) for p in jax.__version__.split(".")[:2])
    except ValueError:  # unparseable dev version: assume current (fixed)
        return False
    return (major, minor) < (0, 5)


def broadcast_pytree(tree, chunk_bytes: int = _BROADCAST_CHUNK_BYTES):
    """Broadcast a host pytree process-0 -> all processes.

    Same contract as ``multihost_utils.broadcast_one_to_all`` (every
    process passes a structurally identical tree — non-source values are
    placeholders — and gets numpy leaves back), which this simply wraps
    on healthy stacks. On jax 0.4.x CPU (gloo transport, see
    :func:`gloo_transport_fragile`) the leaves are packed into one
    byte buffer and broadcast in fixed-size chunks instead: many small
    uniform transfers where the one-shot path crashes the process inside
    gloo. Single-process: the tree comes back unchanged.

    This is also the wire of the multi-process mesh replica's serving
    protocol (serve/mesh_replica.py): command frames, batch payloads,
    and weight swaps all ride it — callers there hold the additional
    single-initiator discipline (exactly one thread in the job starts
    broadcasts, in a total order) that makes it safe off the main
    thread, which the thread-collective lint rule's sanctioned-entry
    declaration records (STATIC_ANALYSIS.md).
    """
    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils

    if not gloo_transport_fragile():
        return multihost_utils.broadcast_one_to_all(tree)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    # np.asarray, NOT ascontiguousarray: the latter promotes 0-d leaves
    # to shape (1,), which silently reshaped every scalar a restore
    # broadcast carried (TrainState.step came back (1,) on every rank —
    # latent until the elastic trainer first RESUMED TRAINING from a
    # multihost save and fold_in rejected the non-scalar step)
    arrs = [np.asarray(leaf) for leaf in leaves]
    packed = (
        np.concatenate(
            [
                np.ascontiguousarray(a).reshape(-1).view(np.uint8)
                for a in arrs
            ]
        )
        if arrs
        else np.zeros(0, np.uint8)
    )
    # pad to a whole number of uniform chunks: every broadcast call then
    # has the same shape, so one compiled collective serves them all
    nchunks = max(1, -(-packed.nbytes // chunk_bytes))
    padded = np.zeros(nchunks * chunk_bytes, np.uint8)
    padded[: packed.nbytes] = packed
    got = np.concatenate(
        [
            np.asarray(
                multihost_utils.broadcast_one_to_all(
                    padded[i * chunk_bytes : (i + 1) * chunk_bytes]
                ),
                np.uint8,
            )
            for i in range(nchunks)
        ]
    )[: packed.nbytes]
    out, off = [], 0
    for a in arrs:
        out.append(
            got[off : off + a.nbytes].view(a.dtype).reshape(a.shape)
        )
        off += a.nbytes
    return jax.tree_util.tree_unflatten(treedef, out)
