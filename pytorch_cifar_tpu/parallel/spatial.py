"""Spatial partitioning: the vision equivalent of sequence/context parallelism.

The reference has no parallelism beyond data-parallel replicas (SURVEY.md
§2.4) — its workload has no sequence axis to split. The tensor that grows
with "context" in a CNN is the image plane, and the TPU-native way to split
it is GSPMD spatial partitioning: lay the batch over a ``data`` mesh axis
AND the image height over a ``spatial`` mesh axis, annotate the input
sharding, and let XLA insert the halo exchanges every 3x3 conv needs at
shard boundaries (the same compiler machinery that inserts ring
collectives for sharded attention). No model code changes — the same flax
modules run unmodified. This is verified at the HLO level, not assumed:
lowering the spatial ResNet18 step shows 96 conv-attributed
``collective-permute`` ops carrying single-row halo payloads (188 on the
3-D data x H x W mesh) and at most one tiny tail ``all-gather`` — never a
full-activation gather
(tests/test_spatial.py::test_spatial_step_hlo_uses_halo_exchange_not_allgather).

Contrast with ``dp.py``: the DP path uses ``shard_map`` (per-shard code,
explicit ``pmean``/``psum``). Here the step stays GLOBAL-semantics
(``make_train_step(axis_name=None)``) under plain ``jit`` with sharding
annotations, and the compiler derives every collective: halo exchange for
convs, cross-shard reductions for BatchNorm batch statistics (i.e. BN is
globally exact — the SyncBN semantics fall out for free), gradient
all-reduce. This is the "pick a mesh, annotate shardings, let XLA insert
collectives" recipe applied one axis further than the reference ever went.

Scaling use: batch 512 CIFAR fits one chip, but the same two-axis mesh is
the recipe for inputs that do NOT fit a chip's HBM (high-res vision, video)
— exactly the role ring attention plays for long sequences.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_cifar_tpu.parallel.mesh import DATA_AXIS

SPATIAL_AXIS = "spatial"
SPATIAL_W_AXIS = "spatial_w"


def make_2d_mesh(
    data: int = 0,
    spatial: int = 1,
    devices=None,
) -> Mesh:
    """(data x spatial) mesh. data=0 means "all devices / spatial"."""
    return make_spatial_mesh(data=data, spatial=spatial, devices=devices)


def make_spatial_mesh(
    data: int = 0,
    spatial: int = 1,
    spatial_w: int = 1,
    devices=None,
) -> Mesh:
    """(data x spatial[_h] [x spatial_w]) mesh.

    ``spatial_w > 1`` additionally shards the image WIDTH — context
    parallelism over both image axes (halo exchanges in both directions,
    all derived by GSPMD). The mesh stays 2-D when spatial_w == 1 so
    existing (data x spatial) call sites and shape assertions are
    unchanged. data=0 means "all devices / (spatial*spatial_w)".
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    sp = spatial * spatial_w
    if spatial < 1 or spatial_w < 1 or n % sp:
        raise ValueError(
            f"spatial={spatial} x spatial_w={spatial_w} must divide "
            f"device count {n}"
        )
    if not data:
        data = n // sp
    if data * sp > n:
        raise ValueError(
            f"{data}x{spatial}x{spatial_w} mesh exceeds {n} devices"
        )
    if spatial_w == 1:
        grid = np.asarray(devices[: data * sp]).reshape(data, spatial)
        return Mesh(grid, (DATA_AXIS, SPATIAL_AXIS))
    grid = np.asarray(devices[: data * sp]).reshape(data, spatial, spatial_w)
    return Mesh(grid, (DATA_AXIS, SPATIAL_AXIS, SPATIAL_W_AXIS))


def spatial_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Images (N,H,W,C): batch over ``data``, height over ``spatial``,
    and width over ``spatial_w`` when the mesh has that axis."""
    if SPATIAL_W_AXIS in mesh.axis_names:
        return NamedSharding(mesh, P(DATA_AXIS, SPATIAL_AXIS, SPATIAL_W_AXIS))
    return NamedSharding(mesh, P(DATA_AXIS, SPATIAL_AXIS))


def spatial_label_sharding(mesh: Mesh) -> NamedSharding:
    """Labels (N,): batch axis only (no spatial dim to split)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def spatial_train_step(
    step_fn: Callable, mesh: Mesh, donate: bool = True, model_name=None
):
    """jit a GLOBAL-semantics train step (built with ``axis_name=None``)
    over the 2-D mesh. GSPMD partitions every conv spatially and inserts
    halo exchanges; state stays replicated; metrics come back replicated.
    """
    from pytorch_cifar_tpu import tpu_compiler_options

    replicated = NamedSharding(mesh, P())
    return jax.jit(
        step_fn,
        in_shardings=(
            replicated,
            (spatial_batch_sharding(mesh), spatial_label_sharding(mesh)),
            replicated,
        ),
        out_shardings=(replicated, replicated),
        donate_argnums=(0,) if donate else (),
        compiler_options=tpu_compiler_options(mesh.devices.flat[0], model=model_name),
    )


def spatial_eval_step(step_fn: Callable, mesh: Mesh, model_name=None):
    from pytorch_cifar_tpu import tpu_compiler_options

    replicated = NamedSharding(mesh, P())
    return jax.jit(
        step_fn,
        in_shardings=(
            replicated,
            (spatial_batch_sharding(mesh), spatial_label_sharding(mesh)),
        ),
        out_shardings=replicated,
        compiler_options=tpu_compiler_options(mesh.devices.flat[0], model=model_name),
    )


def spatial_train_epoch(
    epoch_fn: Callable, mesh: Mesh, donate: bool = True, model_name=None
):
    """jit a GLOBAL-semantics whole-epoch scan over the 2-D mesh.

    Inputs (state, totals, dataset, perm, rng) are all replicated; the
    scan body materializes each global batch on device and pins its
    (data x spatial) layout via with_sharding_constraint (built into
    make_train_epoch through ``batch_sharding=``), from which GSPMD
    derives the halo exchanges and reductions exactly as in
    spatial_train_step — but with one dispatch per epoch instead of per
    step (see make_train_epoch for the measured dispatch economics).
    """
    from pytorch_cifar_tpu import tpu_compiler_options

    replicated = NamedSharding(mesh, P())
    return jax.jit(
        epoch_fn,
        in_shardings=(replicated,) * 6,
        out_shardings=(replicated, replicated),
        donate_argnums=(0, 1) if donate else (),
        compiler_options=tpu_compiler_options(mesh.devices.flat[0], model=model_name),
    )


def spatial_eval_epoch(epoch_fn: Callable, mesh: Mesh, model_name=None):
    from pytorch_cifar_tpu import tpu_compiler_options

    replicated = NamedSharding(mesh, P())
    return jax.jit(
        epoch_fn,
        in_shardings=(replicated,) * 3,
        out_shardings=replicated,
        compiler_options=tpu_compiler_options(mesh.devices.flat[0], model=model_name),
    )


def put_spatial(x, y, mesh: Mesh) -> Tuple[jax.Array, jax.Array]:
    """Place a host batch onto the 2-D mesh (single-process path)."""
    return (
        jax.device_put(x, spatial_batch_sharding(mesh)),
        jax.device_put(y, spatial_label_sharding(mesh)),
    )
