"""SPMD parallelism over a jax.sharding.Mesh.

TPU-native replacement for the reference's two data-parallel flavors
(DataParallel main.py:73-75; DDP/NCCL main_dist.py:140-144) — one SPMD
code path covers both.
"""

from pytorch_cifar_tpu.parallel.mesh import (
    DATA_AXIS,
    initialize_distributed,
    make_mesh,
)
from pytorch_cifar_tpu.parallel.dp import (
    batch_sharding,
    data_parallel_eval_epoch,
    data_parallel_eval_step,
    data_parallel_train_epoch,
    data_parallel_train_step,
    replicate,
    unreplicate,
)
from pytorch_cifar_tpu.parallel.spatial import (
    SPATIAL_AXIS,
    SPATIAL_W_AXIS,
    make_2d_mesh,
    make_spatial_mesh,
    put_spatial,
    spatial_batch_sharding,
    spatial_eval_epoch,
    spatial_eval_step,
    spatial_label_sharding,
    spatial_train_epoch,
    spatial_train_step,
)
