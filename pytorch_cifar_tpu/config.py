"""Typed training configuration + CLI parsing.

Replaces the reference's duplicated argparse flag sets (main.py:18-22,
main_dist.py:25-47) with one dataclass. Hyperparameters the reference
hardcodes (momentum/wd main.py:87-88, T_max main.py:89, batch sizes
main.py:45,50, model choice main.py:71) are all exposed as flags here;
defaults reproduce the reference single-node recipe exactly.
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass
from typing import Optional, Tuple


@dataclass
class TrainConfig:
    # model (reference default: SimpleDLA, main.py:71)
    model: str = "SimpleDLA"
    num_classes: int = 10

    # optimization (reference recipe: main.py:86-89)
    lr: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 5e-4
    epochs: int = 200  # main.py:151
    cosine_t_max: Optional[int] = None  # None -> epochs. Set 200 w/ epochs=100
    # to replicate the reference dist-path quirk (main_dist.py:162 vs :28).

    # data (reference: main.py:28-53)
    batch_size: int = 128
    # 1000 (not the reference's 100, main.py:50): 10 device-friendly eval
    # batches per epoch instead of 100 dispatches; with the on-device metric
    # accumulation in eval_epoch the whole eval costs one D2H fetch
    eval_batch_size: int = 1000
    # train on every image every epoch (reference DataLoader default,
    # main.py:44-45); the ragged tail batch is wrap-padded to a static shape
    # with -1 labels masked from loss/metrics (pipeline.py)
    drop_last: bool = False
    data_dir: str = "./data"
    synthetic_data: bool = False  # run without the CIFAR-10 archive
    # synthetic split sizes; 50000/10000 makes a synthetic run's wall-clock
    # identical to real CIFAR-10 (same shapes, same step count) for timing
    # the full recipe in data-less environments (tools/accuracy_run.py)
    synthetic_train_size: int = 2048
    synthetic_test_size: int = 512
    random_crop: bool = True  # main.py:31 (the dist path drops it; we keep it)
    random_flip: bool = True
    # crop+flip on the host via the native C++ data plane instead of inside
    # the jitted step — for CPU-only training where device augmentation
    # competes with model compute (native/cifar_native.cpp)
    host_augment: bool = False
    # host-loader input pipeline (pipeline.Dataloader; the path taken when
    # the device-resident data plane is off, e.g. with --host_augment):
    #   prefetch     — bounded-queue depth: how many assembled device
    #                  batches may be in flight ahead of the consumer.
    #   async_input  — "on" (default) produces batches (native gather +
    #                  host augment + device_put) on a background worker
    #                  thread so input assembly and H2D overlap step
    #                  dispatch; "off" keeps the inline refill path — the
    #                  debugging escape hatch and the reference stream the
    #                  equivalence tests compare against. Both settings
    #                  yield bit-identical batches in identical order.
    prefetch: int = 2
    async_input: str = "on"
    # device-resident data plane (pipeline.DeviceDataset): stage the whole
    # dataset in HBM once and gather batches on device; only a ~200 KB
    # permutation crosses the host link per epoch. Measured necessity on
    # the tunneled v5e: H2D sustains ~7.5 MB/s, so per-batch transfer
    # (153 MB/epoch) would cost ~20 s/epoch against 1.4 s of compute.
    # Falls back to the host loader when host_augment is set.
    device_data: bool = True
    # epoch-shuffle gather kernel: XLA's row gather is descriptor-bound
    # (~5.3 ms for the 50k-row CIFAR shuffle on the v5e); the Pallas
    # pipelined-DMA kernel (ops/dma_gather.py) does the same move in
    # ~2.8 ms. Auto-gated to TPU meshes; --no-dma_gather forces the XLA
    # gather (e.g. if a future Mosaic regression bites).
    dma_gather: bool = True
    # generate each epoch's shuffle permutation ON DEVICE (seeded from
    # (seed, epoch) via jax.random) instead of uploading a host-numpy one:
    # the device data plane's per-epoch H2D drops to literally zero (the
    # ~200 KB permutation upload shared the serialized transport with
    # metric fetches — the last host dependency in the hot loop,
    # BENCHMARKS.md round 3 "remaining delta"). The shuffle stream differs
    # from the host generator's (both are (seed, epoch)-deterministic
    # uniform permutations); --no-device_perm restores the host stream.
    device_perm: bool = True
    mean: Tuple[float, float, float] = (0.4914, 0.4822, 0.4465)  # main.py:34
    std: Tuple[float, float, float] = (0.2023, 0.1994, 0.2010)

    # precision (uniform bf16 policy replaces per-block autocast,
    # models/resnet.py:39-51 in the reference)
    amp: bool = True  # bf16 compute; fp32 params/BN stats/loss
    # rematerialize the forward during backward (jax.checkpoint): trades
    # ~30% step time for activation memory, unlocking batch sizes past HBM
    remat: bool = False
    # compute narrow-group convs (1 < channels/group <= 16) as
    # block-diagonal dense convs: redundant FLOPs buy back MXU lanes.
    # Numerically identical; measured +6% on ResNeXt29_32x4d (v5e).
    # Off by default — only the narrow-group ResNeXt family benefits.
    dense_grouped_conv: bool = False

    # parallelism
    num_devices: int = 0  # 0 = all local devices, data-parallel mesh
    distributed: bool = False  # multi-host: jax.distributed.initialize()
    # explicit multi-host rendezvous (CPU/GPU testing and the elastic
    # supervisor; on TPU pods leave empty — the runtime discovers
    # coordinator/world/rank itself): "host:port", world size, rank
    dist_coord: str = ""
    dist_procs: int = 0
    dist_rank: int = 0
    # elastic training (train/elastic.py; ROADMAP item 3).
    #   elastic       — THIS RANK runs under an elastic supervisor: on
    #                   resume, process 0 re-cuts the on-disk checkpoint
    #                   layout to the current world size
    #                   (checkpoint.reshard_to_world — a v3 save by M
    #                   processes restores into any N-world already;
    #                   this keeps the dir's layout canonical), and a
    #                   mid-fit failure in a multi-process world exits
    #                   with the elastic reshape code (75) so the
    #                   supervisor relaunches the surviving world with
    #                   --resume instead of declaring the run dead.
    #   elastic_procs — supervisor mode for train.py: spawn this many
    #                   ranks under train.elastic.ElasticTrainRunner,
    #                   which turns a preempted (or added) host into a
    #                   terminate → relaunch-at-new-world-size → resume
    #                   cycle from the last durable checkpoint. 0 = off.
    elastic: bool = False
    elastic_procs: int = 0
    # cross-replica BatchNorm: pmean batch moments over the data axis so
    # normalization uses global-batch statistics. Default off = the
    # reference's per-replica BN under DDP (SURVEY.md §7.2; no SyncBN
    # anywhere in the reference tree)
    sync_bn: bool = False
    # spatial partitioning (parallel/spatial.py): shard image height over a
    # second mesh axis of this size; GSPMD inserts conv halo exchanges and
    # cross-shard BN reductions. 1 = pure data parallel (reference scope).
    # The vision analogue of sequence/context parallelism.
    spatial_devices: int = 1
    # additionally shard image WIDTH over a third mesh axis — context
    # parallelism over both image axes (2-D halo exchanges). Requires the
    # device-resident data plane (the host loader assembles batch x height
    # slabs only).
    spatial_w_devices: int = 1

    # checkpointing (reference: main.py:136-148)
    output_dir: str = "./checkpoint"
    # Checkpoint publish target (ROBUSTNESS.md "canary promotion"):
    #   "live"    — publish into output_dir itself, the dir serving
    #               replicas watch (the pre-pipeline behavior).
    #   "staging" — publish EVERYTHING this trainer writes (best ckpt,
    #               preemption save, rolling history) into
    #               output_dir/staging/ instead; nothing reaches a
    #               hot-reload watcher until the canary promotion
    #               controller (serve/canary.py) vets the checkpoint and
    #               republishes it into the live dir. --resume reads
    #               staging too — the trainer's own newest state lives
    #               there, promoted or not.
    publish: str = "live"
    # Overlapped checkpoint writes (checkpoint.AsyncCheckpointWriter):
    #   "on"  — a save does only the device_get snapshot on the training
    #           thread; serialization + CRC + the fsync'd tmp+rename
    #           commit run on a background writer thread, bounded to ONE
    #           pending save (a newer save supersedes a queued one),
    #           writer errors re-raised on the next trainer interaction,
    #           clean join on shutdown. The best state is additionally
    #           snapshotted ON DEVICE on every improvement so the
    #           pipelined fit's buffer donation can never invalidate it.
    #   "off" — write synchronously inside maybe_checkpoint (the
    #           reference's torch.save timing, main.py:140-147) — the
    #           debugging escape hatch, mirroring --async_input. Both
    #           settings produce bit-identical checkpoint files.
    async_save: str = "on"
    # Rate-limit DISK writes of the best-state snapshot to once per this
    # many epochs (plus the first improvement and a final flush). Even a
    # background ~100 MB device_get stalls training ~14 s when the host
    # link serializes transfers (measured: early epochs improve every
    # epoch, so unthrottled writes add minutes). The on-device snapshot
    # still updates on EVERY improvement — correctness of "best params"
    # is unaffected; only crash-durability granularity changes (SIGTERM
    # preemption still saves exactly). 0 = write on every improvement.
    checkpoint_every: int = 25
    # Rolling checkpoint history (format v2, ROBUSTNESS.md): keep copies
    # of the last N published versions of each checkpoint file as extra
    # restore-fallback candidates. A corrupt current file (torn write,
    # bit rot) then falls back to the previous version instead of the
    # much older other-name checkpoint. 0 = no history.
    keep_last_n: int = 2
    resume: bool = False
    evaluate: bool = False  # load the checkpoint, run eval only, no training

    # Divergence sentinel (ROBUSTNESS.md): what to do when a train step's
    # loss or gradient norm goes non-finite.
    #   "off"      — reference behavior: NaN propagates into the params and
    #                silently poisons every subsequent step (main.py has no
    #                finiteness check anywhere).
    #   "skip"     — discard that step's update via jnp.where (step counter
    #                still advances, so LR schedule/rng stay aligned).
    #   "rollback" — skip, and additionally restore the newest on-disk
    #                checkpoint once `sentinel_budget` consecutive bad
    #                steps accumulate (persistent divergence: a skipped
    #                update cannot fix poisoned BN stats or a bad basin).
    sentinel: str = "skip"
    sentinel_budget: int = 3

    # Observability (OBSERVABILITY.md) — all OFF by default; the hot path
    # pays only a no-op function call per instrumentation site when off.
    #   trace_out: write host-side spans (epoch/step/dispatch/checkpoint/
    #   data-wait) as Chrome/Perfetto trace-event JSON to this file; open
    #   in ui.perfetto.dev or fold with tools/trace_summary.py. Spans also
    #   nest jax.profiler.TraceAnnotation (when this jaxlib has it) so a
    #   --profile device capture lines host spans up with XLA activity.
    trace_out: str = ""
    #   metrics_out: append periodic registry snapshots (counters/gauges/
    #   histograms: step+epoch timing, input-wait, checkpoint IO, sentinel
    #   events) as JSONL to this file, every metrics_every_s seconds, plus
    #   one final line at exit.
    metrics_out: str = ""
    metrics_every_s: float = 10.0

    # misc
    seed: int = 0
    log_every: int = 50
    profile: bool = False  # jax.profiler trace of ~20 steady-state steps

    @property
    def t_max(self) -> int:
        return self.cosine_t_max if self.cosine_t_max is not None else self.epochs


@dataclass
class ServeConfig:
    """Configuration for the inference serving engine (serve.py; see
    SERVING.md for the tuning guidance behind each knob)."""

    model: str = "ResNet18"
    ckpt: str = "./checkpoint"  # Trainer output dir, .msgpack, or ckpt.pth
    num_classes: int = 10

    # multi-tenant zoo serving (SERVING.md "Multi-tenant zoo serving"):
    # a comma-separated tenant list "Name[=ckpt_dir],Name2[=dir2],..."
    # turns this process into a ModelZooServer hosting every named
    # MODEL_REGISTRY model — one engine+batcher pair per resident model,
    # cost-prior-seeded LRU placement under max_resident / zoo_memory_mb,
    # model-id routing on /predict (JSON "model" field, wire-v2 frame
    # field; no model = the FIRST listed tenant). A tenant without
    # "=ckpt_dir" loads <--ckpt>/<Name> when that dir exists, else
    # serves deterministic random-init weights at --seed (bench/drill
    # tenants). Empty = the single-model engine exactly as before.
    models: str = ""
    # resident-set bounds: tenant count (0 = all tenants resident) and
    # estimated weight-bytes budget in MiB (0 = unbounded); eviction is
    # a drain + drop, re-admission a verified AOT-cache import
    max_resident: int = 0
    zoo_memory_mb: float = 0.0

    # engine: one AOT-compiled forward per bucket; partial batches pad up
    # to the nearest bucket, so after warmup NO request shape compiles
    buckets: Tuple[int, ...] = (1, 8, 32, 128)
    # device mesh (mirrors train's --num_devices; 0 = ALL local devices):
    # each bucket program's batch axis is sharded over a 1-D data mesh and
    # the weights are placed replicated, so serve throughput scales with
    # chips. Bucket sizes round up to mesh multiples (SERVING.md). 1 =
    # the single-chip engine exactly as before.
    num_devices: int = 0
    dtype: str = "bfloat16"  # serving compute dtype; logits return fp32
    # int8 bucket lane (SERVING.md "int8 bucket lane"): weight-only
    # symmetric per-channel quantization, AOT-compiled per bucket like
    # any engine. NOT bit-identical to the fp engine — opt-in only,
    # A/B'd for accuracy-vs-throughput (bench.py --serve int8 block) and
    # vetted by the same canary gates before it may serve a fleet.
    int8: bool = False
    mean: Tuple[float, float, float] = (0.4914, 0.4822, 0.4465)
    std: Tuple[float, float, float] = (0.2023, 0.1994, 0.2010)

    # cross-host serving (SERVING.md "Multi-process mesh replica"):
    # mesh_procs > 1 makes this invocation ONE RANK of a logical replica
    # whose device mesh spans that many processes. Rank 0 (the leader)
    # owns the HTTP frontend / micro-batcher and broadcasts every formed
    # batch, weight swap, and shutdown; ranks > 0 run the lock-step
    # follower loop on their main thread and print a small JSON record
    # at drain. mesh_coord is the jax.distributed coordinator address
    # (host:port) every rank must share; mesh_timeout_s bounds dead-peer
    # detection — a rank stuck at a collective longer than this exits
    # non-zero (rc 70) instead of hanging, which is what lets the router
    # evict the logical replica. 1 = single-process serving, exactly as
    # before.
    mesh_procs: int = 1
    mesh_rank: int = 0
    mesh_coord: str = ""
    mesh_timeout_s: float = 60.0

    # micro-batcher: coalesce up to max_batch images per dispatch, waiting
    # at most max_wait_ms after the first queued request; admission
    # control rejects once max_queue images are waiting (backpressure)
    max_batch: int = 0  # 0 = the largest bucket
    max_wait_ms: float = 2.0
    max_queue: int = 1024
    # priority lanes (SERVING.md "priority classes"): bulk-priority
    # requests may occupy at most this share of max_queue and dispatch
    # only when no interactive request is queued — a bulk flood can
    # never starve interactive traffic past its deadline
    bulk_share: float = 0.5
    # continuous batching (SERVING.md): the worker admits newly queued
    # requests into the pad slack of the bucket it is about to dispatch
    # instead of closing admission at batch formation — same compiled
    # programs, strictly more useful rows per device call. --no-continuous
    # restores close-at-formation batching (the A/B escape hatch).
    continuous: bool = True
    # per-request deadline: a request still queued this many ms after
    # submit fails fast with DeadlineExceeded instead of occupying a
    # coalesced batch (an engine stall otherwise strands every queued
    # caller on future.result() forever). 0 = no deadline.
    deadline_ms: float = 0.0

    # checkpoint hot-reload: poll ckpt for a newer best checkpoint and
    # swap params atomically (in-flight requests keep their weights)
    watch: bool = False
    poll_s: float = 1.0

    # synthetic closed-loop load (serve.py demo / bench.py --serve)
    clients: int = 8
    requests: int = 64  # per client
    request_images_max: int = 8  # request size ~ U[1, this]
    duration_s: float = 0.0  # optional wall-clock cap (0 = none)
    seed: int = 0
    # retry-once hedge: a DeadlineExceeded request is resubmitted once
    # (fresh deadline, counted in `hedged` + the serve.hedged counter)
    # before being surfaced as failed — the frontend half of the
    # ROBUSTNESS.md retry/hedging item. --no-hedge fails fast instead.
    hedge: bool = True

    # verify bit-identity of the padded bucket path against a direct
    # unpadded jitted forward before serving (one extra compile)
    verify: bool = False

    # AOT executable cache (SERVING.md): export each compiled bucket
    # program to this directory and import instead of recompiling on the
    # next cold start, so a fresh replica boots in load time with ZERO
    # bucket compiles. Every import is verified by a probe batch checked
    # bit-identical against the entry's stored expectation (and one
    # bucket against a freshly compiled reference) — this container's
    # jaxlib 0.4.36 mis-executes deserialized executables on CPU under
    # donation (ROBUSTNESS.md), so imports are never trusted blindly; a
    # refuted entry is marked poisoned and the engine falls back to
    # compiling. "" = no cache.
    aot_cache: str = ""

    # HTTP frontend (SERVING.md "HTTP frontend & router"): with
    # http_port >= 0 the process serves POST /predict + GET /healthz +
    # live Prometheus GET /metrics over http.server instead of running
    # the in-process load generator, until SIGTERM/SIGINT (graceful
    # drain) or duration_s elapses. 0 binds an ephemeral port (printed
    # on stderr as "==> http: serving on URL" — the router launcher and
    # tests parse it); -1 keeps the PR 1-7 in-process loadgen behavior.
    http_port: int = -1
    http_host: str = "127.0.0.1"
    # which edge serves the port (SERVING.md "Event-loop edge"):
    # "threaded" = thread-per-connection http.server (the PR 8 frontend,
    # simplest to debug); "event" = the non-blocking selectors loop
    # (serve/edge.py) that holds 10k+ keep-alive connections on
    # single-digit threads. Responses are bit-identical either way.
    edge: str = "threaded"

    # observability (OBSERVABILITY.md): host-span trace file, periodic
    # JSONL metrics (queue depth, batch occupancy, admission-to-completion
    # latency, expiries, reloads), and a Prometheus text dump written at
    # exit (the scrape-file convention — the HTTP frontend additionally
    # serves the same text LIVE at GET /metrics)
    trace_out: str = ""
    metrics_out: str = ""
    metrics_every_s: float = 10.0
    prom_out: str = ""


def _add_args(parser: argparse.ArgumentParser, cls=TrainConfig) -> None:
    for f in dataclasses.fields(cls):
        name = "--" + f.name
        if f.type == "bool" or isinstance(f.default, bool):
            parser.add_argument(
                name, action=argparse.BooleanOptionalAction, default=f.default
            )
        elif f.name in ("mean", "std"):
            parser.add_argument(
                name, type=float, nargs=3, default=list(f.default)
            )
        elif isinstance(f.default, tuple):
            # generic variable-length tuple field (e.g. serve buckets)
            elem = type(f.default[0]) if f.default else str
            parser.add_argument(
                name, type=elem, nargs="+", default=list(f.default)
            )
        elif f.name == "cosine_t_max":
            parser.add_argument(name, type=int, default=None)
        else:
            parser.add_argument(name, type=type(f.default), default=f.default)


def _tuplify(cls, d: dict) -> dict:
    for f in dataclasses.fields(cls):
        if isinstance(f.default, tuple):
            d[f.name] = tuple(d[f.name])
    return d


def parse_config(argv=None) -> TrainConfig:
    parser = argparse.ArgumentParser(description="TPU-native CIFAR-10 training")
    _add_args(parser)
    ns = parser.parse_args(argv)
    return TrainConfig(**_tuplify(TrainConfig, vars(ns)))


def parse_serve_config(argv=None) -> ServeConfig:
    parser = argparse.ArgumentParser(
        description="Batched inference serving (see SERVING.md)"
    )
    _add_args(parser, ServeConfig)
    ns = parser.parse_args(argv)
    return ServeConfig(**_tuplify(ServeConfig, vars(ns)))
