"""TrainState: the one pytree that is the whole training run.

The reference's mutable training state is scattered across the net's
parameters/buffers, the optimizer's momentum buffers, the scheduler's epoch
counter, and module-level ``best_acc`` (main.py:25-26,86-89). Here it is a
single immutable pytree: params, BN batch_stats, optimizer state, and step —
checkpointing the full state (strictly more complete than the reference's
3-key dict, SURVEY.md §3.4) and sharding/replication fall out for free.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import unfreeze


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: optax.OptState
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(
            grads, self.opt_state, self.params
        )
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1, params=new_params, opt_state=new_opt_state
        )


def create_train_state(
    model, rng: jax.Array, tx: optax.GradientTransformation, input_shape=(1, 32, 32, 3)
) -> TrainState:
    variables = model.init(rng, jnp.zeros(input_shape, jnp.float32), train=False)
    # plain dicts throughout: model.apply's mutated collections come back as
    # plain dicts, and a FrozenDict-in/dict-out carry would break pytree
    # type stability under lax.scan (the epoch-compiled path)
    params = unfreeze(variables["params"])
    batch_stats = unfreeze(variables.get("batch_stats", {}))
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        apply_fn=model.apply,
        tx=tx,
    )
