"""Preemption-elastic training: ``fit()`` survives world-size changes.

ROADMAP item 3's training half. A fixed-world multi-process job dies
with its first preempted host; an elastic one treats membership change
as a checkpoint-restore-reshard cycle (SERVING.md documents the serving
half; this module is the trainer's):

- Every rank trains normally (``train.py --distributed --elastic``),
  publishing durable checkpoints exactly as before — format v3's
  per-process byte-range shards, commit marker last.
- A **membership change** — a rank killed by preemption, or a new host
  granted — ends the current *generation*: the supervisor
  (:class:`ElasticTrainRunner`) terminates the surviving ranks (SIGTERM
  first, which is ``fit()``'s graceful-stop + preemption-save path;
  SIGKILL bounds a rank wedged in a dead collective), reaps every
  child, and relaunches the world at the new size with ``--resume``.
- The relaunch **resumes, never restarts**: restore accepts the old
  topology's v3 layout into the new world for any M → N (process 0
  reassembles the committed shard set and broadcasts), the elastic
  trainer re-cuts the on-disk layout to the new topology
  (:func:`~pytorch_cifar_tpu.train.checkpoint.reshard_to_world` —
  payload bit-identical, pinned by the reshard tests), and the data
  pipeline re-derives its per-process slices from the new mesh by
  construction (``pipeline.local_slab`` reads the sharding, not a
  cached world size). Training continues from the last durable epoch.

Rank-side contract: a rank that crashes mid-``fit()`` in a
multi-process world exits :data:`ELASTIC_RC` (75, EX_TEMPFAIL) — "my
world broke, resume me" — rather than surfacing a dead-peer collective
error as an unhandled crash. The supervisor treats any abnormal rank
exit as a membership event either way; the code just makes the
post-mortem readable. Restart cycles are bounded by ``max_restarts``:
an actually-broken run (a crash the resume replays deterministically)
fails loudly instead of looping forever.

The supervisor is a plain single-machine process tree here (each rank a
``train.py`` subprocess on a localhost coordinator — the same shape the
multihost test suite drives); on a real cluster the identical loop runs
per-allocation with ranks on different hosts. Every child is waited or
killed on every exit path — the orphan-trainer shape is the same class
graftcheck's ``subprocess-lifecycle`` rule now rejects statically.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

# "membership changed underneath me — relaunch the world and resume"
# (EX_TEMPFAIL: the sysexits code for try-again-later, which is exactly
# the contract; serve's mesh watchdog owns 70 for the serving side)
ELASTIC_RC = 75

# flags the supervisor owns per generation; stripped from the base argv
# so a relaunch can re-derive them for the new world
_OWNED_FLAGS = (
    "--elastic_procs", "--dist_coord", "--dist_procs", "--dist_rank",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def strip_owned_flags(argv: List[str]) -> List[str]:
    """Remove supervisor-owned flags (and their values) plus bare
    ``--distributed``/``--resume`` from a train.py argv: the runner
    re-adds all of them per generation with the current world's
    values."""
    out = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in _OWNED_FLAGS:
            skip = True
            continue
        if any(a.startswith(f + "=") for f in _OWNED_FLAGS):
            continue
        if a in ("--distributed", "--no-distributed", "--resume",
                 "--no-resume", "--elastic", "--no-elastic"):
            continue
        out.append(a)
    return out


class _Rank:
    """One rank subprocess of the current generation: the process plus
    a stderr pump thread (forwards lines with a ``[rank i]`` prefix).
    Always reaped via :meth:`reap` — never orphaned."""

    def __init__(self, rank: int, cmd: List[str], env: dict, cwd: str):
        self.rank = rank
        self.proc = subprocess.Popen(
            cmd,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=cwd,
        )
        self.stdout_tail: List[str] = []
        self._thread = threading.Thread(
            target=self._pump, name=f"elastic-rank-stderr-{rank}",
            daemon=True,
        )
        self._thread.start()

    def _pump(self) -> None:
        for line in self.proc.stderr:
            sys.stderr.write(f"[rank {self.rank}] {line}")

    def alive(self) -> bool:
        return self.proc.poll() is None

    def reap(self, timeout_s: float) -> int:
        """Wait the child out (SIGKILL backstop — a rank wedged in a
        dead gloo collective never answers SIGTERM), drain its stdout
        (the ``best test accuracy`` line rides it), join the pump."""
        try:
            self.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()
        if self.proc.stdout is not None:
            self.stdout_tail = self.proc.stdout.read().splitlines()[-20:]
        self._thread.join(timeout=10)
        return self.proc.returncode


class ElasticTrainRunner:
    """Supervise an elastic multi-process training run (module
    docstring). ``base_argv`` is the train.py argv WITHOUT the
    supervisor-owned flags (:func:`strip_owned_flags` cleans a raw
    one); the runner appends per-generation rendezvous flags and
    ``--resume`` from generation 1 on.

    External membership events: :meth:`add_host` requests a +1 world
    (the "a new host was granted" case — the current generation is
    gracefully stopped via SIGTERM, which is ``fit()``'s
    finish-epoch-and-save path, then relaunched wider). A rank dying
    (preemption, chaos SIGKILL) shrinks the next generation to the
    survivor count, floored at ``min_procs``.
    """

    def __init__(
        self,
        base_argv: List[str],
        procs: int,
        *,
        min_procs: int = 1,
        max_restarts: int = 8,
        grace_s: float = 30.0,
        poll_s: float = 0.2,
        env: Optional[dict] = None,
        cwd: Optional[str] = None,
        resume_first: bool = False,
    ):
        if procs < 1:
            raise ValueError("procs must be >= 1")
        self.base_argv = list(base_argv)
        # the caller asked generation 0 itself to --resume (a supervisor
        # restarted around an existing run); later generations always do
        self.resume_first = bool(resume_first)
        self.world = int(procs)
        self.min_procs = max(int(min_procs), 1)
        self.max_restarts = int(max_restarts)
        self.grace_s = float(grace_s)
        self.poll_s = float(poll_s)
        self.env = dict(os.environ if env is None else env)
        self.cwd = cwd or os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        self.generations: List[dict] = []
        # cross-thread state (tests drive add_host()/pids() from another
        # thread while run() supervises): everything below the lock
        self._lock = threading.Lock()
        self._ranks: List[_Rank] = []
        self._requested_world: Optional[int] = None
        self._current_world = self.world

    # -- external events ----------------------------------------------

    def add_host(self) -> None:
        """Request a +1 world size: the current generation is stopped
        gracefully and relaunched wider — an added host is a resume,
        not a restart."""
        with self._lock:
            self._requested_world = (
                self._requested_world or self._current_world
            ) + 1

    def pids(self) -> Dict[int, int]:
        """Live {rank: pid} of the current generation (chaos drills
        aim their SIGKILLs with this)."""
        with self._lock:
            return {
                r.rank: r.proc.pid for r in self._ranks if r.alive()
            }

    # -- one generation ------------------------------------------------

    def _spawn_generation(self, gen: int, world: int) -> List[_Rank]:
        argv = list(self.base_argv)
        if gen > 0 or self.resume_first:
            argv.append("--resume")
        if world > 1:
            coord = f"127.0.0.1:{_free_port()}"
            argv += [
                "--distributed", "--elastic",
                "--dist_coord", coord,
                "--dist_procs", str(world),
            ]
        else:
            argv += ["--elastic"]
        train_py = os.path.join(self.cwd, "train.py")
        ranks = []
        for rank in range(world):
            cmd = [sys.executable, train_py, *argv]
            if world > 1:
                cmd += ["--dist_rank", str(rank)]
            ranks.append(_Rank(rank, cmd, self.env, self.cwd))
        with self._lock:
            self._ranks = ranks
        print(
            f"==> elastic: generation {gen} world={world} pids="
            f"{[r.proc.pid for r in ranks]}",
            file=sys.stderr,
        )
        return ranks

    def _stop_generation(self, ranks: List[_Rank]) -> List[int]:
        """SIGTERM every live rank (graceful: finish the epoch, write
        the preemption save), then reap with the SIGKILL backstop."""
        for r in ranks:
            if r.alive():
                try:
                    r.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        return [r.reap(self.grace_s) for r in ranks]

    def run(self, timeout_s: float = 3600.0) -> dict:
        """Supervise until a generation completes cleanly (every rank
        exits 0 with no pending membership change), the restart budget
        is exhausted, or the deadline passes. Returns the run record
        (one entry per generation: world size, exit codes, the event
        that ended it)."""
        deadline = time.monotonic() + timeout_s
        world = self.world
        restarts = 0
        completed = False
        best_acc = None
        for gen in range(self.max_restarts + 1):
            with self._lock:
                self._current_world = world
            ranks = self._spawn_generation(gen, world)
            event = "completed"
            while True:
                if time.monotonic() > deadline:
                    event = "timeout"
                    break
                with self._lock:
                    wanted = self._requested_world
                if wanted is not None and wanted != world:
                    event = f"scale:{world}->{wanted}"
                    break
                dead = [r for r in ranks if not r.alive()]
                failed = [
                    r for r in dead if r.proc.returncode != 0
                ]
                if failed:
                    event = "preempted:rank%d:rc%d" % (
                        failed[0].rank, failed[0].proc.returncode,
                    )
                    break
                if len(dead) == len(ranks):
                    break  # everyone exited cleanly on their own
                time.sleep(self.poll_s)
            rcs = self._stop_generation(ranks)
            self.generations.append(
                {"world": world, "rcs": rcs, "event": event}
            )
            print(
                f"==> elastic: generation {gen} ended ({event}) "
                f"rcs={rcs}",
                file=sys.stderr,
            )
            for r in ranks:
                for line in r.stdout_tail:
                    if line.startswith("best test accuracy:"):
                        try:
                            best_acc = float(
                                line.split(":")[1].strip().rstrip("%")
                            )
                        except ValueError:
                            pass
            if event == "timeout":
                break
            if event == "completed" and all(rc == 0 for rc in rcs):
                completed = True
                break
            if event.startswith("scale:"):
                world = max(int(event.split("->")[1]), self.min_procs)
                with self._lock:
                    self._requested_world = None
            else:
                # preemption: the next world is the survivor count —
                # every rank with a clean/elastic exit survives in
                # spirit (its host is still there); the preempted
                # rank's slot is gone
                died = sum(
                    1 for rc in rcs
                    if rc not in (0, ELASTIC_RC, -signal.SIGTERM)
                )
                world = max(world - max(died, 1), self.min_procs)
            restarts += 1
            print(
                f"==> elastic: relaunching world={world} (--resume)",
                file=sys.stderr,
            )
        return {
            "harness": "elastic_train",
            "completed": completed,
            "restarts": restarts,
            "final_world": world,
            "generations": self.generations,
            "best_acc": best_acc,
        }


def run_supervisor(config, argv: Optional[List[str]] = None) -> int:
    """train.py's ``--elastic_procs N`` entry: supervise N ranks of
    THIS command line. Prints the one-JSON-record contract on stdout."""
    raw = list(sys.argv[1:] if argv is None else argv)
    runner = ElasticTrainRunner(
        strip_owned_flags(raw),
        config.elastic_procs,
        resume_first=config.resume,
    )
    record = runner.run()
    print(json.dumps(record))
    return 0 if record["completed"] else 1
