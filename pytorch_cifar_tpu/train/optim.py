"""Optimizer: SGD + momentum + coupled weight decay + per-epoch cosine LR.

Reproduces the reference recipe (main.py:86-89) with torch-exact semantics:

- torch SGD weight_decay is *coupled* L2 added to the gradient **before** the
  momentum buffer update (buf = m*buf + (g + wd*p); p -= lr*buf). The optax
  chain add_decayed_weights -> trace -> scale_by_lr matches that ordering.
  Decay applies to every parameter, including BN scale/bias — the reference
  does not mask anything.
- torch CosineAnnealingLR steps **per epoch**: lr(e) = lr0*(1+cos(pi*e/T))/2.
  We express it as a per-update schedule via floor(step / steps_per_epoch)
  so lr is constant within an epoch, exactly like scheduler.step() placement
  at main.py:154.
- ``t_max`` is independent of ``epochs`` so the reference's T_max=200 vs
  epochs=100 mismatch (main_dist.py:162 vs :28, SURVEY.md §2.5.4) can be
  replicated deliberately via config.cosine_t_max.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def cosine_epoch_schedule(
    lr: float, t_max: int, steps_per_epoch: int
) -> optax.Schedule:
    def schedule(step):
        epoch = jnp.floor_divide(step, steps_per_epoch)
        return 0.5 * lr * (1.0 + jnp.cos(jnp.pi * epoch / t_max))

    return schedule


def make_optimizer(
    lr: float = 0.1,
    momentum: float = 0.9,
    weight_decay: float = 5e-4,
    t_max: int = 200,
    steps_per_epoch: int = 391,
) -> optax.GradientTransformation:
    schedule = cosine_epoch_schedule(lr, t_max, steps_per_epoch)
    return optax.chain(
        optax.add_decayed_weights(weight_decay),
        optax.trace(decay=momentum, nesterov=False),
        optax.scale_by_learning_rate(schedule),  # negates, like torch p -= lr*buf
    )
