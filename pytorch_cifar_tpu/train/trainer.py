"""Trainer: the reference's L4/L5 driver rebuilt TPU-first.

One Trainer covers both reference entry points (single-node main.py:92-154
and distributed main_dist.py:51-261): the device count is a mesh property,
not a code path. Epoch loop semantics match the reference — train over
shuffled shards, full eval, best-acc-gated checkpoint, per-epoch cosine LR
(stepped implicitly via the step-indexed schedule, optim.py).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from typing import Tuple

import jax
import jax.numpy as jnp

from pytorch_cifar_tpu.config import TrainConfig
from pytorch_cifar_tpu.data.cifar10 import load_cifar10, synthetic_cifar10
from pytorch_cifar_tpu.obs import MetricsExporter, MetricsRegistry, trace
from pytorch_cifar_tpu.data.pipeline import (
    Dataloader,
    DeviceDataset,
    eval_batches,
    put_global,
)
from pytorch_cifar_tpu.models import create_model
from pytorch_cifar_tpu.parallel import (
    DATA_AXIS,
    batch_sharding,
    data_parallel_eval_epoch,
    data_parallel_eval_step,
    data_parallel_train_epoch,
    data_parallel_train_step,
    initialize_distributed,
    make_spatial_mesh,
    make_mesh,
    replicate,
    spatial_batch_sharding,
    spatial_eval_epoch,
    spatial_eval_step,
    spatial_label_sharding,
    spatial_train_epoch,
    spatial_train_step,
)
from pytorch_cifar_tpu.ops.dma_gather import rows_dma_tileable
from pytorch_cifar_tpu.parallel.mesh import is_primary
from pytorch_cifar_tpu.train.checkpoint import (
    CKPT_NAME,
    LAST_NAME,
    AsyncCheckpointWriter,
    best_checkpoint_order,
    ensure_staging_dir,
    meta_path,
    remove_stale_last,
    restore_checkpoint,
    save_checkpoint,
)
from pytorch_cifar_tpu.train.optim import make_optimizer
from pytorch_cifar_tpu.train.state import TrainState, create_train_state
from pytorch_cifar_tpu.train.steps import (
    make_eval_epoch,
    make_eval_step,
    make_train_epoch,
    make_train_step,
    zero_metrics,
)
from pytorch_cifar_tpu.utils import progress_bar, set_logger

log = logging.getLogger(__name__)


class Trainer:
    def __init__(self, config: TrainConfig):
        self.config = config
        from pytorch_cifar_tpu.models.common import set_dense_grouped_conv

        # unconditional: a later Trainer in the same process must not
        # inherit an earlier one's flag (process-global trace-time state);
        # set before any tracing — jit traces lazily at first step call
        set_dense_grouped_conv(config.dense_grouped_conv)
        if config.distributed:
            if config.dist_coord and (
                os.environ.get("JAX_PLATFORMS", "").strip().lower()
                == "cpu"
            ):
                # explicit CPU rendezvous (tests, the elastic
                # supervisor): without a cross-process collectives
                # implementation the CPU client silently comes up
                # single-process (same gate as serve.py's mesh ranks)
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
            initialize_distributed(
                config.dist_coord or None,
                config.dist_procs or None,
                config.dist_rank if config.dist_coord else None,
            )
        # rank-aware logging: every rank gets its OWN file handler (a
        # straggler-host post-mortem needs that rank's epoch lines), but
        # non-zero ranks console-log at WARNING — N identical epoch lines
        # interleaved on one terminal help nobody (utils/logging.py)
        pidx = jax.process_index()
        log_name = "train.log" if pidx == 0 else f"train.rank{pidx}.log"
        set_logger(
            f"{config.output_dir}/{log_name}" if config.output_dir else None,
            process_index=pidx,
        )

        # observability (obs/, OBSERVABILITY.md): per-Trainer registry —
        # components own their registries so tests and concurrent Trainers
        # never share counters; CLIs read trainer.obs for export/summary.
        # Metric mutation is always on (it is a lock + float add); the
        # exporter thread and the tracer only exist when flags ask.
        self.obs = MetricsRegistry()
        self._exporter = None
        if config.trace_out:
            trace.install(config.trace_out)

        # -- data ------------------------------------------------------
        if config.synthetic_data:
            tr_x, tr_y, te_x, te_y = synthetic_cifar10(
                n_train=config.synthetic_train_size,
                n_test=config.synthetic_test_size,
            )
        else:
            # strict: a missing dataset raises with remediation advice
            # instead of silently training on synthetic data (accuracy
            # numbers from a silent fallback would be meaningless)
            tr_x, tr_y, te_x, te_y = load_cifar10(
                config.data_dir, synthetic_ok=False
            )
        self.train_images, self.train_labels = tr_x, tr_y
        self.test_images, self.test_labels = te_x, te_y

        # single source of truth for where augmentation runs: host pipeline
        # (native data plane) vs on-device prologue of the train step —
        # derived BEFORE the mesh section because the spatial_w guard needs
        # the effective data-plane decision, not the raw flags
        host_aug = config.host_augment and config.random_crop
        if config.async_input not in ("on", "off"):
            raise ValueError(
                f"async_input must be on/off, got {config.async_input!r}"
            )
        if config.async_save not in ("on", "off"):
            raise ValueError(
                f"async_save must be on/off, got {config.async_save!r}"
            )
        device_data = config.device_data and not host_aug

        # -- mesh ------------------------------------------------------
        self.spatial = max(config.spatial_devices, 1)
        self.spatial_w = max(config.spatial_w_devices, 1)
        if self.spatial > 1 or self.spatial_w > 1:
            # multi-process works too: the loader derives this process's
            # (batch x height) slab from the sharding itself (pipeline.py
            # local_slab) and assembles global arrays from local slabs
            sp_total = self.spatial * self.spatial_w
            total = config.num_devices or len(jax.devices())
            if total % sp_total:
                raise ValueError(
                    f"spatial_devices={self.spatial} x "
                    f"spatial_w_devices={self.spatial_w} must divide the "
                    f"device count {total}"
                )
            for name, v in (
                ("spatial_devices", self.spatial),
                ("spatial_w_devices", self.spatial_w),
            ):
                if 32 % v:
                    # uneven shards: GSPMD silently pads/degrades
                    raise ValueError(
                        f"{name}={v} must divide the 32-pixel CIFAR "
                        "image extent"
                    )
            if self.spatial_w > 1 and not device_data:
                raise ValueError(
                    "spatial_w_devices > 1 requires the device-resident "
                    "data plane (--device_data, no --host_augment): the "
                    "host loader assembles batch x height slabs only"
                )
            self.mesh = make_spatial_mesh(
                data=total // sp_total,
                spatial=self.spatial,
                spatial_w=self.spatial_w,
            )
            n_dev = self.mesh.shape[DATA_AXIS]  # batch divides the data axis
        else:
            self.mesh = make_mesh(config.num_devices)
            n_dev = self.mesh.devices.size
        if (
            self.mesh.devices.size > 1
            and self.mesh.devices.flat[0].platform == "cpu"
        ):
            # XLA:CPU in-process collectives can deadlock-abort when
            # several multi-partition executions are in flight at once
            # (honor_platform_env); serialize dispatch only when a CPU
            # mesh actually has collectives to deadlock — a single-device
            # CPU run keeps pipelining
            jax.config.update("jax_cpu_enable_async_dispatch", False)
        if config.batch_size % n_dev:
            # parity with main_dist.py:112-115's divisibility warning
            log.warning(
                "batch_size %d not divisible by %d devices; rounding down",
                config.batch_size,
                n_dev,
            )
        self.global_batch = max(config.batch_size // n_dev, 1) * n_dev
        eval_bs = max(config.eval_batch_size // n_dev, 1) * n_dev

        if self.spatial > 1 or self.spatial_w > 1:
            sharding = spatial_batch_sharding(self.mesh)
            lbl_sharding = spatial_label_sharding(self.mesh)
        else:
            sharding = batch_sharding(self.mesh)
            lbl_sharding = sharding
        if config.evaluate:
            # eval-only: no shuffling/augmenting loader or train step needed;
            # steps_per_epoch (which anchors the LR schedule restored from
            # the checkpoint) derives from the split size directly
            self.loader = None
            n = tr_x.shape[0]
            self.steps_per_epoch = max(
                n // self.global_batch
                if config.drop_last
                else -(-n // self.global_batch),
                1,
            )
        elif device_data:
            self.loader = DeviceDataset(
                tr_x,
                tr_y,
                batch_size=self.global_batch,
                shuffle=True,
                drop_last=config.drop_last,
                seed=config.seed,
                sharding=sharding,
                label_sharding=lbl_sharding,
                device_perm=config.device_perm,
            )
            self.steps_per_epoch = len(self.loader)
        else:
            self.loader = Dataloader(
                tr_x,
                tr_y,
                batch_size=self.global_batch,
                shuffle=True,
                drop_last=config.drop_last,
                seed=config.seed,
                sharding=sharding,
                label_sharding=lbl_sharding,
                prefetch=config.prefetch,
                async_input=config.async_input == "on",
                host_augment=host_aug,
                augment_flip=config.random_flip,
                registry=self.obs,
            )
            self.steps_per_epoch = len(self.loader)
        # eval data stays device-resident too: the test set is static, so
        # re-transferring it every epoch (the round-1 path) paid the slow
        # H2D link 200 times for the same 30 MB
        self.eval_loader = (
            DeviceDataset(
                te_x,
                te_y,
                batch_size=eval_bs,
                shuffle=False,
                drop_last=False,
                sharding=sharding,
                label_sharding=lbl_sharding,
            )
            if device_data
            else None
        )
        self.eval_bs = eval_bs
        self.sharding = sharding
        self.label_sharding = lbl_sharding

        # -- model/optimizer/state ------------------------------------
        self.model = create_model(
            config.model,
            num_classes=config.num_classes,
            dtype=jnp.bfloat16 if config.amp else None,
        )
        self.tx = make_optimizer(
            lr=config.lr,
            momentum=config.momentum,
            weight_decay=config.weight_decay,
            t_max=config.t_max,
            steps_per_epoch=self.steps_per_epoch,
        )
        state = create_train_state(
            self.model, jax.random.PRNGKey(config.seed), self.tx
        )

        self.start_epoch = 0
        self.best_acc = 0.0
        # Checkpoint publish target (ROBUSTNESS.md "canary promotion"):
        # under --publish staging EVERY checkpoint this trainer writes —
        # best, preemption, history — lands in output_dir/staging/ (the
        # canary pipeline's input; the serving watcher refuses it), and
        # resume reads the same dir, so the trainer's own state never
        # depends on what the promotion controller has vetted so far.
        if config.publish not in ("live", "staging"):
            raise ValueError(
                f"publish must be live/staging, got {config.publish!r}"
            )
        self.ckpt_dir = (
            ensure_staging_dir(config.output_dir)
            if config.publish == "staging"
            else config.output_dir
        )
        if config.resume or config.evaluate:
            # training resume wants the *newest* state: the preemption save
            # (last.msgpack) only when it is actually ahead of the best-params
            # ckpt — a stale one left by an earlier preemption must not roll
            # training back or clobber the true best via its old best_acc.
            # Eval-only always wants the best-accuracy params.
            # restore_checkpoint verifies each candidate's manifest and
            # falls back through the order (and each file's rolling
            # history) on ANY corruption — a truncated last.msgpack no
            # longer kills the resume (ROBUSTNESS.md).
            names = (
                best_checkpoint_order(self.ckpt_dir)
                if config.evaluate
                else self._resume_order(self.ckpt_dir)
            )
            state, self.start_epoch, self.best_acc = restore_checkpoint(
                self.ckpt_dir, state, names=names, registry=self.obs
            )
            log.info(
                "resumed from %s: epoch %d, best_acc %.2f",
                self.ckpt_dir,
                self.start_epoch,
                self.best_acc,
            )
            if config.elastic and not config.evaluate:
                # elastic resume (ROADMAP item 3): the restore above
                # accepted whatever topology wrote the checkpoint (a v3
                # save by M processes restores into any N-world —
                # process 0 reassembles + broadcasts). Re-cut the
                # on-disk layout to THIS world so the new topology's own
                # saves, history, and inspectors see one consistent
                # shard span. Process-0 only; peers already hold the
                # broadcast state and never re-read the files.
                from pytorch_cifar_tpu.train.checkpoint import (
                    reshard_to_world,
                )

                reshard_to_world(self.ckpt_dir, registry=self.obs)
        self.state = replicate(state, self.mesh)

        # -- compiled steps -------------------------------------------
        compute = jnp.bfloat16 if config.amp else jnp.float32
        # on-device augmentation unless the host pipeline already did it
        device_augment = not host_aug
        if config.sentinel not in ("off", "skip", "rollback"):
            raise ValueError(
                f"sentinel must be off/skip/rollback, got {config.sentinel!r}"
            )
        step_kwargs = dict(
            crop=config.random_crop and device_augment,
            flip=config.random_flip and device_augment,
            mean=config.mean,
            std=config.std,
            compute_dtype=compute,
            remat=config.remat,
            # divergence sentinel step half: discard non-finite updates
            # in-graph; the policy half (_apply_sentinel) runs on the
            # per-epoch totals
            skip_nonfinite=config.sentinel != "off",
        )
        eval_kwargs = dict(
            mean=config.mean, std=config.std, compute_dtype=compute
        )
        if self.spatial > 1 or self.spatial_w > 1:
            # GSPMD path: GLOBAL-semantics step (no axis_name — the
            # compiler derives halo exchanges, BN reductions, grad
            # all-reduce from the sharding annotations). BN statistics are
            # globally exact here, so sync_bn has nothing to add.
            wrap_train = lambda fn: spatial_train_step(
                fn, self.mesh, model_name=config.model
            )
            wrap_eval = lambda fn: spatial_eval_step(
                fn, self.mesh, model_name=config.model
            )
            wrap_train_epoch = lambda fn: spatial_train_epoch(
                fn, self.mesh, model_name=config.model
            )
            wrap_eval_epoch = lambda fn: spatial_eval_epoch(
                fn, self.mesh, model_name=config.model
            )
            # NOTE: the spatial path keeps its per-step in-scan gather
            # (see make_train_epoch), which the DMA kernel does not serve
            epoch_kwargs = dict(
                batch_sharding=sharding, label_sharding=lbl_sharding
            )
        else:
            step_kwargs.update(axis_name=DATA_AXIS, sync_bn=config.sync_bn)
            eval_kwargs.update(axis_name=DATA_AXIS)
            wrap_train = lambda fn: data_parallel_train_step(
                fn, self.mesh, model_name=config.model
            )
            wrap_eval = lambda fn: data_parallel_eval_step(
                fn, self.mesh, model_name=config.model
            )
            wrap_train_epoch = lambda fn: data_parallel_train_epoch(
                fn, self.mesh, model_name=config.model
            )
            wrap_eval_epoch = lambda fn: data_parallel_eval_epoch(
                fn, self.mesh, model_name=config.model
            )
            epoch_kwargs = dict(axis_name=DATA_AXIS, n_shards=n_dev)
        if device_data:
            # epoch-compiled path: ONE dispatch per epoch (scan over the
            # device-resident dataset) — per-step dispatch through a
            # remote-TPU transport costs more than the compute it launches
            # (measured ~2 s/epoch of dispatch vs 1.4 s compute;
            # steps.make_train_epoch). The per-step paths below are not
            # built at all: each would be a second multi-minute XLA
            # compile of the same model for no production use.
            self.train_step = None
            self.eval_step = None
            n_eval = te_x.shape[0]
            eval_steps = max(-(-n_eval // eval_bs), 1)
            self.train_epoch_fn = (
                None
                if config.evaluate
                else wrap_train_epoch(
                    make_train_epoch(
                        make_train_step(**step_kwargs),
                        global_batch=self.global_batch,
                        n_data=tr_x.shape[0],
                        num_steps=self.steps_per_epoch,
                        # Pallas compiles for TPU only; CPU meshes (tests,
                        # virtual multi-device CI) and row shapes outside
                        # the kernel's tiling keep the XLA gather. Only
                        # meaningful on the pre-gather (non-spatial) path
                        # — make_train_epoch ignores it otherwise.
                        dma_gather=(
                            config.dma_gather
                            and self.mesh.devices.flat[0].platform == "tpu"
                            and rows_dma_tileable(tr_x.shape[1:])
                        ),
                        **epoch_kwargs,
                    )
                )
            )
            self.eval_epoch_fn = wrap_eval_epoch(
                make_eval_epoch(
                    make_eval_step(**eval_kwargs),
                    global_batch=eval_bs,
                    n_data=n_eval,
                    num_steps=eval_steps,
                    **epoch_kwargs,
                )
            )
        else:
            self.train_epoch_fn = None
            self.eval_epoch_fn = None
            self.train_step = (
                None
                if config.evaluate
                else wrap_train(make_train_step(**step_kwargs))
            )
            self.eval_step = wrap_eval(make_eval_step(**eval_kwargs))
        self.rng = jax.random.PRNGKey(config.seed + 1)
        self._trace_dir = None  # set by fit() for the profiled epoch
        self.profile_steps = 20
        self._stop_requested = False
        # async best-checkpoint machinery: device-side snapshot (taken on
        # every improvement, so the pipelined fit's buffer donation can
        # never invalidate the best state) + the checkpoint module's
        # background commit thread (see maybe_checkpoint; the writer
        # itself lives in checkpoint.AsyncCheckpointWriter — serialization
        # + CRC + fsync'd commit off the training thread, one pending
        # save per checkpoint file, errors re-raised on the next trainer
        # interaction)
        self._copy_state = jax.jit(
            lambda s: jax.tree_util.tree_map(jnp.copy, s)
        )
        self._snapshot = None  # (state copy, epoch, best_acc)
        # Async saves are single-host only: under multihost every process
        # must commit the SAME sequence of sharded publishes, and
        # per-process writers superseding from local queue timing cannot
        # guarantee that (a peer dropping epoch N starves process 0's
        # shard barrier). save_checkpoint enforces the same rule.
        self._ckpt_writer = (
            AsyncCheckpointWriter(registry=self.obs)
            if config.async_save == "on" and jax.process_count() == 1
            else None
        )
        if config.async_save == "on" and self._ckpt_writer is None:
            log.info(
                "--async_save on ignored under multihost (%d processes): "
                "sharded saves commit inline so every host publishes the "
                "same epoch sequence", jax.process_count(),
            )
        # _submitted_epoch (trainer thread only): newest epoch handed to
        # save_checkpoint — throttling + duplicate-submit dedupe.
        # _written_epoch (shared, guarded by _ckpt_lock): newest epoch
        # whose commit actually SUCCEEDED, advanced by the on_commit
        # callback on the writer thread — flush_checkpoints re-submits
        # whenever the snapshot is newer than this, so a failed
        # background commit can never leave a phantom checkpoint.
        self._ckpt_lock = threading.Lock()
        self._submitted_epoch = None
        self._written_epoch = None
        # divergence-sentinel policy state (ROBUSTNESS.md): consecutive
        # non-finite-step counter; totals live in the obs registry now
        # (fault_stats below is a read view over it) and per-step
        # attribution accumulates in _bad_step_indices
        self._consec_bad = 0
        self._bad_step_indices: list = []

    # ------------------------------------------------------------------

    @property
    def fault_stats(self) -> dict:
        """Back-compat view of the sentinel totals (PR 2's ad-hoc dict,
        folded into the obs registry — single source of truth; the keys
        existing callers/tests read are preserved). ``bad_step_indices``
        lists the GLOBAL step index of every skipped update the
        epoch-compiled path attributed (per-step mask in the epoch totals,
        steps.zero_metrics)."""
        return {
            "bad_steps": int(
                self.obs.counter("train.sentinel.bad_steps").value
            ),
            "rollbacks": int(
                self.obs.counter("train.sentinel.rollbacks").value
            ),
            "bad_step_indices": list(self._bad_step_indices),
        }

    @staticmethod
    def _resume_order(output_dir: str):
        """See checkpoint.newest_checkpoint_order (shared rule)."""
        from pytorch_cifar_tpu.train.checkpoint import (
            newest_checkpoint_order,
        )

        return newest_checkpoint_order(output_dir)

    # -- divergence sentinel (policy half; step half is skip_nonfinite) --

    def _apply_sentinel(self, epoch: int, m) -> None:
        """React to the epoch's non-finite step count (the ``nonfinite``
        metric total). Under ``skip`` the in-graph guard already discarded
        the bad updates — this just counts and logs. Under ``rollback``,
        once ``sentinel_budget`` consecutive bad steps accumulate, the
        newest on-disk checkpoint is restored (a skipped update cannot
        repair already-poisoned BN stats or escape a bad basin). On the
        pipelined fit schedule totals arrive one epoch late, so a
        rollback takes effect from the NEXT dispatch — bounded staleness,
        same guarantee."""
        if self.config.sentinel == "off":
            return
        bad = int(round(float(m.get("nonfinite", 0.0))))
        if bad <= 0:
            self._consec_bad = 0
            return
        self._consec_bad += bad
        self.obs.counter("train.sentinel.bad_steps").inc(bad)
        # per-step attribution (closes the ROADMAP "sentinel telemetry"
        # item): the epoch-compiled scan carries a 0/1 slot per step
        # (steps.make_train_epoch), so the log can name WHICH global steps
        # were skipped — rollback/debug granularity of one step, not one
        # epoch. The per-step host loop has no mask (each step's metric is
        # fetched individually there, so attribution was never lost).
        import numpy as np

        mask = m.get("nonfinite_steps")
        bad_steps: list = []
        if mask is not None:
            base = epoch * self.steps_per_epoch
            bad_steps = [
                base + int(i) for i in np.nonzero(np.asarray(mask) > 0)[0]
            ]
            self._bad_step_indices.extend(bad_steps)
            trace.instant(
                "train/sentinel_skip", epoch=epoch, steps=bad_steps
            )
        log.warning(
            "divergence sentinel: %d non-finite step(s) in epoch %d "
            "skipped%s (%d consecutive, policy %s)",
            bad, epoch,
            f" at global step(s) {bad_steps}" if bad_steps else "",
            self._consec_bad, self.config.sentinel,
        )
        if (
            self.config.sentinel == "rollback"
            and self._consec_bad >= self.config.sentinel_budget
        ):
            self._rollback(epoch)

    def _rollback(self, epoch: int) -> None:
        """Restore the newest on-disk checkpoint over the live state."""
        from pytorch_cifar_tpu.train.checkpoint import (
            newest_checkpoint_order,
        )

        if self._ckpt_writer is not None:
            # the newest save may still be in the writer queue; a rollback
            # must restore the actual newest on-disk state, so drain it
            self._ckpt_writer.flush()
        try:
            state, _, _ = restore_checkpoint(
                self.ckpt_dir,
                self.state,
                names=newest_checkpoint_order(self.ckpt_dir),
                registry=self.obs,
            )
        except FileNotFoundError:
            log.warning(
                "sentinel rollback requested at epoch %d but no usable "
                "checkpoint exists; continuing with skipped updates", epoch
            )
            self._consec_bad = 0
            return
        self.state = replicate(state, self.mesh)
        self._consec_bad = 0
        self.obs.counter("train.sentinel.rollbacks").inc()
        trace.instant("train/sentinel_rollback", epoch=epoch)
        log.warning(
            "divergence sentinel: rolled back to the last checkpoint "
            "after %d consecutive non-finite steps (epoch %d)",
            self.config.sentinel_budget, epoch,
        )

    def _timed_batches(self, iterable):
        """Iterate ``iterable`` measuring the host's wait for each batch —
        the input-bound signal: when ``train.input_wait_ms`` rivals step
        time, the pipeline (not the chip) bounds throughput. Near-free:
        two perf_counter reads per batch."""
        wait_hist = self.obs.histogram("train.input_wait_ms")
        wait_total = self.obs.counter("train.input_wait_s")
        it = iter(iterable)
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            dt = time.perf_counter() - t0
            wait_hist.observe(dt * 1e3)
            wait_total.inc(dt)
            yield batch

    def train_epoch(self, epoch: int) -> Tuple[float, float]:
        if self.train_epoch_fn is not None:
            return self._train_epoch_compiled(epoch)
        if self.train_step is None:
            raise RuntimeError(
                "Trainer was built with evaluate=True; training is disabled"
            )
        log.info("\nEpoch: %d", epoch)
        state = self.state
        loss_sum = correct = count = 0.0
        totals = None  # on-device running sums; stays async until displayed
        nb = self.steps_per_epoch
        # fold the epoch into the rng: deterministic, distinct shuffles &
        # augmentations per epoch (the reference's missing set_epoch fix)
        rng = jax.random.fold_in(self.rng, epoch)
        trace_end = min(self.profile_steps, nb) if self._trace_dir else 0
        t0 = time.time()
        tty = sys.stdout.isatty()
        last_sync = 0.0  # wall-clock of the last TTY metric fetch
        epoch_span = trace.span("train/epoch", epoch=epoch, path="step_loop")
        epoch_span.__enter__()
        for i, batch in enumerate(
            self._timed_batches(self.loader.epoch(epoch))
        ):
            if trace_end and i == 0:
                jax.profiler.start_trace(self._trace_dir)
            with trace.span("train/step", step=i):
                # the span times DISPATCH (execution is async) — exactly
                # the host-side cost the per-step path exists to hide
                state, metrics = self.train_step(state, batch, rng)
            if trace_end and i + 1 == trace_end:
                jax.device_get(metrics)  # drain the async queue into the trace
                jax.profiler.stop_trace()
                trace_end = 0
            totals = (
                metrics
                if totals is None
                else jax.tree_util.tree_map(jnp.add, totals, metrics)
            )
            if trace_end:
                # no per-step TTY sync inside the trace window: a device_get
                # each step blocks dispatch run-ahead and the trace would
                # show sync gaps that don't exist in production steps
                continue
            now = time.time() if tty else 0.0
            if (
                i % self.config.log_every == 0
                or i + 1 == nb
                or (tty and now - last_sync >= 0.1)
            ):
                # pulling metrics syncs. On a TTY the bar refreshes at most
                # 10x/s of wall-clock instead of per step: a per-step fetch
                # (the reference's loss.item(), main.py:107) would block
                # dispatch run-ahead on every iteration — through a remote-
                # TPU transport that throttles training to the round-trip
                # latency. 10 Hz is indistinguishable to the eye and costs
                # at most one sync per ~7 steps at ResNet18 speeds.
                last_sync = now
                m = jax.device_get(totals)
                loss_sum = float(m["loss_sum"])
                correct = float(m["correct"])
                count = float(m["count"])
                if is_primary():
                    progress_bar(
                        i,
                        nb,
                        "Loss: %.3f | Acc: %.3f%% (%d/%d)"
                        % (
                            loss_sum / max(count, 1),
                            100.0 * correct / max(count, 1),
                            int(correct),
                            int(count),
                        ),
                        log_every=self.config.log_every,
                    )
        self.state = state
        self._apply_sentinel(epoch, jax.device_get(totals))
        epoch_span.__exit__(None, None, None)
        dt = time.time() - t0
        self._record_epoch_timing(dt, nb)
        imgs = nb * self.global_batch
        log.info(
            "train epoch %d: loss %.4f acc %.2f%% (%.0f img/s)",
            epoch,
            loss_sum / max(count, 1),
            100.0 * correct / max(count, 1),
            imgs / max(dt, 1e-9),
        )
        return loss_sum / max(count, 1), 100.0 * correct / max(count, 1)

    def _record_epoch_timing(self, dt: float, nb: int) -> None:
        """One epoch's wall time into the registry: epoch and derived
        per-step histograms (the step-time p50/p95 the bench obs block
        reports) plus the running epoch-seconds total that anchors the
        input-wait fraction (input_wait_s / epoch_s)."""
        self.obs.counter("train.epochs").inc()
        self.obs.counter("train.epoch_s").inc(dt)
        self.obs.histogram("train.epoch_ms").observe(dt * 1e3)
        self.obs.histogram("train.step_time_ms").observe(
            dt * 1e3 / max(nb, 1)
        )

    def _dispatch_train_epoch(self, epoch: int):
        """Enqueue one whole-epoch computation; return the totals future.

        Host involvement: one ~200 KB permutation upload and one dispatch.
        Nothing here blocks on the device — ``self.state`` advances to the
        (async) output arrays, and the caller chooses when to sync (the
        pipelined ``fit`` loop fetches an epoch's totals only after the
        NEXT epoch is already dispatched, hiding the host round-trip —
        measured ~100 ms through the remote-TPU transport, ~7%/epoch —
        behind device compute)."""
        if self.train_epoch_fn is None:
            raise RuntimeError(
                "Trainer was built with evaluate=True; training is disabled"
            )
        rng = jax.random.fold_in(self.rng, epoch)
        with trace.span("train/dispatch", epoch=epoch):
            perm = self.loader.staged_perm(epoch)
            # num_steps adds the per-step nonfinite mask to the carried
            # totals: the sentinel's per-step attribution on the one-
            # dispatch path (steps.zero_metrics)
            self.state, totals = self.train_epoch_fn(
                self.state,
                zero_metrics(num_steps=self.steps_per_epoch),
                self.loader.images,
                self.loader.labels,
                perm,
                rng,
            )
        return totals

    def _log_train_totals(self, epoch, m, dt) -> Tuple[float, float]:
        self._apply_sentinel(epoch, m)
        nb = self.steps_per_epoch
        self._record_epoch_timing(dt, nb)
        loss_sum = float(m["loss_sum"])
        correct = float(m["correct"])
        count = float(m["count"])
        if is_primary():
            progress_bar(
                nb - 1,
                nb,
                "Loss: %.3f | Acc: %.3f%% (%d/%d)"
                % (
                    loss_sum / max(count, 1),
                    100.0 * correct / max(count, 1),
                    int(correct),
                    int(count),
                ),
                log_every=self.config.log_every,
            )
        log.info(
            "train epoch %d: loss %.4f acc %.2f%% (%.0f img/s)",
            epoch,
            loss_sum / max(count, 1),
            100.0 * correct / max(count, 1),
            count / max(dt, 1e-9),
        )
        return loss_sum / max(count, 1), 100.0 * correct / max(count, 1)

    def _train_epoch_compiled(self, epoch: int) -> Tuple[float, float]:
        """Synchronous one-dispatch epoch (bench/tests and the profiled
        epoch): dispatch, one 12-byte metric fetch, log. The bar renders
        once per epoch — the whole epoch is a single XLA computation
        (~1.4 s for the flagship)."""
        log.info("\nEpoch: %d", epoch)
        t0 = time.time()
        with trace.span("train/epoch", epoch=epoch, path="epoch_compiled"):
            if self._trace_dir:
                jax.profiler.start_trace(self._trace_dir)
            totals = self._dispatch_train_epoch(epoch)
            with trace.span("train/fetch", epoch=epoch):
                m = jax.device_get(totals)  # the one sync of the epoch
            if self._trace_dir:
                jax.profiler.stop_trace()
        return self._log_train_totals(epoch, m, time.time() - t0)

    def eval_epoch(self, epoch: int) -> Tuple[float, float]:
        # Accumulate the psum'd per-batch metrics ON DEVICE and fetch once:
        # a per-batch device_get would cost one blocking D2H round-trip per
        # batch (the reference's loss.item() sync, main.py:107-113, is the
        # same trap), which through a remote-TPU transport dominates the
        # eval epoch. All batches dispatch async; the single fetch at the
        # end drains the queue.
        with trace.span("eval/epoch", epoch=epoch):
            if self.eval_epoch_fn is not None:
                # device-resident test set, whole eval in one dispatch:
                # zero H2D per epoch, one D2H metric fetch
                m = jax.device_get(self._dispatch_eval_epoch())
            else:
                totals = None
                for x, y in eval_batches(
                    self.test_images, self.test_labels, self.eval_bs
                ):
                    batch = put_global(
                        x, y, self.sharding, self.label_sharding
                    )
                    mm = self.eval_step(self.state, batch)
                    totals = (
                        mm
                        if totals is None
                        else jax.tree_util.tree_map(jnp.add, totals, mm)
                    )
                m = jax.device_get(totals)
        return self._log_eval_totals(epoch, m)

    def _log_eval_totals(self, epoch, m) -> Tuple[float, float]:
        loss_sum = float(m["loss_sum"])
        correct = float(m["correct"])
        count = float(m["count"])
        acc = 100.0 * correct / max(count, 1)
        log.info(
            "eval  epoch %d: loss %.4f acc %.2f%%",
            epoch,
            loss_sum / max(count, 1),
            acc,
        )
        return loss_sum / max(count, 1), acc

    def _dispatch_eval_epoch(self):
        """Enqueue the compiled eval epoch on the CURRENT state; return
        the totals future (fetch = sync)."""
        return self.eval_epoch_fn(
            self.state,
            self.eval_loader.images,
            self.eval_loader.labels,
        )

    def maybe_checkpoint(
        self, epoch: int, acc: float, snap_state=None
    ) -> bool:
        """Best-accuracy checkpoint gate (reference semantics,
        main.py:136-148) — but the disk write is decoupled from the
        training loop (--async_save on): the best state is snapshotted on
        DEVICE on every improvement (a device-to-device copy,
        microseconds), disk writes are throttled to --checkpoint_every,
        and an actual write pays only the device_get on this thread —
        serialization, CRC, and the fsync'd commit run on the checkpoint
        module's background writer (checkpoint.AsyncCheckpointWriter;
        ROBUSTNESS.md). ``flush_checkpoints`` (called by fit) guarantees
        the newest snapshot is durably on disk before the run ends.

        ``snap_state``: a device-side copy of the state that achieved
        ``acc``, taken by the caller. The pipelined fit loop must pass it:
        by the time an epoch's eval metrics are fetched, the next epoch's
        dispatch has already donated ``self.state``'s buffers, so the
        snapshot has to be taken at dispatch time."""
        if acc > self.best_acc:
            self.best_acc = acc
            log.info("Saving.. (best acc %.2f%%)", acc)
            if self._ckpt_writer is None:
                save_checkpoint(
                    self.ckpt_dir,
                    self.state if snap_state is None else snap_state,
                    epoch,
                    self.best_acc,
                    keep_last_n=self.config.keep_last_n,
                    registry=self.obs,
                )
                return True
            self._snapshot = (
                self._copy_state(self.state)
                if snap_state is None
                else snap_state,
                epoch,
                self.best_acc,
            )
            self._write_snapshot_async()
            return True
        return False

    def _mark_epoch_written(self, epoch: int) -> None:
        """Record ``epoch`` as durably committed. Runs on the writer
        thread for async saves (hence the lock — graftcheck
        unlocked-shared-mutation), inline for sync ones."""
        with self._ckpt_lock:
            self._written_epoch = epoch

    def _epoch_written(self):
        with self._ckpt_lock:
            return self._written_epoch

    def _submit_snapshot(self, snap) -> None:
        """Hand snapshot ``snap`` to save_checkpoint (async when the
        writer exists, inline otherwise). ``_submitted_epoch`` advances
        immediately (this thread owns it); ``_written_epoch`` advances
        only from the on_commit callback, i.e. once the bytes are
        actually on disk."""
        epoch = snap[1]
        save_checkpoint(
            self.ckpt_dir, snap[0], epoch, snap[2],
            keep_last_n=self.config.keep_last_n,
            registry=self.obs,
            writer=self._ckpt_writer,
            on_commit=lambda: self._mark_epoch_written(epoch),
        )
        self._submitted_epoch = epoch

    def _write_snapshot_async(self) -> None:
        """Hand the current best-state snapshot to the background writer
        (unless throttled). Only the device_get snapshot blocks this
        thread; serialization + commit run on the writer, which keeps at
        most ONE pending save per checkpoint file (a newer snapshot
        supersedes a queued one) and re-raises any background failure on
        the next submit/flush."""
        snap = self._snapshot
        if snap is None or snap[1] == self._submitted_epoch:
            return
        if (
            self._submitted_epoch is not None
            and self.config.checkpoint_every > 0
            and snap[1] - self._submitted_epoch < self.config.checkpoint_every
        ):
            # too soon: keep the device snapshot current but skip the disk
            # write (even the on-thread device_get stalls training ~14 s
            # on a serialized host link); flush_checkpoints writes the
            # final best regardless
            log.info(
                "checkpoint write throttled (epoch %d; last saved best is "
                "epoch %d, next write at epoch >= %d) — a crash before then "
                "resumes from the on-disk state",
                snap[1],
                self._submitted_epoch,
                self._submitted_epoch + self.config.checkpoint_every,
            )
            return
        self._submit_snapshot(snap)

    def flush_checkpoints(self) -> None:
        """Block until the newest best-state snapshot is durably on disk.
        A background write that failed is re-raised here (the writer
        stores it), so persistent failures raise instead of vanishing.
        The re-submit decision compares against ``_written_epoch`` — the
        durably-committed epoch, not the merely-submitted one — so a
        snapshot whose earlier background commit failed (its error
        already consumed by a prior interaction) is written again rather
        than assumed on disk."""
        snap = self._snapshot
        if snap is not None and snap[1] != self._submitted_epoch:
            self._submit_snapshot(snap)
        if self._ckpt_writer is not None:
            try:
                self._ckpt_writer.flush()
            except BaseException:
                # the submitted epoch never became durable: roll the
                # bookkeeping back so a retrying caller re-submits
                # instead of trusting a phantom checkpoint
                self._submitted_epoch = self._epoch_written()
                raise
        if snap is not None and snap[1] != self._epoch_written():
            # earlier commit failed and its stored error was consumed by
            # a previous interaction (the writer raises each error once):
            # write the snapshot synchronously now — this either lands
            # the bytes or raises, never leaves silence
            self._submitted_epoch = self._epoch_written()
            self._submit_snapshot(snap)
            if self._ckpt_writer is not None:
                self._ckpt_writer.flush()

    def fit(self) -> float:
        cfg = self.config
        log.info(
            "==> model %s | %d devices | global batch %d | %d steps/epoch",
            cfg.model,
            self.mesh.devices.size,
            self.global_batch,
            self.steps_per_epoch,
        )
        if cfg.metrics_out:
            # per-rank JSONL (ranks hold distinct registries; one shared
            # file would interleave lines from N processes)
            pidx = jax.process_index()
            mpath = (
                cfg.metrics_out
                if pidx == 0
                else f"{cfg.metrics_out}.rank{pidx}"
            )
            self._exporter = MetricsExporter(
                self.obs, mpath, interval_s=cfg.metrics_every_s
            ).start()
        if cfg.evaluate:
            try:
                _, acc = self.eval_epoch(max(self.start_epoch - 1, 0))
            finally:
                self._close_obs()
            return acc
        # trace a bounded window of the second epoch (steady state, no compile
        # events) — or of the only epoch when just one runs. The reference has
        # no profiler at all (SURVEY.md §5).
        profile_epoch = min(self.start_epoch + 1, cfg.epochs - 1)
        # Preemption safety (SURVEY.md §5: complete checkpoints so preempted
        # TPU jobs resume exactly): SIGTERM requests a graceful stop — finish
        # the current epoch, save the exact latest TrainState as last.msgpack
        # (separate from the best-params ckpt), and return. --resume prefers
        # it. Signal handlers only attach in the main thread.
        import signal

        old_handler = None
        try:
            old_handler = signal.signal(
                signal.SIGTERM, lambda s, f: self.request_stop()
            )
        except ValueError:
            pass
        # Pipelined epoch schedule (compiled data plane only): epoch e's
        # metrics are fetched AFTER epoch e+1 (train + eval) is already
        # enqueued, so the two host round-trips per epoch (~100 ms each
        # through the remote-TPU transport — measured, BENCHMARKS.md round
        # 3) overlap device compute instead of stalling it. The device
        # executes in dispatch order, so train(e+1)'s donation of the
        # state buffers cannot clobber eval(e)'s reads. ``pending`` holds
        # one epoch's (epoch, train totals, eval totals, state snapshot,
        # start time); the snapshot is taken at dispatch time because the
        # buffers are donated away before the metrics arrive.
        pipelined = (
            self.train_epoch_fn is not None and self.eval_epoch_fn is not None
        )
        pending = None
        # finish-to-finish interval: in steady state one finish per epoch,
        # so this is the true wall time an epoch occupies (dispatch-to-
        # fetch would fold the previous epoch's drain into the window and
        # under-report img/s)
        last_mark = time.time()

        def finish(p):
            nonlocal last_mark
            epoch_, tr_totals, ev_totals, snap = p
            with trace.span("train/fetch", epoch=epoch_):
                m = jax.device_get(tr_totals)
            now = time.time()
            self._log_train_totals(epoch_, m, now - last_mark)
            last_mark = now
            _, acc = self._log_eval_totals(epoch_, jax.device_get(ev_totals))
            self.maybe_checkpoint(epoch_, acc, snap_state=snap)

        try:
            for epoch in range(self.start_epoch, cfg.epochs):
                profiled = (
                    cfg.profile and epoch == profile_epoch and is_primary()
                )
                if pipelined and not profiled:
                    log.info("\nEpoch: %d", epoch)
                    tr_totals = self._dispatch_train_epoch(epoch)
                    ev_totals = self._dispatch_eval_epoch()
                    snap = self._copy_state(self.state)
                    if pending is not None:
                        finish(pending)
                    pending = (epoch, tr_totals, ev_totals, snap)
                else:
                    if pending is not None:
                        finish(pending)
                        pending = None
                    if profiled:
                        self._trace_dir = f"{cfg.output_dir}/profile"
                    self.train_epoch(epoch)
                    self._trace_dir = None
                    _, acc = self.eval_epoch(epoch)
                    self.maybe_checkpoint(epoch, acc)
                    last_mark = time.time()  # sync epoch timed itself
                if self._agreed_stop():
                    if pending is not None:
                        finish(pending)
                        pending = None
                    log.info(
                        "stop requested: saving preemption checkpoint at "
                        "epoch %d",
                        epoch,
                    )
                    save_checkpoint(
                        self.ckpt_dir,
                        self.state,
                        epoch,
                        self.best_acc,
                        name=LAST_NAME,
                        keep_last_n=cfg.keep_last_n,
                        registry=self.obs,
                        writer=self._ckpt_writer,
                    )
                    break
            else:
                if pending is not None:
                    finish(pending)
                    pending = None
                # completed normally: a leftover preemption save is now
                # stale; remove it so a routine relaunch with --resume
                # cannot roll training back (process-0 writes only)
                remove_stale_last(self.ckpt_dir)
        finally:
            # A crash mid-epoch must not lose the PREVIOUS epoch's
            # completed eval + best-checkpoint gate (its results are
            # already computed on device; the non-pipelined flow persisted
            # them before starting the next epoch). Guarded so a fetch
            # failure cannot mask the original exception.
            if pending is not None:
                try:
                    finish(pending)
                except Exception:
                    log.exception(
                        "could not finalize epoch %d during unwind",
                        pending[0],
                    )
            # the newest best-state snapshot must be on disk before the
            # process can exit (async writer, maybe_checkpoint); the
            # writer join and obs shutdown run even when the flush
            # re-raises a stored background write error — no thread leak
            # on any exit path
            try:
                self.flush_checkpoints()
            finally:
                if self._ckpt_writer is not None:
                    self._ckpt_writer.close()
                self._close_obs()
                if old_handler is not None:
                    signal.signal(signal.SIGTERM, old_handler)
        return self.best_acc

    def _close_obs(self) -> None:
        """Stop the metrics exporter (writing a final snapshot line) and
        flush the trace file — a crashed/stopped run must still leave a
        valid trace of everything before the stop."""
        if self._exporter is not None:
            self._exporter.stop()
            self._exporter = None
        if self.config.trace_out:
            trace.flush()

    def _agreed_stop(self) -> bool:
        """Multi-host agreement on the stop flag: the per-process SIGTERM
        flag can reach hosts at different epoch boundaries; acting on a
        divergent value strands the other hosts in a collective. Any host
        requesting a stop stops all of them (same pattern as the
        checkpoint-exists broadcast in checkpoint.py)."""
        if jax.process_count() == 1:
            return self._stop_requested
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray(self._stop_requested, np.int32)
        )
        return bool(np.max(flags))

    def request_stop(self) -> None:
        """Ask fit() to stop after the current epoch and write last.msgpack."""
        self._stop_requested = True
