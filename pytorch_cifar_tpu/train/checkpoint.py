"""Checkpoint save/restore of the full TrainState.

Strictly more complete than the reference's 3-key dict (net/acc/epoch,
main.py:140-147): params, BN batch_stats, optimizer state (momentum
buffers), step, epoch, and best_acc all round-trip, so a resumed run
continues the exact momentum + LR trajectory (the reference restarts both,
SURVEY.md §3.4). Same best-accuracy gating semantics (main.py:136-148).

Format: flax msgpack of the array pytree + a JSON sidecar for scalars.
Writes are atomic (tmp + rename) and process-0-only under multi-host SPMD
(rank-0 gating parity, main_dist.py:243).
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from pytorch_cifar_tpu.train.state import TrainState

CKPT_NAME = "ckpt.msgpack"   # best-accuracy checkpoint (reference semantics)
LAST_NAME = "last.msgpack"   # preemption save: exact latest state


def meta_path(output_dir: str, name: str) -> str:
    """Path of the JSON scalar sidecar paired with checkpoint ``name``."""
    return os.path.join(output_dir, os.path.splitext(name)[0] + ".json")


def save_checkpoint(
    output_dir: str,
    state: TrainState,
    epoch: int,
    best_acc: float,
    name: str = CKPT_NAME,
) -> Optional[str]:
    """Write state to ``output_dir`` (process 0 only). Returns the path."""
    if jax.process_index() != 0:
        return None
    os.makedirs(output_dir, exist_ok=True)
    # one logical copy on host; works for replicated or single-device state
    host_state = jax.device_get(
        {
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
            "step": state.step,
        }
    )
    payload = serialization.to_bytes(host_state)
    path = os.path.join(output_dir, name)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)

    meta = {"epoch": int(epoch), "best_acc": float(best_acc)}
    mpath = meta_path(output_dir, name)
    tmp = mpath + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, mpath)
    return path


def newest_checkpoint_order(output_dir: str):
    """Checkpoint preference for training resume: whichever of
    last.msgpack / ckpt.msgpack has the newer epoch in its meta sidecar
    (ties go to the preemption save — it has the exact latest opt state).
    An unreadable/corrupt sidecar counts as epoch -1 instead of raising,
    so a torn write never blocks resume. Shared by Trainer and
    tools/export_torch_checkpoint.py so the rule cannot drift."""

    def epoch_of(name):
        try:
            with open(meta_path(output_dir, name)) as f:
                return int(json.load(f).get("epoch", -1))
        except (OSError, ValueError):
            return -1

    if epoch_of(LAST_NAME) >= epoch_of(CKPT_NAME):
        return [LAST_NAME, CKPT_NAME]
    return [CKPT_NAME, LAST_NAME]


def best_checkpoint_order(output_dir: str = None):
    """Checkpoint preference when the caller wants the BEST params (eval
    and serving, not training resume): the best-accuracy ckpt first, the
    preemption save only as a fallback for runs that never improved past
    epoch 0. Shared by Trainer (--evaluate) and serve/ so the rule cannot
    drift. ``output_dir`` is accepted for signature symmetry with
    :func:`newest_checkpoint_order`; the best-first order is static."""
    return [CKPT_NAME, LAST_NAME]


def remove_stale_last(output_dir: str) -> None:
    """Delete the preemption save (last.msgpack + sidecar) after a run
    COMPLETES normally: a leftover one would make a routine relaunch with
    --resume roll training back to the preemption point. Shared by
    Trainer.fit and tools/accuracy_run.py so the rule cannot drift."""
    if jax.process_index() != 0 or not output_dir:
        return
    for path in (
        os.path.join(output_dir, LAST_NAME),
        meta_path(output_dir, LAST_NAME),
    ):
        try:
            os.remove(path)
        except OSError:
            pass


def restore_checkpoint(
    output_dir: str, state: TrainState, name: str = CKPT_NAME
) -> Tuple[TrainState, int, float]:
    """Load ``output_dir``'s checkpoint into ``state``'s structure.

    Returns (state, start_epoch, best_acc); start_epoch is the next epoch to
    run (saved epoch + 1).
    """
    path = os.path.join(output_dir, name)
    multihost = jax.process_count() > 1
    if multihost:
        from jax.experimental import multihost_utils
    # Saves are process-0-only, so under multi-host without a shared
    # filesystem only process 0 sees the file. Process 0 decides whether a
    # checkpoint exists and every process follows that decision, then the
    # restored arrays are broadcast — no per-host file requirement, and no
    # host can diverge (raise vs proceed) and deadlock the collective job.
    have_ckpt = os.path.isfile(path)
    if multihost:
        have_ckpt = bool(
            multihost_utils.broadcast_one_to_all(
                np.asarray(have_ckpt, np.int32)
            )
        )
    if not have_ckpt:
        raise FileNotFoundError(
            f"no checkpoint at {path!r} — run without --resume first "
            "(parity: main.py:79 asserts ./checkpoint exists)"
        )

    target = {
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
        "opt_state": jax.device_get(state.opt_state),
        "step": np.zeros((), np.int32),
    }
    epoch, best_acc = -1, 0.0
    if jax.process_index() == 0:
        with open(path, "rb") as f:
            payload = f.read()
        restored = serialization.from_bytes(target, payload)
        mpath = meta_path(output_dir, name)
        if os.path.isfile(mpath):
            with open(mpath) as f:
                meta = json.load(f)
            epoch = int(meta.get("epoch", -1))
            best_acc = float(meta.get("best_acc", 0.0))
    else:
        restored = target  # placeholder structure; overwritten by broadcast
    if multihost:
        restored, scalars = multihost_utils.broadcast_one_to_all(
            (restored, np.asarray([epoch, best_acc], np.float64))
        )
        epoch, best_acc = int(scalars[0]), float(scalars[1])

    state = state.replace(
        params=restored["params"],
        batch_stats=restored["batch_stats"],
        opt_state=restored["opt_state"],
        step=restored["step"],
    )
    return state, epoch + 1, best_acc
