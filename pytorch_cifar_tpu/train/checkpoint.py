"""Checkpoint save/restore of the full TrainState.

Strictly more complete than the reference's 3-key dict (net/acc/epoch,
main.py:140-147): params, BN batch_stats, optimizer state (momentum
buffers), step, epoch, and best_acc all round-trip, so a resumed run
continues the exact momentum + LR trajectory (the reference restarts both,
SURVEY.md §3.4). Same best-accuracy gating semantics (main.py:136-148).

Formats (ROBUSTNESS.md):

- **v2** (single-host): flax msgpack of the array pytree + a JSON sidecar
  carrying the scalars AND a payload manifest (CRC32 + size). Writes are
  atomic and durable — tmp file fsync'd before the rename, directory
  fsync'd after.
- **v3** (sharded, multihost default): the SAME msgpack payload split
  into N contiguous byte ranges, one per process — each host writes only
  its own shard (plus a shard sidecar carrying that range's manifest),
  and process 0 publishes the commit marker LAST: the main sidecar,
  which lists every shard with its CRC32/size plus the whole-payload
  manifest. A reader trusts nothing that the commit marker does not
  describe, so an interrupted sharded publish is simply invisible (the
  old commit marker still describes the old complete set). Byte-range
  sharding (rather than pytree-partition sharding) is deliberate: the
  state is replicated, so every host already holds the full serialized
  bytes, the reassembled payload is bit-identical to a v2 save of the
  same state, and restore reuses the exact v2 deserialization path.
  A consequence the elastic-training path (ROADMAP item 3) leans on:
  restore accepts a v3 save written by M processes into a world of N
  for ANY M, N — process 0 reassembles the committed shard set and
  broadcasts, so a preempted or added host is a resume, not a restart —
  and :func:`reshard_checkpoint` re-cuts a committed publish to the new
  topology with the payload bit-identical.

Saves can be **asynchronous**: ``save_checkpoint(..., writer=...)`` does
only the device_get snapshot on the calling thread and hands
serialization + CRC + the fsync'd tmp+rename commit to an
:class:`AsyncCheckpointWriter` background thread — bounded to ONE pending
save *per checkpoint name* (a newer save of the same file supersedes its
queued predecessor; saves of different files — e.g. a preemption
``last.msgpack`` behind a queued best ``ckpt.msgpack`` — queue
independently and are never dropped), with writer errors re-raised on the
next submit/flush and a clean join on shutdown. Multihost sharded saves
always commit inline: per-process writers would make their supersede
decisions from local queue timing, so hosts could publish different
epoch sequences and deadlock process 0's shard barrier.

Restore verifies the manifest(s) and falls back through the candidate
order on ANY corruption (truncated payload, bad msgpack, checksum
mismatch, missing/corrupt shard, absent commit marker), not just a
missing file; under multi-host the winning candidate is process 0's
decision, broadcast to every host, so no host can diverge. v1 checkpoints
(no manifest) still restore, with a logged warning. ``keep_last_n`` keeps
a rolling history of prior checkpoint versions as extra fallback
candidates.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import re
import threading
import time
import zlib
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import numpy as np
from flax import serialization

from pytorch_cifar_tpu import faults
from pytorch_cifar_tpu.obs import trace
from pytorch_cifar_tpu.train.state import TrainState

log = logging.getLogger(__name__)

CKPT_NAME = "ckpt.msgpack"   # best-accuracy checkpoint (reference semantics)
LAST_NAME = "last.msgpack"   # preemption save: exact latest state

MANIFEST_FORMAT = 2
SHARDED_FORMAT = 3

# sharded-publish barrier: how long process 0 waits for every peer's shard
# (and how often it re-polls the shared filesystem) before the commit
# marker may be written. Generous: a peer paying a slow device_get or a
# laggy NFS close must not fail the whole publish.
_SHARD_BARRIER_TIMEOUT_S = 120.0
_SHARD_BARRIER_POLL_S = 0.05


class CheckpointCorrupt(RuntimeError):
    """A checkpoint payload failed verification (checksum/size mismatch,
    missing/corrupt shard, or undeserializable bytes). Restore falls
    back; serving skips the swap."""


def meta_path(output_dir: str, name: str) -> str:
    """Path of the JSON scalar sidecar paired with checkpoint ``name``."""
    return os.path.join(output_dir, os.path.splitext(name)[0] + ".json")


# -- staging / quarantine / promotion (serve/canary.py pipeline) ---------

STAGING_SUBDIR = "staging"
STAGING_MARKER = ".staging"


def staging_dir(output_dir: str) -> str:
    """The staging subdirectory of ``output_dir`` — where a trainer
    running under ``--publish staging`` commits its checkpoints for the
    canary pipeline to vet. Never watched by serving replicas (the
    hot-reload watcher refuses staging dirs outright); only the promotion
    controller reads it (ROBUSTNESS.md "canary promotion")."""
    return os.path.join(output_dir, STAGING_SUBDIR)


def ensure_staging_dir(output_dir: str) -> str:
    """Create the staging dir with its marker file. The marker is what
    lets a watcher (or ckpt_inspect) recognize a staging dir it was
    mistakenly pointed at, independent of the directory's name."""
    path = staging_dir(output_dir)
    os.makedirs(path, exist_ok=True)
    marker = os.path.join(path, STAGING_MARKER)
    if not os.path.exists(marker):
        _atomic_write(
            marker, b"staging checkpoint dir: never serve directly\n"
        )
    return path


def is_staging_dir(path: str) -> bool:
    """A dir is staging when it carries the marker file OR is literally
    named like one — either way its checkpoints are unvetted by
    definition and must never be hot-loaded into a serving engine."""
    return os.path.exists(os.path.join(path, STAGING_MARKER)) or (
        os.path.basename(os.path.abspath(path)) == STAGING_SUBDIR
    )


def quarantine_path(output_dir: str, name: str) -> str:
    """Path of the quarantine tombstone sidecar for checkpoint ``name``."""
    return os.path.join(
        output_dir, os.path.splitext(name)[0] + ".quarantined.json"
    )


def publish_fingerprint(meta: dict) -> Optional[dict]:
    """Identity of one committed publish, independent of format: the
    whole-payload manifest (v2 ``manifest``, v3 ``total``) reduced to
    crc32+size. Quarantine tombstones record it so a tombstone poisons
    exactly ONE publish — a later (different) candidate committed under
    the same file name evaluates fresh."""
    man = (meta or {}).get("manifest") or (meta or {}).get("total")
    if not man:
        return None
    return {
        "crc32": int(man.get("crc32", -1)),
        "size": int(man.get("size", -1)),
    }


def quarantine_checkpoint(
    output_dir: str, name: str, reason: str, meta: Optional[dict] = None,
    extra: Optional[dict] = None,
) -> str:
    """Write the tombstone sidecar marking the CURRENT publish of
    ``name`` rejected (canary verdict, ROBUSTNESS.md "canary promotion").
    The checkpoint files themselves are left in place as evidence; the
    tombstone is what every reader (controller, watcher, ckpt_inspect)
    keys on. One atomic write — a tombstone is never torn."""
    if meta is None:
        meta = _read_meta(output_dir, name)
    rec = {
        "reason": str(reason),
        "epoch": meta.get("epoch"),
        "best_acc": meta.get("best_acc"),
        "fingerprint": publish_fingerprint(meta),
        "at": time.time(),
    }
    rec.update(extra or {})
    path = quarantine_path(output_dir, name)
    _atomic_write(path, json.dumps(rec).encode())
    return path


def read_quarantine(output_dir: str, name: str) -> Optional[dict]:
    """The tombstone record for ``name`` (None when absent/unreadable)."""
    try:
        with open(quarantine_path(output_dir, name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def is_quarantined(
    output_dir: str, name: str, meta: Optional[dict] = None
) -> bool:
    """True when the CURRENT publish of ``name`` carries a matching
    quarantine tombstone. A tombstone whose fingerprint differs from the
    current sidecar's belongs to an older rejected publish and is inert
    (the new candidate deserves a fresh verdict); a fingerprint-less
    comparison (v1 sidecar, torn meta) stays quarantined — when in doubt,
    never serve."""
    tomb = read_quarantine(output_dir, name)
    if tomb is None:
        return False
    fp = tomb.get("fingerprint")
    if not fp:
        return True
    cur = publish_fingerprint(
        meta if meta is not None else _read_meta(output_dir, name)
    )
    return cur is None or cur == fp


def publish_checkpoint(
    src_dir: str, dst_dir: str, name: str = CKPT_NAME,
    extra_meta: Optional[dict] = None,
) -> str:
    """Atomically promote checkpoint ``name`` from ``src_dir`` into
    ``dst_dir`` (the live dir a fleet's hot-reload watchers key on).

    The payload is read VERIFIED from the source (v3 candidates are
    reassembled from their committed shards), so a torn or corrupt
    staging checkpoint can never be promoted; the destination is always
    a single-payload format-v2 publish written payload first, sidecar
    (the commit marker carrying the manifest) LAST — the discipline every
    writer in this repo follows, so a watcher can never observe a torn
    pair. ``extra_meta`` (e.g. the promotion-generation stamp) merges
    into the destination sidecar.

    Raises FileNotFoundError (candidate absent) or CheckpointCorrupt
    like restore would — the promotion controller quarantines on the
    latter."""
    meta = _read_meta(src_dir, name)
    payload = read_verified_payload(src_dir, name, meta)
    os.makedirs(dst_dir, exist_ok=True)
    _preserve_previous_publish(dst_dir, name)
    out_meta = {
        "epoch": meta.get("epoch"),
        "best_acc": meta.get("best_acc"),
        "manifest": payload_manifest(payload),
    }
    out_meta.update(extra_meta or {})
    _atomic_write(os.path.join(dst_dir, name), payload)
    _atomic_write(meta_path(dst_dir, name), json.dumps(out_meta).encode())
    return os.path.join(dst_dir, name)


def prev_publish_name(name: str = CKPT_NAME) -> str:
    """On-disk name of the rollback pair kept beside the live publish:
    the previous generation's payload, preserved by the next
    ``publish_checkpoint``."""
    stem, ext = os.path.splitext(name)
    return f"{stem}.prev{ext}"


def _preserve_previous_publish(dst_dir: str, name: str) -> None:
    """Before overwriting a live publish, keep a VERIFIED copy of the
    incumbent as the ``.prev`` pair — the fleet-wide rollback source for
    generation-aware rolling deploys (SERVING.md "Durable control
    plane"). Payload first, sidecar (the commit marker, carrying the old
    manifest AND the old promotion-generation stamp) last, so the
    rollback pair is itself never observably torn. A torn or corrupt
    incumbent is not worth preserving and is skipped."""
    if not os.path.exists(os.path.join(dst_dir, name)):
        return
    try:
        prev_meta = _read_meta(dst_dir, name)
        prev_payload = read_verified_payload(dst_dir, name, prev_meta)
    except (OSError, ValueError, CheckpointCorrupt):
        return
    prev_name = prev_publish_name(name)
    _atomic_write(os.path.join(dst_dir, prev_name), prev_payload)
    _atomic_write(
        meta_path(dst_dir, prev_name), json.dumps(prev_meta).encode()
    )


def restore_previous_publish(dst_dir: str, name: str = CKPT_NAME) -> bool:
    """Republish the ``.prev`` rollback pair over the live publish —
    the fleet controller's halt-and-roll-back action when a rolling
    deploy's canary gate fails mid-rollout. Verified read (a corrupt
    rollback source raises :class:`CheckpointCorrupt` loudly rather than
    restoring garbage), then the usual payload-first sidecar-last
    publish; the restored sidecar carries the OLD promotion-generation
    stamp, so watchers and the controller's generation probe converge
    back on the pre-rollout generation. Returns False when there is no
    rollback pair to restore."""
    prev_name = prev_publish_name(name)
    if not os.path.exists(os.path.join(dst_dir, prev_name)):
        return False
    prev_meta = _read_meta(dst_dir, prev_name)
    prev_payload = read_verified_payload(dst_dir, prev_name, prev_meta)
    _atomic_write(os.path.join(dst_dir, name), prev_payload)
    _atomic_write(
        meta_path(dst_dir, name), json.dumps(prev_meta).encode()
    )
    return True


def shard_name(name: str, index: int, num_shards: int) -> str:
    """On-disk name of byte-range shard ``index`` of ``name`` (format v3).

    The ``-of-N`` suffix is part of the identity: a save from a different
    process count can never be confused with (or partially overwrite) the
    current one, because every shard name changes with N."""
    stem = os.path.splitext(name)[0]
    return f"{stem}.shard{int(index):05d}-of-{int(num_shards):05d}.msgpack"


def payload_manifest(payload: bytes) -> dict:
    """The sidecar manifest entry that lets any reader verify the payload
    without deserializing it (format v2; v3 reuses it per shard)."""
    return {
        "format": MANIFEST_FORMAT,
        "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
        "size": len(payload),
    }


def verify_checkpoint_payload(payload: bytes, meta: dict, path: str) -> None:
    """Check ``payload`` against the sidecar ``meta``'s manifest.

    Raises :class:`CheckpointCorrupt` on size/checksum mismatch. A sidecar
    without a manifest (format v1, pre-robustness checkpoints) passes with
    a logged warning — old checkpoints must keep restoring."""
    manifest = (meta or {}).get("manifest")
    if not manifest:
        log.warning(
            "checkpoint %s has no manifest (format v1): restoring "
            "unverified — re-save to upgrade to format v2", path
        )
        return
    if len(payload) != int(manifest.get("size", -1)):
        raise CheckpointCorrupt(
            f"{path}: payload is {len(payload)} bytes, manifest says "
            f"{manifest.get('size')} (truncated or torn write)"
        )
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    if crc != int(manifest.get("crc32", -1)):
        raise CheckpointCorrupt(
            f"{path}: payload crc32 {crc:#010x} != manifest "
            f"{int(manifest.get('crc32', -1)):#010x} (bit corruption)"
        )


def _fsync_dir(dirpath: str) -> None:
    """Durably record a rename in its directory. Best-effort: some
    filesystems (FUSE/NFS mounts on TPU hosts) reject directory fsync."""
    try:
        fd = os.open(dirpath or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + fsync + rename + dir fsync: after this returns, a crash at
    ANY point leaves either the old complete file or the new complete
    file — never a zero-length or half-written "atomically" renamed one
    (an os.replace of an unfsynced tmp can journal the rename before the
    data blocks reach disk)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


def _chaos_stall() -> None:
    """Chaos injection point (inert unless armed): sleep between a
    payload/shard write and its sidecar/commit-marker write, so the kill
    drill (tools/chaos_run.py --mode ckpt) can land a SIGKILL
    deterministically inside the torn-pair window."""
    ms = faults.get("ckpt_write_stall")
    if ms:
        time.sleep(float(ms) / 1e3)


# -- rolling history -----------------------------------------------------

def _history_stem(name: str) -> str:
    return os.path.splitext(name)[0]


def _history_name(name: str, epoch: int) -> str:
    return f"{_history_stem(name)}-e{max(int(epoch), 0):05d}.msgpack"


def history_names(output_dir: str, name: str):
    """Rolling-history checkpoint names for ``name``, newest epoch first —
    the extra fallback candidates behind the primary file. Shard files
    (``<stem>-eNNNNN.shard*``) are not history entries themselves: they
    belong to the v3 history commit marker that lists them."""
    pat = re.compile(
        re.escape(_history_stem(name)) + r"-e(\d+)\.msgpack$"
    )
    found = []
    for path in glob.glob(
        os.path.join(output_dir, _history_stem(name) + "-e*.msgpack")
    ):
        m = pat.search(os.path.basename(path))
        if m:
            found.append((int(m.group(1)), os.path.basename(path)))
    # v3 history entries have no <hist>.msgpack payload file — only the
    # commit sidecar and shards — so also scan the sidecars
    spat = re.compile(re.escape(_history_stem(name)) + r"-e(\d+)\.json$")
    for path in glob.glob(
        os.path.join(output_dir, _history_stem(name) + "-e*.json")
    ):
        m = spat.search(os.path.basename(path))
        if m:
            entry = (int(m.group(1)), _history_name(name, int(m.group(1))))
            if entry not in found:
                found.append(entry)
    return [n for _, n in sorted(set(found), reverse=True)]


def _remove_candidate_files(output_dir: str, name: str) -> None:
    """Delete every file belonging to checkpoint candidate ``name``:
    payload, sidecar, and any v3 shards + shard sidecars."""
    stem = os.path.splitext(name)[0]
    targets = [os.path.join(output_dir, name), meta_path(output_dir, name)]
    for sp in glob.glob(
        os.path.join(output_dir, stem + ".shard*-of-*.msgpack")
    ):
        targets.append(sp)
        targets.append(meta_path(output_dir, os.path.basename(sp)))
    for p in targets:
        try:
            os.remove(p)
        except OSError:
            pass


def _prune_history(output_dir: str, name: str, keep_last_n: int) -> None:
    for stale in history_names(output_dir, name)[keep_last_n:]:
        _remove_candidate_files(output_dir, stale)


def _update_history(
    output_dir: str, name: str, epoch: int, payload: bytes, meta: dict,
    keep_last_n: int,
) -> None:
    """Publish a history copy of the just-written checkpoint and prune the
    oldest entries beyond ``keep_last_n``. Copies (not hardlinks): a
    separate inode means corruption of the primary file cannot reach its
    history fallback."""
    hname = _history_name(name, epoch)
    _atomic_write(os.path.join(output_dir, hname), payload)
    _atomic_write(
        meta_path(output_dir, hname),
        json.dumps(meta).encode(),
    )
    _prune_history(output_dir, name, keep_last_n)


# -- async writer --------------------------------------------------------

class AsyncCheckpointWriter:
    """Background commit thread for :func:`save_checkpoint`.

    Contract (ROBUSTNESS.md "async writer"):

    - **Bounded to one pending save per checkpoint name.** The queue
      holds at most one not-yet-started commit per submit ``key`` (the
      checkpoint file name); submitting while one with the same key is
      queued replaces it (the newer snapshot supersedes — only the
      newest state of a given file matters for durability, and an
      unbounded queue would let a fast improvement streak pile up
      minutes of serialized writes). Jobs with DIFFERENT keys queue
      independently in submit order: a preemption ``last.msgpack`` save
      can never displace a queued best ``ckpt.msgpack`` commit — every
      distinct file promised a write gets one.
    - **Errors re-raise on the next trainer interaction.** A failed
      background commit (disk full, dir deleted, barrier timeout) is
      stored and re-raised by the next :meth:`submit`, :meth:`flush`, or
      :meth:`close` — never silently dropped, never a phantom checkpoint.
    - **Clean join on shutdown.** :meth:`close` drains whatever is
      pending, joins the thread, and re-raises any stored error. The
      thread is started lazily on first submit, so a writer that never
      sees a save costs nothing.

    Every cross-thread attribute is mutated only under ``self._cond``
    (graftcheck ``unlocked-shared-mutation`` passes by construction).
    """

    def __init__(self, registry=None, name: str = "ckpt-writer"):
        self._cond = threading.Condition()
        # one pending slot per submit key (insertion-ordered: commits of
        # distinct checkpoint files run FIFO; a re-submitted key keeps
        # its place in line but carries the newer closure)
        self._pending: dict = {}
        self._busy = False
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self._obs = registry
        self._name = name

    def _publish_depth_locked(self) -> None:
        if self._obs is not None:
            self._obs.gauge("checkpoint.pending_saves").set(
                len(self._pending) + (1 if self._busy else 0)
            )

    def _raise_pending_error_locked(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def submit(self, job: Callable[[], Any], key: str = "") -> None:
        """Queue ``job`` (a commit closure) for the background thread.
        Replaces any still-queued older job with the same ``key`` (the
        checkpoint file name — jobs for different files never supersede
        each other); re-raises a stored error from an earlier failed
        commit."""
        with self._cond:
            self._raise_pending_error_locked()
            if key in self._pending:
                if self._obs is not None:
                    self._obs.counter("checkpoint.superseded_saves").inc()
            self._pending[key] = job
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True
                )
                self._thread.start()
            self._publish_depth_locked()
            self._cond.notify_all()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stopping:
                    self._cond.wait()
                if not self._pending:
                    return
                key = next(iter(self._pending))
                job = self._pending.pop(key)
                self._busy = True
                self._publish_depth_locked()
            t0 = time.perf_counter()
            err = None
            try:
                job()
            except BaseException as e:  # stored, re-raised on interaction
                err = e
            if self._obs is not None:
                self._obs.histogram("checkpoint.writer_ms").observe(
                    (time.perf_counter() - t0) * 1e3
                )
            with self._cond:
                if err is not None and self._error is None:
                    self._error = err
                self._busy = False
                self._publish_depth_locked()
                self._cond.notify_all()

    def flush(self) -> None:
        """Block until every submitted commit is durably on disk;
        re-raise any background error."""
        with self._cond:
            while self._pending or self._busy:
                self._cond.wait()
            self._raise_pending_error_locked()

    def close(self) -> None:
        """Drain pending work, join the thread, re-raise any error. The
        writer is reusable afterwards (a later submit restarts it)."""
        with self._cond:
            self._stopping = True
            t = self._thread
            self._thread = None
            self._cond.notify_all()
        if t is not None:
            t.join()
        with self._cond:
            self._stopping = False
            self._raise_pending_error_locked()


# -- save ----------------------------------------------------------------

def _regress_leaf(scale: float, seed: int = 0xC0FFEE):
    """Leaf perturber for the ckpt_regress fault: add N(0, scale*std)
    noise to every float leaf (std floor 1.0 keeps zero-initialized
    leaves perturbed too). Values stay finite — the checkpoint loads,
    verifies, and serves; only its OUTPUTS are wrong. Deterministic per
    leaf shape+order via one shared stream."""
    rs = np.random.RandomState(seed)

    def perturb(a):
        arr = np.asarray(a)
        if not np.issubdtype(arr.dtype, np.floating):
            return a
        sd = float(arr.std()) or 1.0
        return (arr + rs.normal(0.0, scale * sd, size=arr.shape)).astype(
            arr.dtype
        )

    return perturb


def _write_unsharded(
    output_dir: str, name: str, payload: bytes, epoch: int,
    best_acc: float, keep_last_n: int,
) -> str:
    """Format v2 commit: payload first, sidecar (carrying the payload's
    manifest) second — a reader that verifies the manifest therefore
    never trusts a payload/sidecar pairing from two different
    publishes (serve/reload.py gates its hot swap on exactly this)."""
    path = os.path.join(output_dir, name)
    with trace.span("checkpoint/write", bytes=len(payload)):
        _atomic_write(path, payload)
        _chaos_stall()
        meta = {
            "epoch": int(epoch),
            "best_acc": float(best_acc),
            "manifest": payload_manifest(payload),
        }
        _atomic_write(
            meta_path(output_dir, name), json.dumps(meta).encode()
        )
        if keep_last_n > 0:
            _update_history(
                output_dir, name, epoch, payload, meta, keep_last_n
            )
    return path


def _await_shard(
    output_dir: str, sname: str, epoch: int, deadline: float
) -> dict:
    """Wait until shard ``sname`` of THIS publish is durably on disk:
    its sidecar's epoch matches and the shard bytes verify against the
    sidecar manifest. Returns the shard manifest. The epoch check is what
    keeps a stale same-name shard from a previous publish out of the
    commit; atomic renames mean no torn intermediate is ever visible."""
    spath = os.path.join(output_dir, sname)
    while True:
        try:
            with open(meta_path(output_dir, sname)) as f:
                smeta = json.load(f)
            if (
                int(smeta.get("epoch", -2)) == int(epoch)
                and smeta.get("manifest")
            ):
                with open(spath, "rb") as f:
                    blob = f.read()
                verify_checkpoint_payload(blob, smeta, spath)
                return smeta["manifest"]
        except (OSError, ValueError, CheckpointCorrupt):
            pass
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"sharded checkpoint barrier timed out waiting for "
                f"{sname} (epoch {epoch}) — peer process dead or "
                f"checkpoint dir not shared?"
            )
        time.sleep(_SHARD_BARRIER_POLL_S)


def _write_sharded(
    output_dir: str, name: str, payload: bytes, epoch: int,
    best_acc: float, keep_last_n: int, num_shards: int,
    shard_index: Optional[int],
) -> Optional[str]:
    """Format v3 commit (orbax-style, ROBUSTNESS.md): every process
    writes its own byte-range shard + shard sidecar; process 0 waits for
    the full set (filesystem barrier — no collectives, so the writer
    thread stays gloo-safe) and then publishes the commit marker LAST.
    ``shard_index`` None = this process writes every shard (single-process
    sharded save, used by tests and tools)."""
    n = int(num_shards)
    chunk = max(1, -(-len(payload) // n))
    names = [shard_name(name, k, n) for k in range(n)]
    hname = _history_name(name, epoch) if keep_last_n > 0 else None
    mine = range(n) if shard_index is None else (int(shard_index),)
    for k in mine:
        blob = payload[k * chunk:(k + 1) * chunk]
        smeta = {"epoch": int(epoch), "manifest": payload_manifest(blob)}
        _atomic_write(os.path.join(output_dir, names[k]), blob)
        _chaos_stall()
        _atomic_write(
            meta_path(output_dir, names[k]), json.dumps(smeta).encode()
        )
        if hname is not None:
            hs = shard_name(hname, k, n)
            _atomic_write(os.path.join(output_dir, hs), blob)
            _atomic_write(
                meta_path(output_dir, hs), json.dumps(smeta).encode()
            )
    if shard_index not in (None, 0):
        return None  # peers are done; process 0 owns the commit marker
    deadline = time.monotonic() + _SHARD_BARRIER_TIMEOUT_S
    manifests = []
    for k in range(n):
        manifests.append(_await_shard(output_dir, names[k], epoch, deadline))
        if hname is not None:
            _await_shard(
                output_dir, shard_name(hname, k, n), epoch, deadline
            )
    meta = {
        "format": SHARDED_FORMAT,
        "epoch": int(epoch),
        "best_acc": float(best_acc),
        "total": payload_manifest(payload),
        "shards": [
            {"name": nm, "crc32": mf["crc32"], "size": mf["size"]}
            for nm, mf in zip(names, manifests)
        ],
    }
    _chaos_stall()
    _atomic_write(meta_path(output_dir, name), json.dumps(meta).encode())
    if hname is not None:
        hmeta = dict(meta)
        hmeta["shards"] = [
            {
                "name": shard_name(hname, k, n),
                "crc32": mf["crc32"],
                "size": mf["size"],
            }
            for k, mf in enumerate(manifests)
        ]
        _atomic_write(
            meta_path(output_dir, hname), json.dumps(hmeta).encode()
        )
        _prune_history(output_dir, name, keep_last_n)
    return os.path.join(output_dir, name)


def _commit_host_state(
    output_dir: str, name: str, host_state, epoch: int, best_acc: float,
    keep_last_n: int, registry, num_shards: int,
    shard_index: Optional[int], t0: float,
) -> Optional[str]:
    """Serialize + CRC + fsync'd atomic publish of an already-fetched
    host snapshot — the half of a save that runs on the writer thread
    under ``--async_save on`` (and inline under sync)."""
    payload = serialization.to_bytes(host_state)
    if num_shards > 1:
        with trace.span(
            "checkpoint/write", bytes=len(payload), shards=num_shards
        ):
            path = _write_sharded(
                output_dir, name, payload, epoch, best_acc, keep_last_n,
                num_shards, shard_index,
            )
    else:
        path = _write_unsharded(
            output_dir, name, payload, epoch, best_acc, keep_last_n
        )
    if registry is not None and shard_index in (None, 0):
        registry.counter("checkpoint.saves").inc()
        registry.counter("checkpoint.saved_bytes").inc(len(payload))
        registry.histogram("checkpoint.save_ms").observe(
            (time.perf_counter() - t0) * 1e3
        )
    return path


def save_checkpoint(
    output_dir: str,
    state: TrainState,
    epoch: int,
    best_acc: float,
    name: str = CKPT_NAME,
    keep_last_n: int = 0,
    registry=None,
    writer: Optional[AsyncCheckpointWriter] = None,
    num_shards: Optional[int] = None,
    on_commit: Optional[Callable[[], None]] = None,
) -> Optional[str]:
    """Write state to ``output_dir``. Returns the primary path on the
    committing process (process 0), None elsewhere.

    Single-host writes format v2 (process 0 only). Under multihost every
    process participates in a format-v3 sharded publish: each host writes
    its own byte-range shard and process 0 writes the commit marker last
    (``_write_sharded``). ``num_shards`` > 1 forces a v3 layout from a
    single process (tests/tools); under multihost it must equal the
    process count.

    ``writer`` (:class:`AsyncCheckpointWriter`, optional): only the
    device_get snapshot runs on the calling thread — serialization, CRC,
    and the fsync'd commit move to the writer thread, so the trainer's
    save stall shrinks to the snapshot cost. ``registry`` records
    ``checkpoint.save_stall_ms`` (calling-thread blocked time) either
    way; the commit half records saves/bytes/``save_ms`` on completion
    and the writer records ``checkpoint.writer_ms`` (OBSERVABILITY.md).

    ``on_commit`` (optional): called once, with no arguments, after the
    commit half succeeds — on the writer thread for async saves, inline
    otherwise. Never called for a failed or superseded commit, so the
    trainer can track which epoch is *durably* on disk rather than
    merely submitted.

    A multihost sharded publish always commits inline even when a
    ``writer`` is passed: each process's writer would decide superseding
    from its LOCAL queue timing, so hosts could commit different epoch
    sequences and starve process 0's shard barrier (it would wait the
    full timeout for shards a peer's writer silently dropped).
    """
    pidx, pcount = jax.process_index(), jax.process_count()
    n = int(num_shards) if num_shards else (pcount if pcount > 1 else 1)
    if pcount > 1 and n > 1 and n != pcount:
        raise ValueError(
            f"num_shards={n} must equal the process count ({pcount}) "
            "under multihost — each process writes exactly its own shard"
        )
    if n <= 1 and pidx != 0:
        return None
    shard_index = pidx if (pcount > 1 and n > 1) else None
    if writer is not None and shard_index is not None:
        log.warning(
            "async checkpoint writer ignored for the multihost sharded "
            "save of %s: per-process supersede decisions would desync "
            "the shard barrier; committing inline", name,
        )
        writer = None
    t0 = time.perf_counter()
    with trace.span(
        "checkpoint/save", file=name, epoch=int(epoch), shards=n
    ):
        os.makedirs(output_dir, exist_ok=True)
        # one logical copy on host; works for replicated or single-device
        # state. This is the fast on-thread snapshot: the state buffers
        # are free to be donated/overwritten the moment it returns.
        with trace.span("checkpoint/device_get"):
            host_state = jax.device_get(
                {
                    "params": state.params,
                    "batch_stats": state.batch_stats,
                    "opt_state": state.opt_state,
                    "step": state.step,
                }
            )
        # chaos injection point (inert unless armed): a ckpt_regress
        # fault perturbs the snapshot's params so the PUBLISHED
        # checkpoint is plausible-but-wrong — finite weights, valid
        # manifest, wrong outputs — the failure class only the canary
        # pipeline's output-level vetting can catch (torn/bitflipped
        # files are CRC-visible; this is not). ROBUSTNESS.md.
        regress = faults.ckpt_regress_scale()
        if regress:
            log.warning(
                "ckpt_regress fault armed: perturbing %s params "
                "(scale %.2f) before publish", name, regress,
            )
            host_state["params"] = jax.tree_util.tree_map(
                _regress_leaf(regress), host_state["params"]
            )

        def commit():
            r = _commit_host_state(
                output_dir, name, host_state, epoch, best_acc,
                keep_last_n, registry, n, shard_index, t0,
            )
            if on_commit is not None:
                on_commit()
            return r

        if writer is None:
            commit()
        else:
            writer.submit(commit, key=name)
    if registry is not None:
        registry.histogram("checkpoint.save_stall_ms").observe(
            (time.perf_counter() - t0) * 1e3
        )
    return os.path.join(output_dir, name) if pidx == 0 else None


def committed_shard_count(output_dir: str, name: str) -> Optional[int]:
    """Shard count of the CURRENT committed publish of ``name``: the
    length of the commit marker's shard list for a v3 publish, 1 for a
    monolithic v1/v2 publish, None when no committed publish exists."""
    meta = _read_meta(output_dir, name)
    if not meta:
        return None
    shards = meta.get("shards")
    if shards:
        return len(shards)
    if os.path.isfile(os.path.join(output_dir, name)):
        return 1
    return None


def reshard_checkpoint(
    output_dir: str,
    name: str = CKPT_NAME,
    num_shards: int = 1,
    registry=None,
) -> str:
    """Re-cut a committed publish of ``name`` to ``num_shards``
    byte-range shards — the elastic-training topology change
    (ROADMAP item 3): a v3 save written by M processes becomes a save
    laid out for an N-process world, with the PAYLOAD BIT-IDENTICAL
    (byte-range sharding is a pure layout property; the reassembled
    bytes never change, which the reshard tests pin).

    Crash-safe by the same commit-marker-last discipline every writer
    here follows: the new layout's files land first and the sidecar
    (which atomically REPLACES the old one) describes only complete
    sets — a crash at any point leaves a restorable checkpoint. The
    superseded layout's files are removed only after the new commit
    marker is durable. ``num_shards <= 1`` produces a v2 monolithic
    publish. Raises FileNotFoundError when no committed publish of
    ``name`` exists, CheckpointCorrupt when it exists but fails
    verification (nothing is rewritten from unverified bytes).
    """
    meta = _read_meta(output_dir, name)
    old_n = committed_shard_count(output_dir, name)
    if old_n is None:
        raise FileNotFoundError(
            f"no committed publish of {name!r} in {output_dir!r}"
        )
    n = max(int(num_shards), 1)
    payload = read_verified_payload(output_dir, name, meta)
    if old_n == n:
        return os.path.join(output_dir, name)
    epoch = int(meta.get("epoch", -1))
    best_acc = float(meta.get("best_acc", 0.0))
    old_shards = [s["name"] for s in (meta.get("shards") or ())]
    with trace.span(
        "checkpoint/reshard", file=name, shards_from=old_n, shards_to=n
    ):
        if n > 1:
            _write_sharded(
                output_dir, name, payload, epoch, best_acc,
                keep_last_n=0, num_shards=n, shard_index=None,
            )
        else:
            _write_unsharded(
                output_dir, name, payload, epoch, best_acc, keep_last_n=0
            )
    # the new commit marker is durable; retire the superseded layout.
    # v3 -> smaller/larger N: the old -of-M names can never collide with
    # -of-N ones (the span is part of the identity), so this is cleanup,
    # not correctness. v2 -> v3: the monolithic payload file goes too
    # (the new sidecar lists shards; a reader never opens it again).
    stale = [s for s in old_shards]
    if old_n == 1 and n > 1:
        stale.append(name)
    for sn in stale:
        for p in (
            os.path.join(output_dir, sn),
            meta_path(output_dir, sn) if sn != name else None,
        ):
            if p is None:
                continue
            try:
                os.remove(p)
            except OSError:
                pass
    if registry is not None:
        registry.counter("checkpoint.reshards").inc()
    log.info(
        "resharded %s/%s: %d -> %d shard(s), payload bit-identical",
        output_dir, name, old_n, n,
    )
    return os.path.join(output_dir, name)


def reshard_to_world(output_dir: str, registry=None) -> None:
    """Re-cut every committed checkpoint the resume path may read
    (best + preemption save) to THIS world's topology — one shard per
    process under multihost, the monolithic v2 layout single-host.
    Called by the trainer's elastic resume (process 0 only): after a
    membership change, restore already accepted the old topology's
    layout (any M into any N — process 0 reassembles and broadcasts);
    this step re-cuts the on-disk layout so the new world's own
    incremental saves and inspectors see one consistent topology."""
    if jax.process_index() != 0:
        return
    world = jax.process_count()
    n = world if world > 1 else 1
    for name in (CKPT_NAME, LAST_NAME):
        old = committed_shard_count(output_dir, name)
        if old is None or old == n:
            continue
        try:
            reshard_checkpoint(output_dir, name, n, registry=registry)
        except CheckpointCorrupt as e:
            # a corrupt candidate is restore's business (it falls back);
            # resharding must not turn a resumable dir into a crash
            log.warning(
                "elastic reshard skipped corrupt candidate %s (%s)",
                name, e,
            )


def newest_checkpoint_order(output_dir: str):
    """Checkpoint preference for training resume: whichever of
    last.msgpack / ckpt.msgpack has the newer epoch in its meta sidecar
    (ties go to the preemption save — it has the exact latest opt state).
    An unreadable/corrupt sidecar counts as epoch -1 instead of raising,
    so a torn write never blocks resume. Shared by Trainer and
    tools/export_torch_checkpoint.py so the rule cannot drift."""

    def epoch_of(name):
        try:
            with open(meta_path(output_dir, name)) as f:
                return int(json.load(f).get("epoch", -1))
        except (OSError, ValueError):
            return -1

    if epoch_of(LAST_NAME) >= epoch_of(CKPT_NAME):
        return [LAST_NAME, CKPT_NAME]
    return [CKPT_NAME, LAST_NAME]


def best_checkpoint_order(output_dir: str = None):
    """Checkpoint preference when the caller wants the BEST params (eval
    and serving, not training resume): the best-accuracy ckpt first, the
    preemption save only as a fallback for runs that never improved past
    epoch 0. Shared by Trainer (--evaluate) and serve/ so the rule cannot
    drift. ``output_dir`` is accepted for signature symmetry with
    :func:`newest_checkpoint_order`; the best-first order is static."""
    return [CKPT_NAME, LAST_NAME]


def remove_stale_last(output_dir: str) -> None:
    """Delete the preemption save (last.msgpack + sidecar + any v3
    shards) after a run COMPLETES normally: a leftover one would make a
    routine relaunch with --resume roll training back to the preemption
    point. Shared by Trainer.fit and tools/accuracy_run.py so the rule
    cannot drift."""
    if jax.process_index() != 0 or not output_dir:
        return
    stale = [LAST_NAME] + history_names(output_dir, LAST_NAME)
    for name in stale:
        _remove_candidate_files(output_dir, name)


# -- restore -------------------------------------------------------------

def _read_meta(output_dir: str, name: str) -> dict:
    try:
        with open(meta_path(output_dir, name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def read_verified_payload(
    output_dir: str, name: str, meta: Optional[dict] = None
) -> bytes:
    """The verified msgpack payload of checkpoint candidate ``name`` —
    reassembled from v3 shards when the sidecar is a sharded commit
    marker, read + manifest-verified directly otherwise (v1/v2).

    FileNotFoundError means "candidate absent" — including a v3 publish
    whose commit marker was never written (torn shards are invisible
    without it, by construction). CheckpointCorrupt means "candidate
    exists but is unusable": truncated/mismatched payload, or a COMMITTED
    shard that is missing or fails its CRC. Shared by restore and
    serve's ``load_checkpoint_trees`` so the format rules cannot drift.
    """
    if meta is None:
        meta = _read_meta(output_dir, name)
    path = os.path.join(output_dir, name)
    shards = (meta or {}).get("shards")
    if shards:
        parts = []
        for s in shards:
            sp = os.path.join(output_dir, s["name"])
            try:
                with open(sp, "rb") as f:
                    blob = f.read()
            except OSError as e:
                raise CheckpointCorrupt(
                    f"{path}: committed shard {s['name']} is missing ({e})"
                ) from e
            verify_checkpoint_payload(blob, {"manifest": s}, sp)
            parts.append(blob)
        payload = b"".join(parts)
        total = meta.get("total")
        if total:
            verify_checkpoint_payload(payload, {"manifest": total}, path)
        return payload
    with open(path, "rb") as f:
        payload = f.read()
    verify_checkpoint_payload(payload, meta, path)
    return payload


def _read_verified(output_dir: str, name: str, target) -> Tuple[Any, int, float]:
    """Read + verify + deserialize one candidate. FileNotFoundError means
    "candidate absent" (silent skip); CheckpointCorrupt means "candidate
    exists but is unusable" (logged skip)."""
    meta = _read_meta(output_dir, name)
    payload = read_verified_payload(output_dir, name, meta)
    path = os.path.join(output_dir, name)
    try:
        restored = serialization.from_bytes(target, payload)
    except Exception as e:  # flax/msgpack raise a zoo of decode errors
        raise CheckpointCorrupt(f"{path}: undeserializable payload: {e}") from e
    return restored, int(meta.get("epoch", -1)), float(meta.get("best_acc", 0.0))


def restore_checkpoint(
    output_dir: str,
    state: TrainState,
    name: str = CKPT_NAME,
    names: Optional[Sequence[str]] = None,
    registry=None,
) -> Tuple[TrainState, int, float]:
    """Load ``output_dir``'s checkpoint into ``state``'s structure.

    ``names`` (e.g. :func:`newest_checkpoint_order`) gives the candidate
    preference; each candidate is expanded with its rolling history, and
    restore falls back through the list on ANY corruption — a truncated
    payload, a checksum mismatch, a missing or corrupt v3 shard, or
    undeserializable bytes all behave like a missing file with a warning,
    never a crash deep inside flax. A v3 publish without its commit
    marker is treated as absent (never reassembled from loose shards).
    Raises FileNotFoundError only when NO candidate is usable.

    Topology-free by construction: a v3 candidate saved by M processes
    restores into a world of N for any M, N — process 0 reads the
    commit marker's complete shard set (the saving topology's) and the
    broadcast hands every current process the same bytes. The elastic
    trainer additionally re-cuts the on-disk layout to the new world
    afterwards (:func:`reshard_to_world`).

    Returns (state, start_epoch, best_acc); start_epoch is the next epoch
    to run (saved epoch + 1).
    """
    t0 = time.perf_counter()
    candidates = list(names) if names is not None else [name]
    multihost = jax.process_count() > 1
    if multihost:
        # gloo-safe pytree broadcast (chunked on jax 0.4.x CPU, one-shot
        # everywhere else — parallel/mesh.py has the version gate)
        from pytorch_cifar_tpu.parallel.mesh import broadcast_pytree

    target = {
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
        "opt_state": jax.device_get(state.opt_state),
        "step": np.zeros((), np.int32),
    }
    # Under multi-host process 0 walks the candidate order, decides which
    # checkpoint wins (reassembling any sharded candidate itself — the
    # sharded format requires a shared checkpoint dir), and every process
    # follows that decision via broadcast — no host can diverge (raise vs
    # proceed, or restore DIFFERENT candidates) and deadlock the
    # collective job.
    restored = None
    epoch, best_acc = -1, 0.0
    if jax.process_index() == 0:
        expanded = []
        for cand in candidates:
            expanded.append(cand)
            expanded.extend(history_names(output_dir, cand))
        for cand in expanded:
            try:
                with trace.span("checkpoint/restore", file=cand):
                    restored, epoch, best_acc = _read_verified(
                        output_dir, cand, target
                    )
            except FileNotFoundError:
                continue
            except CheckpointCorrupt as e:
                log.warning(
                    "checkpoint candidate %s is corrupt (%s); "
                    "falling back", cand, e
                )
                if registry is not None:
                    registry.counter("checkpoint.corrupt_candidates").inc()
                trace.instant("checkpoint/corrupt_candidate", file=cand)
                continue
            if cand != expanded[0]:
                log.warning(
                    "restored fallback checkpoint %s (epoch %d) — the "
                    "preferred candidate was missing or corrupt",
                    cand, epoch,
                )
                if registry is not None:
                    registry.counter("checkpoint.fallbacks").inc()
            break
    have_ckpt = restored is not None
    if multihost:
        have_ckpt = bool(
            broadcast_pytree(np.asarray(have_ckpt, np.int32))
        )
    if not have_ckpt:
        raise FileNotFoundError(
            f"no usable checkpoint in {output_dir!r} "
            f"(tried {candidates} and their history) — run without "
            "--resume first (parity: main.py:79 asserts ./checkpoint exists)"
        )
    if restored is None:
        restored = target  # placeholder structure; overwritten by broadcast
    if multihost:
        restored, scalars = broadcast_pytree(
            (restored, np.asarray([epoch, best_acc], np.float64))
        )
        epoch, best_acc = int(scalars[0]), float(scalars[1])

    state = state.replace(
        params=restored["params"],
        batch_stats=restored["batch_stats"],
        opt_state=restored["opt_state"],
        step=restored["step"],
    )
    if registry is not None:
        registry.counter("checkpoint.restores").inc()
        registry.histogram("checkpoint.restore_ms").observe(
            (time.perf_counter() - t0) * 1e3
        )
    return state, epoch + 1, best_acc
