"""Checkpoint save/restore of the full TrainState.

Strictly more complete than the reference's 3-key dict (net/acc/epoch,
main.py:140-147): params, BN batch_stats, optimizer state (momentum
buffers), step, epoch, and best_acc all round-trip, so a resumed run
continues the exact momentum + LR trajectory (the reference restarts both,
SURVEY.md §3.4). Same best-accuracy gating semantics (main.py:136-148).

Format v2 (ROBUSTNESS.md): flax msgpack of the array pytree + a JSON
sidecar carrying the scalars AND a payload manifest (CRC32 + size). Writes
are atomic and durable — tmp file fsync'd before the rename, directory
fsync'd after — and process-0-only under multi-host SPMD (rank-0 gating
parity, main_dist.py:243). Restore verifies the manifest and falls back
through the candidate order on ANY corruption (truncated payload, bad
msgpack, checksum mismatch), not just a missing file; under multi-host the
winning candidate is process 0's decision, broadcast to every host, so no
host can diverge. v1 checkpoints (no manifest) still restore, with a
logged warning. ``keep_last_n`` keeps a rolling history of prior
checkpoint versions as extra fallback candidates.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import re
import time
import zlib
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from flax import serialization

from pytorch_cifar_tpu.obs import trace
from pytorch_cifar_tpu.train.state import TrainState

log = logging.getLogger(__name__)

CKPT_NAME = "ckpt.msgpack"   # best-accuracy checkpoint (reference semantics)
LAST_NAME = "last.msgpack"   # preemption save: exact latest state

MANIFEST_FORMAT = 2


class CheckpointCorrupt(RuntimeError):
    """A checkpoint payload failed verification (checksum/size mismatch or
    undeserializable bytes). Restore falls back; serving skips the swap."""


def meta_path(output_dir: str, name: str) -> str:
    """Path of the JSON scalar sidecar paired with checkpoint ``name``."""
    return os.path.join(output_dir, os.path.splitext(name)[0] + ".json")


def payload_manifest(payload: bytes) -> dict:
    """The sidecar manifest entry that lets any reader verify the payload
    without deserializing it (format v2)."""
    return {
        "format": MANIFEST_FORMAT,
        "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
        "size": len(payload),
    }


def verify_checkpoint_payload(payload: bytes, meta: dict, path: str) -> None:
    """Check ``payload`` against the sidecar ``meta``'s manifest.

    Raises :class:`CheckpointCorrupt` on size/checksum mismatch. A sidecar
    without a manifest (format v1, pre-robustness checkpoints) passes with
    a logged warning — old checkpoints must keep restoring."""
    manifest = (meta or {}).get("manifest")
    if not manifest:
        log.warning(
            "checkpoint %s has no manifest (format v1): restoring "
            "unverified — re-save to upgrade to format v2", path
        )
        return
    if len(payload) != int(manifest.get("size", -1)):
        raise CheckpointCorrupt(
            f"{path}: payload is {len(payload)} bytes, manifest says "
            f"{manifest.get('size')} (truncated or torn write)"
        )
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    if crc != int(manifest.get("crc32", -1)):
        raise CheckpointCorrupt(
            f"{path}: payload crc32 {crc:#010x} != manifest "
            f"{int(manifest.get('crc32', -1)):#010x} (bit corruption)"
        )


def _fsync_dir(dirpath: str) -> None:
    """Durably record a rename in its directory. Best-effort: some
    filesystems (FUSE/NFS mounts on TPU hosts) reject directory fsync."""
    try:
        fd = os.open(dirpath or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + fsync + rename + dir fsync: after this returns, a crash at
    ANY point leaves either the old complete file or the new complete
    file — never a zero-length or half-written "atomically" renamed one
    (an os.replace of an unfsynced tmp can journal the rename before the
    data blocks reach disk)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


# -- rolling history -----------------------------------------------------

def _history_stem(name: str) -> str:
    return os.path.splitext(name)[0]


def _history_name(name: str, epoch: int) -> str:
    return f"{_history_stem(name)}-e{max(int(epoch), 0):05d}.msgpack"


def history_names(output_dir: str, name: str):
    """Rolling-history checkpoint names for ``name``, newest epoch first —
    the extra fallback candidates behind the primary file."""
    pat = re.compile(
        re.escape(_history_stem(name)) + r"-e(\d+)\.msgpack$"
    )
    found = []
    for path in glob.glob(
        os.path.join(output_dir, _history_stem(name) + "-e*.msgpack")
    ):
        m = pat.search(os.path.basename(path))
        if m:
            found.append((int(m.group(1)), os.path.basename(path)))
    return [n for _, n in sorted(found, reverse=True)]


def _update_history(
    output_dir: str, name: str, epoch: int, payload: bytes, meta: dict,
    keep_last_n: int,
) -> None:
    """Publish a history copy of the just-written checkpoint and prune the
    oldest entries beyond ``keep_last_n``. Copies (not hardlinks): a
    separate inode means corruption of the primary file cannot reach its
    history fallback."""
    hname = _history_name(name, epoch)
    _atomic_write(os.path.join(output_dir, hname), payload)
    _atomic_write(
        meta_path(output_dir, hname),
        json.dumps(meta).encode(),
    )
    for stale in history_names(output_dir, name)[keep_last_n:]:
        for p in (
            os.path.join(output_dir, stale),
            meta_path(output_dir, stale),
        ):
            try:
                os.remove(p)
            except OSError:
                pass


# -- save ----------------------------------------------------------------

def save_checkpoint(
    output_dir: str,
    state: TrainState,
    epoch: int,
    best_acc: float,
    name: str = CKPT_NAME,
    keep_last_n: int = 0,
    registry=None,
) -> Optional[str]:
    """Write state to ``output_dir`` (process 0 only). Returns the path.

    Write order is part of the format: payload first, sidecar (carrying
    the payload's manifest) second — a reader that verifies the manifest
    therefore never trusts a payload/sidecar pairing from two different
    publishes (serve/reload.py gates its hot swap on exactly this).

    ``registry`` (obs.MetricsRegistry, optional): records duration and
    payload bytes — through a serialized host link the device_get below is
    the dominant cost of a save, and without a number it gets blamed on
    the training step it stalls (OBSERVABILITY.md)."""
    if jax.process_index() != 0:
        return None
    t0 = time.perf_counter()
    with trace.span("checkpoint/save", file=name, epoch=int(epoch)):
        os.makedirs(output_dir, exist_ok=True)
        # one logical copy on host; works for replicated or single-device
        # state
        with trace.span("checkpoint/device_get"):
            host_state = jax.device_get(
                {
                    "params": state.params,
                    "batch_stats": state.batch_stats,
                    "opt_state": state.opt_state,
                    "step": state.step,
                }
            )
        payload = serialization.to_bytes(host_state)
        path = os.path.join(output_dir, name)
        with trace.span("checkpoint/write", bytes=len(payload)):
            _atomic_write(path, payload)

            meta = {
                "epoch": int(epoch),
                "best_acc": float(best_acc),
                "manifest": payload_manifest(payload),
            }
            _atomic_write(
                meta_path(output_dir, name), json.dumps(meta).encode()
            )
            if keep_last_n > 0:
                _update_history(
                    output_dir, name, epoch, payload, meta, keep_last_n
                )
    if registry is not None:
        registry.counter("checkpoint.saves").inc()
        registry.counter("checkpoint.saved_bytes").inc(len(payload))
        registry.histogram("checkpoint.save_ms").observe(
            (time.perf_counter() - t0) * 1e3
        )
    return path


def newest_checkpoint_order(output_dir: str):
    """Checkpoint preference for training resume: whichever of
    last.msgpack / ckpt.msgpack has the newer epoch in its meta sidecar
    (ties go to the preemption save — it has the exact latest opt state).
    An unreadable/corrupt sidecar counts as epoch -1 instead of raising,
    so a torn write never blocks resume. Shared by Trainer and
    tools/export_torch_checkpoint.py so the rule cannot drift."""

    def epoch_of(name):
        try:
            with open(meta_path(output_dir, name)) as f:
                return int(json.load(f).get("epoch", -1))
        except (OSError, ValueError):
            return -1

    if epoch_of(LAST_NAME) >= epoch_of(CKPT_NAME):
        return [LAST_NAME, CKPT_NAME]
    return [CKPT_NAME, LAST_NAME]


def best_checkpoint_order(output_dir: str = None):
    """Checkpoint preference when the caller wants the BEST params (eval
    and serving, not training resume): the best-accuracy ckpt first, the
    preemption save only as a fallback for runs that never improved past
    epoch 0. Shared by Trainer (--evaluate) and serve/ so the rule cannot
    drift. ``output_dir`` is accepted for signature symmetry with
    :func:`newest_checkpoint_order`; the best-first order is static."""
    return [CKPT_NAME, LAST_NAME]


def remove_stale_last(output_dir: str) -> None:
    """Delete the preemption save (last.msgpack + sidecar) after a run
    COMPLETES normally: a leftover one would make a routine relaunch with
    --resume roll training back to the preemption point. Shared by
    Trainer.fit and tools/accuracy_run.py so the rule cannot drift."""
    if jax.process_index() != 0 or not output_dir:
        return
    stale = [LAST_NAME] + history_names(output_dir, LAST_NAME)
    for name in stale:
        for path in (
            os.path.join(output_dir, name),
            meta_path(output_dir, name),
        ):
            try:
                os.remove(path)
            except OSError:
                pass


# -- restore -------------------------------------------------------------

def _read_verified(output_dir: str, name: str, target) -> Tuple[Any, int, float]:
    """Read + verify + deserialize one candidate. FileNotFoundError means
    "candidate absent" (silent skip); CheckpointCorrupt means "candidate
    exists but is unusable" (logged skip)."""
    path = os.path.join(output_dir, name)
    with open(path, "rb") as f:
        payload = f.read()
    meta: dict = {}
    try:
        with open(meta_path(output_dir, name)) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        meta = {}
    verify_checkpoint_payload(payload, meta, path)
    try:
        restored = serialization.from_bytes(target, payload)
    except Exception as e:  # flax/msgpack raise a zoo of decode errors
        raise CheckpointCorrupt(f"{path}: undeserializable payload: {e}") from e
    return restored, int(meta.get("epoch", -1)), float(meta.get("best_acc", 0.0))


def restore_checkpoint(
    output_dir: str,
    state: TrainState,
    name: str = CKPT_NAME,
    names: Optional[Sequence[str]] = None,
    registry=None,
) -> Tuple[TrainState, int, float]:
    """Load ``output_dir``'s checkpoint into ``state``'s structure.

    ``names`` (e.g. :func:`newest_checkpoint_order`) gives the candidate
    preference; each candidate is expanded with its rolling history, and
    restore falls back through the list on ANY corruption — a truncated
    payload, a checksum mismatch, or undeserializable bytes all behave
    like a missing file with a warning, never a crash deep inside flax.
    Raises FileNotFoundError only when NO candidate is usable.

    Returns (state, start_epoch, best_acc); start_epoch is the next epoch
    to run (saved epoch + 1).
    """
    t0 = time.perf_counter()
    candidates = list(names) if names is not None else [name]
    multihost = jax.process_count() > 1
    if multihost:
        # gloo-safe pytree broadcast (chunked on jax 0.4.x CPU, one-shot
        # everywhere else — parallel/mesh.py has the version gate)
        from pytorch_cifar_tpu.parallel.mesh import broadcast_pytree

    target = {
        "params": jax.device_get(state.params),
        "batch_stats": jax.device_get(state.batch_stats),
        "opt_state": jax.device_get(state.opt_state),
        "step": np.zeros((), np.int32),
    }
    # Saves are process-0-only, so under multi-host without a shared
    # filesystem only process 0 sees the files. Process 0 walks the
    # candidate order, decides which checkpoint wins, and every process
    # follows that decision via broadcast — no per-host file requirement,
    # and no host can diverge (raise vs proceed, or restore DIFFERENT
    # candidates) and deadlock the collective job.
    restored = None
    epoch, best_acc = -1, 0.0
    if jax.process_index() == 0:
        expanded = []
        for cand in candidates:
            expanded.append(cand)
            expanded.extend(history_names(output_dir, cand))
        for cand in expanded:
            try:
                with trace.span("checkpoint/restore", file=cand):
                    restored, epoch, best_acc = _read_verified(
                        output_dir, cand, target
                    )
            except FileNotFoundError:
                continue
            except CheckpointCorrupt as e:
                log.warning(
                    "checkpoint candidate %s is corrupt (%s); "
                    "falling back", cand, e
                )
                if registry is not None:
                    registry.counter("checkpoint.corrupt_candidates").inc()
                trace.instant("checkpoint/corrupt_candidate", file=cand)
                continue
            if cand != expanded[0]:
                log.warning(
                    "restored fallback checkpoint %s (epoch %d) — the "
                    "preferred candidate was missing or corrupt",
                    cand, epoch,
                )
                if registry is not None:
                    registry.counter("checkpoint.fallbacks").inc()
            break
    have_ckpt = restored is not None
    if multihost:
        have_ckpt = bool(
            broadcast_pytree(np.asarray(have_ckpt, np.int32))
        )
    if not have_ckpt:
        raise FileNotFoundError(
            f"no usable checkpoint in {output_dir!r} "
            f"(tried {candidates} and their history) — run without "
            "--resume first (parity: main.py:79 asserts ./checkpoint exists)"
        )
    if restored is None:
        restored = target  # placeholder structure; overwritten by broadcast
    if multihost:
        restored, scalars = broadcast_pytree(
            (restored, np.asarray([epoch, best_acc], np.float64))
        )
        epoch, best_acc = int(scalars[0]), float(scalars[1])

    state = state.replace(
        params=restored["params"],
        batch_stats=restored["batch_stats"],
        opt_state=restored["opt_state"],
        step=restored["step"],
    )
    if registry is not None:
        registry.counter("checkpoint.restores").inc()
        registry.histogram("checkpoint.restore_ms").observe(
            (time.perf_counter() - t0) * 1e3
        )
    return state, epoch + 1, best_acc
