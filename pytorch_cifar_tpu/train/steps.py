"""Jitted train/eval steps.

The reference's hot loop (main.py:99-113) is eager: per-batch H2D copy,
autograd backward, optimizer step, and a blocking ``loss.item()`` sync every
iteration. Here the whole iteration — on-device augmentation, forward, loss,
backward, SGD update, metric accumulation — is ONE traced function compiled
once by XLA, with donated state buffers and no host sync in the loop.

``axis_name`` plumbs the data-parallel mesh axis: when set (shard_map path,
parallel/dp.py), gradients and metrics are psum'd across devices — the
TPU-native replacement for DDP's bucketed NCCL all-reduce
(main_dist.py:140-144). BatchNorm normalizes over the *local* per-device
batch (parity with the reference's non-Sync BN under DDP, SURVEY.md §7.2)
while updated running stats are pmean'd so eval statistics are deterministic
across hosts (an intentional improvement over per-rank stats drift).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax

from pytorch_cifar_tpu.data.augment import CIFAR10_MEAN, CIFAR10_STD, augment_batch, normalize
from pytorch_cifar_tpu.models.common import sync_batchnorm
from pytorch_cifar_tpu.train.state import TrainState

Metrics = dict


def cross_entropy_sums(logits: jax.Array, labels: jax.Array):
    """(sum of CE over valid rows, valid count) in fp32; labels < 0 are
    padding (pipeline.py wrap-pad / eval_batches) and contribute nothing.
    The single source of the masking rule — loss, gradients, and metrics
    all reduce these same two numbers."""
    valid = labels >= 0
    losses = optax.softmax_cross_entropy_with_integer_labels(
        # at-least-fp32: bf16 logits promote to fp32; f64 logits (the x64
        # trajectory-parity harness) are not demoted
        logits.astype(jnp.promote_types(logits.dtype, jnp.float32)),
        jnp.maximum(labels, 0),
    )
    return jnp.where(valid, losses, 0.0).sum(), valid.sum()


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean CE over valid (label >= 0) entries, computed in fp32."""
    loss_sum, n_valid = cross_entropy_sums(logits, labels)
    return loss_sum / jnp.maximum(n_valid, 1)


def _metrics(logits, labels) -> Metrics:
    valid = labels >= 0
    pred = jnp.argmax(logits, axis=-1)
    correct = jnp.sum((pred == labels) & valid)
    loss_sum, n_valid = cross_entropy_sums(logits, labels)
    return {
        "loss_sum": loss_sum,
        "correct": correct.astype(jnp.float32),
        "count": n_valid.astype(jnp.float32),
        # divergence sentinel: count of (shard, step) observations whose
        # loss went non-finite; the train step additionally folds in the
        # gradient-norm check (trainer.py applies the skip/rollback policy)
        "nonfinite": (~jnp.isfinite(loss_sum)).astype(jnp.float32),
    }


def make_train_step(
    augment: bool = True,
    crop: bool = True,
    flip: bool = True,
    mean: Sequence[float] = CIFAR10_MEAN,
    std: Sequence[float] = CIFAR10_STD,
    compute_dtype=jnp.float32,
    axis_name: Optional[str] = None,
    remat: bool = False,
    sync_bn: bool = False,
    skip_nonfinite: bool = False,
) -> Callable:
    """Returns step(state, batch=(uint8 images, labels), rng) -> (state, metrics).

    ``remat=True`` wraps the forward in ``jax.checkpoint``: activations are
    recomputed during backward instead of stored, trading FLOPs for HBM —
    the lever for batch sizes whose activation footprint exceeds chip
    memory (no reference equivalent; torch's is torch.utils.checkpoint).

    ``sync_bn=True`` (requires ``axis_name``) switches every BatchNorm to
    cross-replica statistics: batch moments are pmean'd over the mesh axis,
    so normalization matches single-device BN over the global batch. The
    default (False) matches the reference's per-replica BN under DDP
    (SURVEY.md §7.2).

    ``skip_nonfinite=True`` is the divergence sentinel's step half
    (ROBUSTNESS.md): when the loss or the (post-all-reduce) gradient norm
    goes non-finite, the parameter/optimizer/BN update is DISCARDED via
    ``jnp.where`` — the step counter still advances, so the LR schedule
    and per-step rng stream stay aligned with a clean run — and the
    ``nonfinite`` metric reports the event. The flag is replica-agreed
    (psum over ``axis_name``) so data-parallel shards can never split on
    the skip decision and diverge. A finite step pays one scalar select
    per leaf; results are bit-identical to the unguarded step.
    """
    if sync_bn and axis_name is None:
        raise ValueError("sync_bn requires a data-parallel axis_name")
    # fault-injection point (chaos harness): poison the gradient loss at
    # one global step. Read ONCE when the step closure is built, so the
    # compiled program is static; inert unless faults.inject("nan_loss", k)
    # or PCT_FAULTS=nan_loss=k armed it before the Trainer was constructed.
    from pytorch_cifar_tpu import faults

    nan_step = faults.nan_loss_step()

    def step(state: TrainState, batch, rng) -> Tuple[TrainState, Metrics]:
        images, labels = batch
        key = jax.random.fold_in(rng, state.step)
        if axis_name is not None:
            # decorrelate augmentation across data-parallel shards
            key = jax.random.fold_in(key, jax.lax.axis_index(axis_name))
        # independent subkeys: the augmentation offsets and the model's
        # "stochastic" rng stream (stochastic depth) must not draw from
        # identical bits (graftcheck prng-reuse)
        k_aug, k_model = jax.random.split(key)
        if augment:
            x = augment_batch(
                k_aug, images, crop=crop, flip=flip, mean=mean, std=std,
                dtype=compute_dtype,
            )
        else:
            x = normalize(images, mean, std, dtype=compute_dtype)

        def fwd(params, x, key):
            variables = {"params": params, "batch_stats": state.batch_stats}
            with sync_batchnorm(axis_name if sync_bn else None):
                return state.apply_fn(
                    variables, x, train=True, mutable=["batch_stats"],
                    rngs={"stochastic": key},
                )

        if remat:
            fwd = jax.checkpoint(fwd)

        def loss_fn(params):
            logits, mutated = fwd(params, x, k_model)
            loss_sum, n_valid = cross_entropy_sums(logits, labels)
            if axis_name is None:
                loss = loss_sum / jnp.maximum(n_valid, 1)
            else:
                # global-batch-mean CE. With a wrap-padded ragged batch
                # (pipeline.py drop_last=False) shards can hold different
                # valid counts; a local mean + pmean(grads) would upweight
                # examples on light shards. Scaling the local sum by
                # P/global_count makes the later pmean reduce exactly to
                # global_sum/global_count — the reference's per-batch mean
                # (main.py:103).
                n_global = jax.lax.psum(n_valid, axis_name)
                n_dev = jax.lax.psum(jnp.ones((), jnp.float32), axis_name)
                loss = loss_sum * n_dev / jnp.maximum(n_global, 1)
            if nan_step is not None:
                # chaos injection: NaN at one global step (or every step
                # when armed with a negative value). MULTIPLIED in, not
                # selected in: d(where(c, nan, loss))/dloss is 0 on the
                # constant branch, which would leave the gradients clean —
                # a NaN factor poisons loss AND every gradient, exactly
                # like a real numeric blow-up
                trigger = (
                    jnp.asarray(True)
                    if nan_step < 0
                    else state.step == nan_step
                )
                loss = loss * jnp.where(trigger, jnp.float32(jnp.nan), 1.0)
            return loss, (logits, mutated.get("batch_stats", state.batch_stats))

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)

        metrics = _metrics(logits, labels)
        if axis_name is not None:
            grads = jax.lax.pmean(grads, axis_name)
            if not sync_bn:  # under sync_bn stats are already replica-identical
                new_stats = jax.lax.pmean(new_stats, axis_name)
        # sentinel flag: loss is shard-local, the grad norm is computed on
        # the post-pmean (replica-identical) gradients; psum'ing the local
        # verdict makes every shard see the same boolean, so the skip below
        # can never leave shards holding different parameters
        bad = jnp.logical_or(
            ~jnp.isfinite(loss), ~jnp.isfinite(optax.global_norm(grads))
        )
        if axis_name is not None:
            bad = jax.lax.psum(bad.astype(jnp.float32), axis_name) > 0
            metrics = jax.tree_util.tree_map(
                lambda m: jax.lax.psum(m, axis_name), metrics
            )
        # exactly 0/1 per step regardless of shard count, so the epoch
        # total is a bad-STEP count (the budget the trainer reasons about)
        metrics["nonfinite"] = jnp.maximum(
            (metrics["nonfinite"] > 0).astype(jnp.float32),
            bad.astype(jnp.float32),
        )
        new_state = state.apply_gradients(grads)
        new_state = new_state.replace(batch_stats=new_stats)
        if skip_nonfinite:
            # discard the poisoned update but keep the step counter moving
            # (LR schedule + rng stream stay aligned with a clean run)
            safe = state.replace(step=new_state.step)
            new_state = jax.tree_util.tree_map(
                lambda o, n: jnp.where(bad, o, n), safe, new_state
            )
        return new_state, metrics

    return step


def zero_metrics(num_steps: int = 0) -> Metrics:
    """Initial value for the on-device running metric sums. DISTINCT
    arrays: the epoch fns donate this argument, and aliasing one buffer
    across leaves trips XLA's donate-same-buffer-twice check.

    ``num_steps > 0`` adds a ``nonfinite_steps`` vector (one 0/1 slot per
    scan step) for the epoch-compiled path: the sentinel's per-step
    bad-step attribution (which steps were skipped, not just how many —
    the ROADMAP item the per-epoch total could not answer). Scalar-only
    callers (the per-step loop, eval) keep the old shape."""
    m = {
        "loss_sum": jnp.zeros((), jnp.float32),
        "correct": jnp.zeros((), jnp.float32),
        "count": jnp.zeros((), jnp.float32),
        "nonfinite": jnp.zeros((), jnp.float32),
    }
    if num_steps > 0:
        m["nonfinite_steps"] = jnp.zeros((num_steps,), jnp.float32)
    return m


def make_train_epoch(
    step: Callable,
    global_batch: int,
    n_data: int,
    num_steps: int,
    axis_name: Optional[str] = None,
    n_shards: int = 1,
    batch_sharding=None,
    label_sharding=None,
    dma_gather: bool = False,
) -> Callable:
    """Compile a WHOLE training epoch into one XLA computation.

    epoch_fn(state, totals, images, labels, perm, rng) -> (state, totals)

    ``lax.scan`` over ``num_steps`` iterations; each iteration materializes
    its batch from the device-resident dataset (dynamic-slice of the
    epoch permutation + gather, the same arithmetic as
    pipeline.DeviceDataset) and runs ``step`` (a make_train_step closure —
    per-shard under shard_map when ``axis_name`` is set, global semantics
    for the GSPMD spatial path when ``batch_sharding`` is given).

    Why an epoch, not a step, is the dispatch unit: through a remote-TPU
    transport each host->device dispatch costs ~4-6 ms; at 98 steps/epoch
    the per-step loop pays ~2 s/epoch of pure dispatch against 1.4 s of
    compute (measured, BENCHMARKS.md). One scan = one dispatch per epoch;
    the loop body compiles ONCE regardless of num_steps. The reference's
    eager hot loop (main.py:99-113) is the opposite extreme: per-batch
    H2D + per-step .item() sync.

    Wrap-padded tail rows (extended-permutation positions >= n_data) get
    label -1, masked from loss/grads/metrics exactly like the host path.

    Batch materialization (round 3): on the shard_map/single-device paths
    the whole epoch's batches are gathered ONCE before the scan — one large
    row-gather at full HBM bandwidth — and the scan body takes contiguous
    ``dynamic_slice``s of the pre-gathered block. The previous per-step
    512-row gather was the dominant cost of the 10% epoch-vs-step
    throughput gap (BENCHMARKS.md round 3). The GSPMD spatial path keeps
    the per-step gather: its batches carry a sharding constraint, and a
    dynamic-slice along a GSPMD-sharded batch dimension would force the
    partitioner to all-gather (exactly the pessimization
    tests/test_spatial.py guards against); the bytes are identical either
    way, only the grouping differs, so results are bit-exact.
    """
    shard_batch = global_batch // max(n_shards, 1)

    def epoch_fn(state, totals, images, labels, perm, rng):
        pregather = batch_sharding is None
        if pregather:
            # epoch positions this shard will visit, in visit order:
            # step i covers [i*global_batch + shard*shard_batch, +shard_batch)
            pos = (
                jnp.arange(num_steps, dtype=jnp.int32)[:, None] * global_batch
                + jnp.arange(shard_batch, dtype=jnp.int32)[None, :]
            )
            if axis_name is not None:
                pos = pos + jax.lax.axis_index(axis_name) * shard_batch
            pos = pos.reshape(-1)
            idx = jnp.take(perm, pos, axis=0)
            if dma_gather:
                # TPU meshes only (Trainer auto-gates): XLA's row gather
                # runs descriptor-bound (~5.3 ms for 50k CIFAR rows);
                # the pipelined-DMA kernel does the same move in ~2.8 ms
                # incl. layout reshapes (ops/dma_gather.py, BENCHMARKS.md
                # round 3)
                from pytorch_cifar_tpu.ops.dma_gather import dma_row_gather

                x_all = dma_row_gather(images, idx)
            else:
                x_all = jnp.take(images, idx, axis=0)
            y_all = jnp.where(
                pos < n_data, jnp.take(labels, idx, axis=0), -1
            )

        def body(carry, i):
            state, totals = carry
            if pregather:
                x = jax.lax.dynamic_slice_in_dim(
                    x_all, i * shard_batch, shard_batch, axis=0
                )
                y = jax.lax.dynamic_slice_in_dim(
                    y_all, i * shard_batch, shard_batch, axis=0
                )
            else:
                start = i * global_batch
                if axis_name is not None:
                    start = (
                        start + jax.lax.axis_index(axis_name) * shard_batch
                    )
                idx = jax.lax.dynamic_slice(perm, (start,), (shard_batch,))
                x = jnp.take(images, idx, axis=0)
                y = jnp.take(labels, idx, axis=0)
                pos = start + jnp.arange(shard_batch, dtype=jnp.int32)
                y = jnp.where(pos < n_data, y, -1)
                # GSPMD path: pin the materialized batch's layout so the
                # compiler partitions the gather output over the mesh
                # instead of replicating downstream compute
                x = jax.lax.with_sharding_constraint(x, batch_sharding)
                y = jax.lax.with_sharding_constraint(y, label_sharding)
            state, metrics = step(state, (x, y), rng)
            if "nonfinite_steps" in totals:
                # per-step attribution rides the carry, not the running
                # sums: slot i records THIS step's replica-agreed 0/1
                # verdict (metrics["nonfinite"] is exactly 0/1 per step),
                # so the fetched epoch totals say WHICH steps the sentinel
                # skipped, not just how many
                totals = dict(totals)
                mask = totals.pop("nonfinite_steps")
                totals = jax.tree_util.tree_map(jnp.add, totals, metrics)
                totals["nonfinite_steps"] = mask.at[i].set(
                    metrics["nonfinite"]
                )
            else:
                totals = jax.tree_util.tree_map(jnp.add, totals, metrics)
            return (state, totals), None

        (state, totals), _ = jax.lax.scan(
            body,
            (state, totals),
            jnp.arange(num_steps, dtype=jnp.int32),
        )
        return state, totals

    return epoch_fn


def make_eval_epoch(
    step: Callable,
    global_batch: int,
    n_data: int,
    num_steps: int,
    axis_name: Optional[str] = None,
    n_shards: int = 1,
    batch_sharding=None,
    label_sharding=None,
) -> Callable:
    """One-dispatch eval epoch: epoch_fn(state, images, labels) -> totals.

    The test set is device-resident and static, so the batch arithmetic
    needs no permutation input at all: batch i is rows [i*B, (i+1)*B) with
    tail positions >= n_data masked to -1 (clamped gather keeps the read
    in bounds; masked rows contribute nothing).
    """
    shard_batch = global_batch // max(n_shards, 1)

    def epoch_fn(state, images, labels):
        def body(totals, i):
            start = i * global_batch
            if axis_name is not None:
                start = start + jax.lax.axis_index(axis_name) * shard_batch
            pos = start + jnp.arange(shard_batch, dtype=jnp.int32)
            safe = jnp.minimum(pos, n_data - 1)
            x = jnp.take(images, safe, axis=0)
            y = jnp.where(pos < n_data, jnp.take(labels, safe, axis=0), -1)
            if batch_sharding is not None:
                x = jax.lax.with_sharding_constraint(x, batch_sharding)
                y = jax.lax.with_sharding_constraint(y, label_sharding)
            metrics = step(state, (x, y))
            return jax.tree_util.tree_map(jnp.add, totals, metrics), None

        totals, _ = jax.lax.scan(
            body, zero_metrics(), jnp.arange(num_steps, dtype=jnp.int32)
        )
        return totals

    return epoch_fn


def make_eval_step(
    mean: Sequence[float] = CIFAR10_MEAN,
    std: Sequence[float] = CIFAR10_STD,
    compute_dtype=jnp.float32,
    axis_name: Optional[str] = None,
) -> Callable:
    """Returns step(state, batch) -> metrics. Labels < 0 are padding."""

    def step(state: TrainState, batch) -> Metrics:
        images, labels = batch
        x = normalize(images, mean, std, dtype=compute_dtype)
        variables = {"params": state.params, "batch_stats": state.batch_stats}
        logits = state.apply_fn(variables, x, train=False)
        metrics = _metrics(logits, labels)
        if axis_name is not None:
            metrics = jax.tree_util.tree_map(
                lambda m: jax.lax.psum(m, axis_name), metrics
            )
        return metrics

    return step
