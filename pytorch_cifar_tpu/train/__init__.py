from pytorch_cifar_tpu.train.state import TrainState, create_train_state  # noqa: F401
from pytorch_cifar_tpu.train.optim import make_optimizer, cosine_epoch_schedule  # noqa: F401
from pytorch_cifar_tpu.train.steps import make_train_step, make_eval_step  # noqa: F401
