"""Host-side trace spans: Chrome/Perfetto trace-event JSON + XLA nesting.

``span("train/step", step=i)`` times a host-side region and records it as
a Chrome trace-event "complete" event (``ph: "X"``) — the format
chrome://tracing and ui.perfetto.dev open directly, and the one
``tools/trace_summary.py`` folds into a top-spans table. Nesting needs no
begin/end pairing: viewers reconstruct the stack from (tid, ts, dur).

When the installed jaxlib exposes ``jax.profiler.TraceAnnotation``, every
span additionally enters one, so a host span lines up with XLA device
activity inside a ``jax.profiler.start_trace`` capture. Probed once and
cached — same defensive pattern as ``xla_collective_timeout_flags``
(pytorch_cifar_tpu/__init__.py): a jaxlib predating the API must degrade
to host-only spans, never crash (this container's jaxlib 0.4.36 HAS it,
but the gate is what makes that an observation instead of an assumption).

A process has at most one installed tracer (module-level, like the stdlib
logging root): instrumentation sites in trainer/checkpoint/pipeline call
``trace.span(...)`` unconditionally, and when nothing is installed they
get one shared no-op context manager — no allocation, no lock, no thread;
the disabled cost is a dict-free function call (pinned by test_obs.py and
the bench <2% regression budget).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

_TRACE_ANNOTATION = None
_TRACE_ANNOTATION_PROBED = False


def jax_trace_annotation():
    """``jax.profiler.TraceAnnotation`` or None when this jaxlib lacks it
    (probed once; the probe itself must never initialize a backend)."""
    global _TRACE_ANNOTATION, _TRACE_ANNOTATION_PROBED
    if not _TRACE_ANNOTATION_PROBED:
        _TRACE_ANNOTATION_PROBED = True
        try:
            import jax.profiler

            _TRACE_ANNOTATION = getattr(
                jax.profiler, "TraceAnnotation", None
            )
        except Exception:
            _TRACE_ANNOTATION = None
    return _TRACE_ANNOTATION


class _NullSpan:
    """Shared no-op context manager: the whole disabled-mode cost."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "_name", "_args", "_t0", "_xla")

    def __init__(self, tracer: "Tracer", name: str, args):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._xla = None

    def __enter__(self):
        ann = jax_trace_annotation() if self._tracer.xla_annotations else None
        if ann is not None:
            self._xla = ann(self._name)
            self._xla.__enter__()
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur_us = (time.perf_counter_ns() - self._t0) / 1e3
        if self._xla is not None:
            self._xla.__exit__(*exc)
        self._tracer._emit(
            {
                "name": self._name,
                "ph": "X",
                "ts": (self._t0 - self._tracer._epoch_ns) / 1e3,
                "dur": dur_us,
                "pid": self._tracer.pid,
                "tid": threading.get_ident() & 0x7FFFFFFF,
                **({"args": self._args} if self._args else {}),
            }
        )
        return False


class Tracer:
    """Buffered trace-event collector writing ``{"traceEvents": [...]}``.

    ``flush()`` rewrites the whole file each call (atomic tmp+rename like
    the checkpoint writer), so a crashed run still leaves a valid,
    openable trace of everything emitted before the crash. Events buffer
    in memory between flushes — a 200-epoch run emits thousands of spans,
    not millions; per-device-step events stay XLA's job.
    """

    def __init__(self, path: str, *, xla_annotations: bool = True):
        self.path = path
        self.pid = os.getpid()
        self.xla_annotations = xla_annotations
        self._lock = threading.Lock()
        self._events: list = []
        self._epoch_ns = time.perf_counter_ns()

    def _emit(self, event: dict) -> None:
        with self._lock:
            self._events.append(event)

    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker (``ph: "i"``): one-shot occurrences like
        a checkpoint fallback or a sentinel skip."""
        self._emit(
            {
                "name": name,
                "ph": "i",
                "s": "t",  # thread-scoped instant
                "ts": (time.perf_counter_ns() - self._epoch_ns) / 1e3,
                "pid": self.pid,
                "tid": threading.get_ident() & 0x7FFFFFFF,
                **({"args": args} if args else {}),
            }
        )

    def flush(self) -> None:
        with self._lock:
            events = list(self._events)
        payload = json.dumps(
            {"traceEvents": events, "displayTimeUnit": "ms"}
        )
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        tmp = self.path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(payload)
        # graftcheck: noqa[atomic-publish] -- profiling artifact: rename-atomicity for concurrent readers is the contract; durability after a host crash is worthless for a trace dump
        os.replace(tmp, self.path)


_installed: Optional[Tracer] = None


def install(path: str, *, xla_annotations: bool = True) -> Tracer:
    """Install the process tracer (idempotent per path: reinstalling over
    a different path replaces the tracer after flushing the old one)."""
    global _installed
    if _installed is not None and _installed.path != path:
        _installed.flush()
    if _installed is None or _installed.path != path:
        _installed = Tracer(path, xla_annotations=xla_annotations)
    return _installed


def uninstall(flush: bool = True) -> None:
    global _installed
    if _installed is not None and flush:
        _installed.flush()
    _installed = None


def installed() -> Optional[Tracer]:
    return _installed


def span(name: str, **args):
    """A span on the installed tracer, or the shared no-op when none is
    installed. The call sites never branch — this function is the single
    disabled-mode gate."""
    t = _installed
    if t is None:
        return _NULL_SPAN
    return t.span(name, **args)


def instant(name: str, **args) -> None:
    t = _installed
    if t is not None:
        t.instant(name, **args)


def flush() -> None:
    t = _installed
    if t is not None:
        t.flush()
