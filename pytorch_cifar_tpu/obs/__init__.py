"""Observability substrate: metrics registry, trace spans, exporters.

One instrumentation layer for every subsystem (train / serve / data /
checkpoint / faults) instead of the per-PR one-offs it replaces
(``trainer.fault_stats``, the batcher's ``stats`` dict — both survive as
thin views over the registry). Three pieces:

- :mod:`~pytorch_cifar_tpu.obs.metrics` — process-local, thread-safe
  counters / gauges / fixed-bucket histograms whose snapshots are plain
  JSON-serializable pytrees, so they cross-host merge through the same
  collective helpers the checkpoint broadcast uses and summarize
  deterministically (no unordered iteration anywhere);
- :mod:`~pytorch_cifar_tpu.obs.trace` — host-side span API (context
  manager + instant events) emitting Chrome/Perfetto trace-event JSON,
  nesting ``jax.profiler.TraceAnnotation`` when the installed jaxlib has
  it so host spans line up with XLA device activity;
- :mod:`~pytorch_cifar_tpu.obs.export` — periodic JSONL emitter, an
  end-of-run summary, and a Prometheus-text dump for the serving path.

Everything is OFF by default and near-zero-cost when off: an uninstalled
tracer makes ``trace.span`` return one shared no-op context manager, and
no exporter thread exists unless a CLI flag asked for one (pinned by
tests/test_obs.py). See OBSERVABILITY.md for metric names and the span
naming convention.
"""

from pytorch_cifar_tpu.obs.metrics import (  # noqa: F401
    MetricsRegistry,
    merge_snapshots,
    summarize,
)
from pytorch_cifar_tpu.obs import trace  # noqa: F401
from pytorch_cifar_tpu.obs.export import (  # noqa: F401
    MetricsExporter,
    prometheus_text,
)
