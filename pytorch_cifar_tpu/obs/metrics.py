"""Process-local metrics registry: counters, gauges, fixed-bucket histograms.

Design constraints (the reasons this is not just a dict of ints):

- **Thread-safe**: the serving path mutates from the batcher worker, the
  submit callers, and the watcher thread at once; the trainer mutates from
  the epoch loop and the async checkpoint writer. Each instrument carries
  its own small lock — an ``inc`` is a lock + float add, cheap against
  anything it ever measures (a train step, a queue wait, a disk write).
- **Snapshots are plain pytrees** of floats and lists (JSON-serializable
  as-is): they ride the JSONL exporter unmodified and cross-host merge
  through the same collective helpers the checkpoint broadcast uses
  (``allgather_merged`` below wraps ``process_allgather`` exactly like
  train/checkpoint.py wraps ``broadcast_one_to_all``).
- **Deterministic summaries**: histogram percentiles interpolate inside
  fixed buckets and every emitted dict is key-sorted, so two hosts (or two
  runs) holding equal counts produce byte-identical summaries.

Instances, not a process singleton: each Trainer / MicroBatcher owns its
registry (tests assert exact counts; a shared global would bleed state
between components and test cases), and the CLIs wire one registry through
every component they build when a unified export is wanted.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

# default histogram boundaries (upper bounds, ms-friendly): latency-shaped
# work from ~0.1 ms queue waits to minute-long checkpoint writes lands in
# a distinct bucket without per-site tuning. +inf is implicit.
DEFAULT_BUCKETS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0,
)


class Counter:
    """Monotonic float counter. Merge rule: add."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> float:
        return self.value


class Gauge:
    """Last-set value plus the max ever set. Merge rule: last wins for
    ``value`` is meaningless across hosts, so merge keeps the max of both
    fields — the cross-host-interesting number for queue depths and
    occupancy is the peak, not one host's last sample."""

    __slots__ = ("_lock", "_value", "_max")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0
        self._max = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)
            if v > self._max:
                self._max = float(v)

    def add(self, delta: float) -> None:
        with self._lock:
            self._value += delta
            if self._value > self._max:
                self._max = self._value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    @property
    def max(self) -> float:
        with self._lock:
            return self._max

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {"value": self._value, "max": self._max}


class Histogram:
    """Fixed-bucket histogram: per-bucket counts (non-cumulative), sum,
    count, min, max. Merge rule: counts/sum/count add, min/max extremize —
    so a cross-host merge is exact, not an approximation."""

    __slots__ = ("_lock", "bounds", "_counts", "_sum", "_count", "_min", "_max")

    def __init__(self, bounds: Sequence[float] = DEFAULT_BUCKETS):
        bounds = tuple(sorted(float(b) for b in bounds))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        self.bounds = bounds
        self._counts = [0.0] * (len(bounds) + 1)  # last = overflow (+inf)
        self._sum = 0.0
        self._count = 0.0
        self._min = float("inf")
        self._max = float("-inf")

    def observe(self, v: float) -> None:
        v = float(v)
        # bisect by hand: bounds are short tuples and this avoids importing
        # bisect under the lock's hot path for nothing
        i = 0
        for b in self.bounds:
            if v <= b:
                break
            i += 1
        with self._lock:
            self._counts[i] += 1.0
            self._sum += v
            self._count += 1.0
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    class _Timer:
        __slots__ = ("_h", "_t0")

        def __init__(self, h: "Histogram"):
            self._h = h

        def __enter__(self):
            import time

            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            import time

            self._h.observe((time.perf_counter() - self._t0) * 1e3)
            return False

    def time_ms(self) -> "_Timer":
        """Context manager observing the wrapped block's wall time in ms."""
        return Histogram._Timer(self)

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "bounds": list(self.bounds),
                "counts": list(self._counts),
                "sum": self._sum,
                "count": self._count,
                "min": self._min if self._count else 0.0,
                "max": self._max if self._count else 0.0,
            }


def _percentile_from_buckets(snap: Dict, pct: float) -> float:
    """Deterministic percentile estimate: linear interpolation inside the
    target bucket, clamped by the observed min/max so tiny samples do not
    report a bucket bound no value ever reached."""
    count = snap["count"]
    if count <= 0:
        return 0.0
    bounds = list(snap["bounds"])
    rank = pct / 100.0 * count
    cum = 0.0
    lo = snap["min"]
    for i, c in enumerate(snap["counts"]):
        if c <= 0:
            continue
        hi = bounds[i] if i < len(bounds) else snap["max"]
        if cum + c >= rank:
            frac = (rank - cum) / c
            est = lo + (hi - lo) * max(0.0, min(1.0, frac))
            return float(min(max(est, snap["min"]), snap["max"]))
        cum += c
        lo = hi
    return float(snap["max"])


class MetricsRegistry:
    """Name -> instrument map with get-or-create accessors.

    Names are dotted paths (``train.step_time_ms``, ``serve.queue_depth``);
    OBSERVABILITY.md tables every name the built-in instrumentation emits.
    Re-requesting a name returns the same instrument; requesting an
    existing name as a different kind raises (two subsystems silently
    sharing one name as different types would corrupt both).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def _get(self, table: Dict, name: str, factory):
        with self._lock:
            inst = table.get(name)
            if inst is None:
                for other in (self._counters, self._gauges, self._histograms):
                    if other is not table and name in other:
                        raise ValueError(
                            f"metric {name!r} already registered as a "
                            "different kind"
                        )
                inst = table[name] = factory()
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, name, Gauge)

    def histogram(
        self, name: str, bounds: Sequence[float] = DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get(self._histograms, name, lambda: Histogram(bounds))

    def snapshot(self) -> Dict:
        """Plain-pytree snapshot: {'counters': {...}, 'gauges': {...},
        'histograms': {...}}, every leaf a float or list of floats."""
        with self._lock:
            c = dict(self._counters)
            g = dict(self._gauges)
            h = dict(self._histograms)
        return {
            "counters": {k: c[k].snapshot() for k in sorted(c)},
            "gauges": {k: g[k].snapshot() for k in sorted(g)},
            "histograms": {k: h[k].snapshot() for k in sorted(h)},
        }

    def summary(self) -> Dict:
        return summarize(self.snapshot())


def merge_snapshots(*snaps: Dict) -> Dict:
    """Merge snapshots by each kind's semantic: counters add, gauges keep
    the max of both fields, histograms add counts/sum/count and extremize
    min/max. Histograms merged under one name must share bucket bounds
    (they do by construction: bounds are part of the instrumented name's
    definition); mismatched bounds raise rather than mis-merge."""
    if not snaps:
        return {"counters": {}, "gauges": {}, "histograms": {}}
    out = {
        "counters": dict(snaps[0].get("counters", {})),
        "gauges": {k: dict(v) for k, v in snaps[0].get("gauges", {}).items()},
        "histograms": {
            k: {**v, "bounds": list(v["bounds"]), "counts": list(v["counts"])}
            for k, v in snaps[0].get("histograms", {}).items()
        },
    }
    for snap in snaps[1:]:
        for k, v in snap.get("counters", {}).items():
            out["counters"][k] = out["counters"].get(k, 0.0) + float(v)
        for k, v in snap.get("gauges", {}).items():
            cur = out["gauges"].setdefault(k, {"value": 0.0, "max": 0.0})
            cur["value"] = max(float(cur["value"]), float(v["value"]))
            cur["max"] = max(float(cur["max"]), float(v["max"]))
        for k, v in snap.get("histograms", {}).items():
            cur = out["histograms"].get(k)
            if cur is None:
                out["histograms"][k] = {
                    **v,
                    "bounds": list(v["bounds"]),
                    "counts": list(v["counts"]),
                }
                continue
            if list(cur["bounds"]) != list(v["bounds"]):
                raise ValueError(
                    f"cannot merge histogram {k!r}: bucket bounds differ"
                )
            cur["counts"] = [
                a + b for a, b in zip(cur["counts"], v["counts"])
            ]
            cur["sum"] = cur["sum"] + v["sum"]
            have = cur["count"] > 0
            incoming = v["count"] > 0
            cur["min"] = (
                min(cur["min"], v["min"])
                if have and incoming
                else (v["min"] if incoming else cur["min"])
            )
            cur["max"] = (
                max(cur["max"], v["max"])
                if have and incoming
                else (v["max"] if incoming else cur["max"])
            )
            cur["count"] = cur["count"] + v["count"]
    return out


def summarize(snapshot: Dict) -> Dict:
    """Flat, deterministic (key-sorted) summary of a snapshot: counters as
    values, gauges as value/max, histograms as count/mean/p50/p95/max."""
    out: Dict[str, float] = {}
    for k in sorted(snapshot.get("counters", {})):
        out[k] = snapshot["counters"][k]
    for k in sorted(snapshot.get("gauges", {})):
        g = snapshot["gauges"][k]
        out[f"{k}.value"] = g["value"]
        out[f"{k}.max"] = g["max"]
    for k in sorted(snapshot.get("histograms", {})):
        h = snapshot["histograms"][k]
        n = h["count"]
        out[f"{k}.count"] = n
        out[f"{k}.mean"] = (h["sum"] / n) if n else 0.0
        out[f"{k}.p50"] = _percentile_from_buckets(h, 50.0)
        out[f"{k}.p95"] = _percentile_from_buckets(h, 95.0)
        out[f"{k}.max"] = h["max"]
    return out


def allgather_merged(snapshot: Dict) -> Dict:
    """Cross-host merge: allgather every process's snapshot and merge with
    the per-kind semantics. Single-process returns the snapshot unchanged.
    Every leaf is a float or a fixed-length list of floats, so the pytree
    rides ``process_allgather`` as-is — the same collective-helper pattern
    the checkpoint fallback broadcast uses (train/checkpoint.py)."""
    import jax

    if jax.process_count() == 1:
        return snapshot
    import numpy as np
    from jax.experimental import multihost_utils

    arr_tree = jax.tree_util.tree_map(
        lambda v: np.asarray(v, np.float64), snapshot
    )
    gathered = multihost_utils.process_allgather(arr_tree)
    nproc = jax.process_count()

    def _per_process(i):
        def pick(leaf, orig):
            part = np.asarray(leaf)[i]
            if isinstance(orig, list):
                return [float(x) for x in np.atleast_1d(part)]
            return float(part)

        return jax.tree_util.tree_map(pick, gathered, snapshot)

    return merge_snapshots(*[_per_process(i) for i in range(nproc)])
