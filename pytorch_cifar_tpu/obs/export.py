"""Metrics export: periodic JSONL, end-of-run summary, Prometheus text.

Three consumers, three shapes:

- a *trajectory* consumer (dashboards, the bench history) wants periodic
  snapshots: :class:`MetricsExporter` appends one JSON line per interval
  to ``--metrics_out`` — append-only JSONL so a crash never corrupts the
  lines already written, and a tail -f follows a live run;
- a *run verdict* consumer (the CLIs' end-of-run print, bench's ``obs``
  block) wants one flat deterministic dict: ``registry.summary()``;
- a *scrape* consumer (the serving path; Prometheus/node-exporter
  convention) wants the text exposition format: :func:`prometheus_text`.

No exporter thread exists unless a CLI flag asked for one — constructing
registries and instrumenting code paths starts nothing (pinned by
tests/test_obs.py's disabled-mode case).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Optional

from pytorch_cifar_tpu.obs.metrics import MetricsRegistry


class MetricsExporter:
    """Background thread appending one ``{"ts_s", "seq", "metrics"}`` JSON
    line per ``interval_s`` to ``path``; ``stop()`` writes a final line so
    short runs (shorter than one interval) still export something."""

    def __init__(
        self,
        registry: MetricsRegistry,
        path: str,
        interval_s: float = 10.0,
    ):
        self.registry = registry
        self.path = path
        self.interval_s = float(interval_s)
        self._seq = 0
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        # guards the sequence counter and the thread handle: the export
        # thread and stop()'s final-line write share both (graftcheck
        # unlocked-shared-mutation)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def _write_line(self) -> None:
        with self._lock:
            seq = self._seq
            self._seq += 1
        line = json.dumps(
            {
                "ts_s": round(time.monotonic() - self._t0, 3),
                "seq": seq,
                "metrics": self.registry.snapshot(),
            }
        )
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(line + "\n")

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._write_line()
            except OSError:
                # a full/unmounted disk must degrade metrics, not the run
                pass

    def start(self) -> "MetricsExporter":
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="metrics-exporter", daemon=True
                )
                self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        # take the handle under the lock, join outside it (a concurrent
        # start() must not wait a full interval behind the join)
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None:
            t.join()
        try:
            self._write_line()  # final snapshot even for sub-interval runs
        except OSError:
            pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def _prom_name(name: str) -> str:
    """Dotted registry names -> Prometheus-legal snake metric names."""
    return re.sub(r"[^a-zA-Z0-9_]", "_", name)


def prometheus_text(snapshot: dict, prefix: str = "pct") -> str:
    """Render a snapshot in the Prometheus text exposition format.

    Counters map to ``counter``, gauges emit value and ``_peak``,
    histograms emit the standard cumulative ``_bucket{le=...}`` series
    plus ``_sum``/``_count``. Deterministic: key-sorted, fixed float
    formatting."""
    lines = []
    for k in sorted(snapshot.get("counters", {})):
        n = f"{prefix}_{_prom_name(k)}"
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {snapshot['counters'][k]:g}")
    for k in sorted(snapshot.get("gauges", {})):
        g = snapshot["gauges"][k]
        n = f"{prefix}_{_prom_name(k)}"
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {g['value']:g}")
        lines.append(f"# TYPE {n}_peak gauge")
        lines.append(f"{n}_peak {g['max']:g}")
    for k in sorted(snapshot.get("histograms", {})):
        h = snapshot["histograms"][k]
        n = f"{prefix}_{_prom_name(k)}"
        lines.append(f"# TYPE {n} histogram")
        cum = 0.0
        for bound, c in zip(h["bounds"], h["counts"]):
            cum += c
            lines.append(f'{n}_bucket{{le="{bound:g}"}} {cum:g}')
        cum += h["counts"][len(h["bounds"])]
        lines.append(f'{n}_bucket{{le="+Inf"}} {cum:g}')
        lines.append(f"{n}_sum {h['sum']:g}")
        lines.append(f"{n}_count {h['count']:g}")
    return "\n".join(lines) + "\n"


def write_prometheus(path: str, snapshot: dict, prefix: str = "pct") -> None:
    """Atomic dump of :func:`prometheus_text` (tmp+rename: a scraper
    reading mid-write must never see a half file)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(prometheus_text(snapshot, prefix))
    # graftcheck: noqa[atomic-publish] -- scrape artifact rewritten every interval: a scraper must never see a half file (rename atomicity), but fsync durability buys nothing a crash would not immediately overwrite
    os.replace(tmp, path)
