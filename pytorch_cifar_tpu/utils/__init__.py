from pytorch_cifar_tpu.utils.logging import set_logger
from pytorch_cifar_tpu.utils.progress import format_time, progress_bar
