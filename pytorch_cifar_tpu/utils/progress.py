"""TTY-safe terminal progress bar with per-step and total wall time.

Capability parity with the reference's xlua-style bar (utils.py:52-125)
minus its crash: the reference shells out to ``stty size`` at import time
(utils.py:46-47), which dies in any non-TTY context (CI, piped logs —
SURVEY.md §2.5.10). Here width comes from ``shutil.get_terminal_size`` and
non-TTY streams degrade to periodic plain log lines.
"""

from __future__ import annotations

import shutil
import sys
import time
from typing import Optional

_BAR_FRACTION = 65.0 / 80.0  # bar share of the terminal, like the reference
_last_time: Optional[float] = None
_begin_time: Optional[float] = None


def format_time(seconds: float) -> str:
    """Compact '1D2h3m4s5ms' rendering (parity: utils.py:95-125)."""
    days = int(seconds / 3600 / 24)
    seconds -= days * 3600 * 24
    hours = int(seconds / 3600)
    seconds -= hours * 3600
    minutes = int(seconds / 60)
    seconds -= minutes * 60
    secs = int(seconds)
    millis = int((seconds - secs) * 1000)

    out = ""
    count = 0
    for value, unit in (
        (days, "D"),
        (hours, "h"),
        (minutes, "m"),
        (secs, "s"),
        (millis, "ms"),
    ):
        if value > 0 and count < 2:
            out += f"{value}{unit}"
            count += 1
    return out or "0ms"


def progress_bar(
    current: int, total: int, msg: str = "", stream=None, log_every: int = 50
) -> None:
    """Render step ``current`` of ``total`` (0-based current).

    TTY: in-place bar  [=====>....]  Step: 12ms | Tot: 4s | <msg> 17/391
    non-TTY: one plain line every ``log_every`` steps and on the last step.
    """
    global _last_time, _begin_time
    stream = stream or sys.stdout
    now = time.time()
    if current == 0:
        _begin_time = now
    step_time = now - _last_time if _last_time is not None and current else 0.0
    _last_time = now
    total_time = now - (_begin_time or now)

    tail = f"  Step: {format_time(step_time)} | Tot: {format_time(total_time)}"
    if msg:
        tail += " | " + msg
    counter = f" {current + 1}/{total}"

    if not stream.isatty():
        if current % log_every == 0 or current + 1 >= total:
            stream.write(f"[{current + 1}/{total}]{tail}\n")
            stream.flush()
        return

    cols = shutil.get_terminal_size((80, 24)).columns
    bar_len = max(10, int(cols * _BAR_FRACTION) - 10)
    filled = int(bar_len * (current + 1) / max(total, 1))
    bar = "=" * max(filled - 1, 0) + ">" + "." * (bar_len - filled)
    line = f" [{bar}]{tail}{counter}"
    stream.write("\r" + line[: cols - 1].ljust(cols - 1))
    if current + 1 >= total:
        stream.write("\n")
    stream.flush()
