"""File + console logging (parity: utils.py:128-141, installed
main_dist.py:88)."""

from __future__ import annotations

import logging
import os
from typing import Optional


def set_logger(log_path: Optional[str] = None) -> logging.Logger:
    """Configure the root logger with a console handler and, when
    ``log_path`` is given, a file handler. Idempotent."""
    logger = logging.getLogger()
    logger.setLevel(logging.INFO)

    have_stream = any(
        type(h) is logging.StreamHandler for h in logger.handlers
    )
    if not have_stream:
        sh = logging.StreamHandler()
        sh.setFormatter(logging.Formatter("%(message)s"))
        logger.addHandler(sh)

    if log_path:
        log_path = os.path.abspath(log_path)
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        have_file = any(
            isinstance(h, logging.FileHandler)
            and getattr(h, "baseFilename", None) == log_path
            for h in logger.handlers
        )
        if not have_file:
            fh = logging.FileHandler(log_path)
            fh.setFormatter(
                logging.Formatter("%(asctime)s:%(levelname)s: %(message)s")
            )
            logger.addHandler(fh)
    return logger
