"""File + console logging (parity: utils.py:128-141, installed
main_dist.py:88), rank-aware under multihost."""

from __future__ import annotations

import logging
import os
from typing import Optional


def set_logger(
    log_path: Optional[str] = None, process_index: int = 0
) -> logging.Logger:
    """Configure the root logger with a console handler and, when
    ``log_path`` is given, a file handler. Idempotent.

    ``process_index``: under multihost SPMD every rank runs the same epoch
    loop, so an unfiltered console would print every epoch line N times
    interleaved. Non-zero ranks keep their console at WARNING (problems
    still surface, narration does not) while the file handler — callers
    pass a rank-distinct ``log_path`` — records everything, so a per-rank
    post-mortem loses nothing. Re-calling with a different index adjusts
    the existing console handler (idempotency must not freeze the first
    caller's rank).
    """
    logger = logging.getLogger()
    logger.setLevel(logging.INFO)
    console_level = logging.INFO if process_index == 0 else logging.WARNING

    stream = next(
        (h for h in logger.handlers if type(h) is logging.StreamHandler),
        None,
    )
    if stream is None:
        stream = logging.StreamHandler()
        stream.setFormatter(logging.Formatter("%(message)s"))
        logger.addHandler(stream)
    stream.setLevel(console_level)

    if log_path:
        log_path = os.path.abspath(log_path)
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        have_file = any(
            isinstance(h, logging.FileHandler)
            and getattr(h, "baseFilename", None) == log_path
            for h in logger.handlers
        )
        if not have_file:
            fh = logging.FileHandler(log_path)
            fh.setFormatter(
                logging.Formatter("%(asctime)s:%(levelname)s: %(message)s")
            )
            logger.addHandler(fh)
    return logger
