"""graftcheck engine: parse once, run rules, suppress, baseline, report.

Design constraints:

- **Pure stdlib, pure AST.** The engine never imports the modules it
  lints (importing would execute them — and half the tree initializes a
  jax backend at import time). Everything is ``ast`` + ``tokenize``.
- **Suppressions carry a reason.** ``# graftcheck: noqa[rule] -- reason``
  on the finding's first line (or the line above, for comment-above
  style). A noqa with no reason, or naming an unknown rule, is itself a
  finding (rule ``suppression``) — a silent mute is exactly the
  grandfathering-without-accountability this layer exists to prevent.
- **Baseline = grandfathered findings, keyed by content.** Fingerprints
  hash (rule, basename, normalized source line, occurrence index), so
  they survive line moves and reformats but expire when the flagged code
  changes — a stale entry is reported so the baseline cannot rot.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# engine-level diagnostic "rule" for malformed suppressions; always
# reported (a bad noqa cannot noqa itself)
SUPPRESSION_RULE = "suppression"
# unparseable file: reported as a finding so the CLI exits 1, not 2 — a
# syntax error in LINTED code is a code problem, not a usage problem
PARSE_RULE = "parse-error"

_NOQA_RE = re.compile(
    r"#\s*graftcheck:\s*noqa\[(?P<rules>[^\]]*)\]"
    r"(?:\s*--\s*(?P<reason>\S.*))?"
)


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # repo-relative (or as-given) path, for stable output
    line: int
    col: int
    message: str
    fingerprint: str = ""
    suppressed: bool = False
    suppress_reason: str = ""
    baselined: bool = False

    @property
    def status(self) -> str:
        if self.suppressed:
            return "suppressed"
        if self.baselined:
            return "baselined"
        return "open"

    def to_json(self) -> dict:
        d = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "fingerprint": self.fingerprint,
            "status": self.status,
        }
        if self.suppress_reason:
            d["reason"] = self.suppress_reason
        return d

    def render(self) -> str:
        tag = ""
        if self.suppressed:
            tag = " (suppressed: %s)" % self.suppress_reason
        elif self.baselined:
            tag = " (baselined)"
        return "%s:%d:%d: [%s] %s%s" % (
            self.path, self.line, self.col, self.rule, self.message, tag
        )


class ModuleCtx:
    """One parsed file handed to every rule: path, source, AST, comment
    suppressions, and the lazy whole-project view (``self.project`` —
    config-field tables, the import/call graph, reachability sets)."""

    def __init__(self, path: str, relpath: str, source: str, project,
                 tree: Optional[ast.Module] = None):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        # the project AST cache guarantees ONE parse per file per run:
        # rules walking ctx.tree and the project graph walking the same
        # module see identical node objects (seed sets stay node sets)
        self.tree = (
            tree if tree is not None
            else ast.parse(source, filename=path)
        )
        self.project = project
        self._nodes: Optional[List[ast.AST]] = None
        # line -> list of (frozenset of rule names or {"*"}, reason, raw)
        self.noqa: Dict[int, List[Tuple[frozenset, str]]] = {}
        self.noqa_problems: List[Finding] = []
        self._scan_comments()

    def nodes(self) -> List[ast.AST]:
        """Every node of ``self.tree``, flattened ONCE and shared by all
        rules of the run. With 22 rules each re-running ``ast.walk``
        over the full module, the walk generator machinery — not the
        rule logic — was the biggest single cost of a whole-tree run;
        iterating this list is the same traversal order for a fraction
        of the time."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    def _scan_comments(self) -> None:
        from pytorch_cifar_tpu.lint.rules import rule_names

        known = set(rule_names()) | {"*"}
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [
                (t.start[0], t.string)
                for t in toks
                if t.type == tokenize.COMMENT
            ]
        except tokenize.TokenError:
            comments = []
        for lineno, text in comments:
            m = _NOQA_RE.search(text)
            if not m:
                continue
            rules = frozenset(
                r.strip() for r in m.group("rules").split(",") if r.strip()
            )
            reason = (m.group("reason") or "").strip()
            bad = sorted(r for r in rules if r not in known)
            if not rules or bad:
                self.noqa_problems.append(
                    Finding(
                        SUPPRESSION_RULE, self.relpath, lineno, 0,
                        "noqa names unknown rule(s) %s — see "
                        "`tools/lint.py --list-rules`" % (bad or ["<none>"]),
                    )
                )
                continue
            if not reason:
                self.noqa_problems.append(
                    Finding(
                        SUPPRESSION_RULE, self.relpath, lineno, 0,
                        "noqa without a reason: write "
                        "`# graftcheck: noqa[rule] -- why this is safe`",
                    )
                )
                continue
            self.noqa.setdefault(lineno, []).append((rules, reason))

    def suppression_for(self, finding: Finding):
        """A noqa applies when it sits on the finding's line or on the
        line immediately above (comment-above style for statements too
        long to carry a trailing comment)."""
        for lineno in (finding.line, finding.line - 1):
            for rules, reason in self.noqa.get(lineno, ()):
                if "*" in rules or finding.rule in rules:
                    return reason
        return None


class _Project:
    """Lazy cross-file state shared by every ModuleCtx of one run: the
    config-field tables (flag-config-drift), the shared AST cache (one
    parse per file per run), and the whole-project graph
    (:mod:`pytorch_cifar_tpu.lint.project`) that backs the cross-module
    rules."""

    def __init__(self, repo_root: Optional[str], files: Sequence[str] = ()):
        self.repo_root = repo_root
        self.files = [os.path.abspath(f) for f in files]
        self._config_fields: Optional[Dict[str, set]] = None
        self._ast_cache: Dict[str, Tuple[str, ast.Module]] = {}
        self._graph = None

    def source_and_tree(self, path: str) -> Tuple[str, ast.Module]:
        """Read + parse ``path`` once per run (raises OSError on a
        missing file, SyntaxError on an unparseable one)."""
        ap = os.path.abspath(path)
        hit = self._ast_cache.get(ap)
        if hit is not None:
            return hit
        with open(ap, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=ap)
        self._ast_cache[ap] = (source, tree)
        return source, tree

    def graph(self):
        """The whole-project import/call graph, built on first use over
        this run's file set (plus on-demand external modules)."""
        if self._graph is None:
            from pytorch_cifar_tpu.lint.project import ProjectGraph

            self._graph = ProjectGraph(
                self.repo_root, self.files, self.source_and_tree
            )
        return self._graph

    # -- rule-facing delegates (see project.ProjectGraph) --------------

    def external_traced(self, path: str):
        return self.graph().traced_seeds_for(path)

    def hot_def_nodes(self, path: str):
        return self.graph().hot_def_nodes(path)

    def thread_reachable(self, path: str):
        return self.graph().thread_reachable_for(path)

    def loop_callback_reachable(self, path: str):
        return self.graph().loop_callback_reachable_for(path)

    def sanction_issues(self, path: str):
        return self.graph().sanction_issues_for(path)

    def donating_wrapper(self, path: str, qual: str):
        return self.graph().resolve_donating_wrapper(path, qual)

    def lock_analysis(self):
        return self.graph().locks()

    def exception_flow(self):
        return self.graph().exceptions()

    def fd_lifecycle(self):
        return self.graph().fds()

    def metric_doc_names(self):
        """The metric names OBSERVABILITY.md's tables document, or None
        when the doc cannot be located (fixture trees without a repo
        root: the metric-name-drift rule then stays silent)."""
        if getattr(self, "_metric_docs", None) is None:
            self._metric_docs = (False, None)
            if self.repo_root:
                doc = os.path.join(self.repo_root, "OBSERVABILITY.md")
                if os.path.isfile(doc):
                    from pytorch_cifar_tpu.lint.rules import (
                        parse_metric_doc_names,
                    )

                    with open(doc, encoding="utf-8") as f:
                        self._metric_docs = (
                            True, parse_metric_doc_names(f.read())
                        )
        return self._metric_docs[1]

    def config_fields(self) -> Dict[str, set]:
        """{'TrainConfig': {field/property names}, 'ServeConfig': {...}};
        empty dict when config.py cannot be located (standalone fixture
        trees: the drift rule then only checks in-module evidence)."""
        if self._config_fields is not None:
            return self._config_fields
        self._config_fields = {}
        if self.repo_root:
            cfg = os.path.join(
                self.repo_root, "pytorch_cifar_tpu", "config.py"
            )
            if os.path.isfile(cfg):
                with open(cfg, encoding="utf-8") as f:
                    src = f.read()
                self._config_fields = parse_config_fields(src)
        return self._config_fields


def parse_config_fields(source: str) -> Dict[str, set]:
    """Extract dataclass field + @property names for the config classes."""
    out: Dict[str, set] = {}
    tree = ast.parse(source)
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        if node.name not in ("TrainConfig", "ServeConfig"):
            continue
        names = set()
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                names.add(stmt.target.id)
            elif isinstance(stmt, ast.FunctionDef):
                names.add(stmt.name)
        out[node.name] = names
    return out


def _find_repo_root(path: str) -> Optional[str]:
    """Walk up from ``path`` to the directory containing the package."""
    d = os.path.abspath(path)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    for _ in range(12):
        if os.path.isfile(
            os.path.join(d, "pytorch_cifar_tpu", "config.py")
        ):
            return d
        nxt = os.path.dirname(d)
        if nxt == d:
            return None
        d = nxt
    return None


def collect_python_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into a sorted list of .py files."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".pytest_cache")
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
        elif p.endswith(".py"):
            out.append(p)
        else:
            raise FileNotFoundError(p)
    seen, uniq = set(), []
    for p in out:
        ap = os.path.abspath(p)
        if ap not in seen:
            seen.add(ap)
            uniq.append(p)
    return uniq


def _fingerprints(findings: List[Finding], ctx: ModuleCtx) -> None:
    """Content-keyed fingerprint: hash of (rule, basename, normalized
    flagged line, k) with k disambiguating identical lines — stable
    under line moves/renumbering, expired by edits to the flagged code."""
    counts: Dict[str, int] = {}
    for f in findings:
        src = ""
        if 1 <= f.line <= len(ctx.lines):
            src = "".join(ctx.lines[f.line - 1].split())
        base = "%s:%s:%s" % (f.rule, os.path.basename(f.path), src)
        k = counts.get(base, 0)
        counts[base] = k + 1
        f.fingerprint = hashlib.sha1(
            ("%s:%d" % (base, k)).encode()
        ).hexdigest()[:16]


def lint_file(
    path: str,
    rules=None,
    relpath: Optional[str] = None,
    project=None,
    stats: Optional[Dict[str, dict]] = None,
) -> List[Finding]:
    """Run ``rules`` (default: all) over one file; returns findings with
    fingerprints computed and inline suppressions applied. ``stats``
    (optional dict) accumulates per-rule wall time and finding counts
    across calls — the CLI's ``--stats`` view."""
    import time

    from pytorch_cifar_tpu.lint.rules import RULES

    rules = RULES if rules is None else rules
    relpath = relpath or path
    if project is None:
        project = _Project(_find_repo_root(path), files=[path])
    try:
        source, tree = project.source_and_tree(path)
    except SyntaxError as e:
        return [
            Finding(
                PARSE_RULE, relpath, e.lineno or 1, e.offset or 0,
                "file does not parse: %s" % e.msg,
            )
        ]
    ctx = ModuleCtx(path, relpath, source, project, tree=tree)
    findings: List[Finding] = list(ctx.noqa_problems)
    for rule in rules:
        t0 = time.perf_counter()
        rule_findings = list(rule.check(ctx))
        if stats is not None:
            s = stats.setdefault(
                rule.name, {"seconds": 0.0, "findings": 0}
            )
            s["seconds"] += time.perf_counter() - t0
            s["findings"] += len(rule_findings)
        for f in rule_findings:
            f.path = relpath
            findings.append(f)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    _fingerprints(findings, ctx)
    for f in findings:
        if f.rule in (SUPPRESSION_RULE, PARSE_RULE):
            continue  # meta-findings cannot be noqa'd away
        reason = ctx.suppression_for(f)
        if reason is not None:
            f.suppressed = True
            f.suppress_reason = reason
    return findings


@dataclasses.dataclass
class LintRun:
    findings: List[Finding]
    files: List[str]  # repo-relative paths of every file linted
    stats: Dict[str, dict] = dataclasses.field(default_factory=dict)
    project: Optional[_Project] = None


def lint_paths(
    paths: Sequence[str],
    rules=None,
    repo_root: Optional[str] = None,
) -> LintRun:
    """Lint every .py under ``paths``. Paths are reported relative to
    ``repo_root`` (default: auto-detected) when possible. Returns the
    findings plus the full linted-file list (a clean file produces no
    findings but still anchors stale-baseline detection), per-rule
    timing stats, and the project handle (import graph access)."""
    files = collect_python_files(paths)
    root = repo_root or (_find_repo_root(files[0]) if files else None)
    project = _Project(root, files=files)
    findings: List[Finding] = []
    rels: List[str] = []
    stats: Dict[str, dict] = {}
    for path in files:
        rel = path
        if root:
            try:
                rel = os.path.relpath(os.path.abspath(path), root)
            except ValueError:
                rel = path
        rels.append(rel)
        findings.extend(
            lint_file(
                path, rules=rules, relpath=rel, project=project,
                stats=stats,
            )
        )
    return LintRun(findings, rels, stats, project)


# -- baseline ----------------------------------------------------------


class BaselineError(ValueError):
    """Malformed baseline file (a usage error: CLI exits 2)."""


def load_baseline(path: str) -> List[dict]:
    with open(path, encoding="utf-8") as f:
        try:
            obj = json.load(f)
        except ValueError as e:
            raise BaselineError("%s: not valid JSON: %s" % (path, e))
    if (
        not isinstance(obj, dict)
        or obj.get("version") != 1
        or not isinstance(obj.get("findings"), list)
    ):
        raise BaselineError(
            "%s: expected {'version': 1, 'findings': [...]}" % path
        )
    for e in obj["findings"]:
        if not isinstance(e, dict) or "fingerprint" not in e:
            raise BaselineError(
                "%s: baseline entries need a 'fingerprint'" % path
            )
    return obj["findings"]


def write_baseline(path: str, findings: Iterable[Finding]) -> int:
    """Write the grandfather file from the run's OPEN findings (already-
    suppressed ones stay suppressed inline). Returns the entry count."""
    entries = [
        {
            "rule": f.rule,
            "path": f.path.replace(os.sep, "/"),
            "fingerprint": f.fingerprint,
        }
        for f in findings
        if f.status == "open" and f.rule != SUPPRESSION_RULE
    ]
    payload = json.dumps({"version": 1, "findings": entries}, indent=2)
    tmp = path + ".tmp.%d" % os.getpid()
    # tmp+fsync+rename (the atomic-publish rule's own sanctioned shape):
    # the baseline is checked in and hand-reviewed, so a crash must leave
    # either the old complete file or the new complete file
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(payload + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return len(entries)


def match_baseline(
    findings: List[Finding],
    entries: List[dict],
    linted_files: Optional[Sequence[str]] = None,
) -> List[dict]:
    """Mark findings present in the baseline as ``baselined``; return the
    STALE entries — baseline lines whose file was linted this run but
    whose finding no longer exists (fixed or edited code), so the
    baseline can be pruned with ``--write-baseline``. Entries for files
    outside this run's path set are left alone (a partial run must not
    declare the rest of the baseline stale)."""
    by_fp = {f.fingerprint: f for f in findings}
    if linted_files is None:
        linted = {f.path.replace(os.sep, "/") for f in findings}
    else:
        linted = {p.replace(os.sep, "/") for p in linted_files}
    stale = []
    for e in entries:
        f = by_fp.get(e["fingerprint"])
        if f is not None:
            f.baselined = True
        elif e.get("path") in linted:
            stale.append(e)
    return stale


# -- reporting ---------------------------------------------------------


def summarize(findings: List[Finding]) -> Dict[str, int]:
    c = {"total": len(findings), "open": 0, "suppressed": 0, "baselined": 0}
    for f in findings:
        c[f.status] += 1
    return c


def render_report(
    findings: List[Finding], stale: Sequence[dict] = (), verbose: bool = False
) -> str:
    lines = []
    for f in findings:
        if f.status == "open" or verbose:
            lines.append(f.render())
    for e in stale:
        lines.append(
            "stale baseline entry: %s [%s] %s — fixed or edited; refresh "
            "with --write-baseline"
            % (e.get("path", "?"), e.get("rule", "?"), e["fingerprint"])
        )
    c = summarize(findings)
    lines.append(
        "graftcheck: %d finding(s) — %d open, %d suppressed, %d baselined"
        % (c["total"], c["open"], c["suppressed"], c["baselined"])
        + (", %d stale baseline entr%s" % (
            len(stale), "y" if len(stale) == 1 else "ies"
        ) if stale else "")
    )
    return "\n".join(lines)


def json_report(
    findings: List[Finding], stale: Sequence[dict] = ()
) -> dict:
    from pytorch_cifar_tpu.lint.rules import rule_names

    return {
        "version": 1,
        "rules": list(rule_names()),
        "counts": summarize(findings),
        "findings": [f.to_json() for f in findings],
        "stale_baseline": list(stale),
    }


def sarif_report(findings: List[Finding]) -> dict:
    """SARIF 2.1.0 (the `--sarif` CLI mode): the schema code-review
    tooling (GitHub code scanning, VS Code SARIF viewers) renders
    inline. Open findings are level `error`; suppressed/baselined ones
    ride along with a `suppressions` entry so the tooling shows them as
    reviewed, not hides them. The content fingerprint doubles as the
    SARIF partial fingerprint, so alert identity survives line moves
    exactly like the baseline does."""
    from pytorch_cifar_tpu.lint.rules import RULES

    rules_meta = [
        {
            "id": r.name,
            "shortDescription": {"text": r.summary},
        }
        for r in RULES
    ] + [
        {
            "id": SUPPRESSION_RULE,
            "shortDescription": {
                "text": "malformed graftcheck noqa comment"
            },
        },
        {
            "id": PARSE_RULE,
            "shortDescription": {"text": "file does not parse"},
        },
    ]
    results = []
    for f in findings:
        res = {
            "ruleId": f.rule,
            "level": "error" if f.status == "open" else "note",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path.replace(os.sep, "/"),
                        },
                        "region": {
                            "startLine": max(f.line, 1),
                            "startColumn": f.col + 1,
                        },
                    }
                }
            ],
        }
        if f.fingerprint:
            res["partialFingerprints"] = {
                "graftcheck/v1": f.fingerprint,
            }
        if f.suppressed:
            res["suppressions"] = [
                {"kind": "inSource", "justification": f.suppress_reason}
            ]
        elif f.baselined:
            res["suppressions"] = [{"kind": "external"}]
        results.append(res)
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftcheck",
                        "informationUri": "STATIC_ANALYSIS.md",
                        "rules": rules_meta,
                    }
                },
                "results": results,
            }
        ],
    }
