"""graftcheck rules: 22 JAX/concurrency invariants this repo has bled for.

Every rule is grounded in a failure mode from this repo's own history
(STATIC_ANALYSIS.md has the catalog with one real-world example each).
Rules are deliberately CONSERVATIVE: a lint that cries wolf gets turned
off, so each detector only fires on patterns it can resolve statically —
the fixture tests in tests/test_lint.py pin both the positive (fires)
and negative (stays quiet) cases for each rule.

Since PR 8 the rules see the WHOLE linted tree, not one module at a
time: ``ctx.project`` carries an import graph and a cross-module call
graph (:mod:`pytorch_cifar_tpu.lint.project`), so traced closures are
followed across module boundaries, the dp.py donation table is derived
from dp.py's own AST (aliases included), host-sync hot paths are scoped
by reachability from the trainer step loop / engine dispatch, and
thread-entry reachability backs the thread-collective rule.

Shared analyses:

- :func:`traced_functions` — which function defs end up inside a jax
  trace (jit/scan/vmap/grad/pallas_call/AOT ``.lower``, decorators,
  ``make_*_step``/``make_*_epoch`` factory returns, lexical nesting, one
  same-module call-graph fixpoint, plus the project graph's
  externally-traced seeds).
- :func:`qualname` — dotted-name resolution for Name/Attribute chains.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pytorch_cifar_tpu.lint.engine import Finding, ModuleCtx
from pytorch_cifar_tpu.lint.locks import _classify_blocking
from pytorch_cifar_tpu.lint.project import (  # noqa: F401  (re-exported)
    HOST_COLLECTIVES,
    TRACER_CALLS,
    TRACER_DECORATORS,
    FuncNode,
    parents_map,
    qualname,
    walk_no_nested_funcs,
)

_FACTORY_RE = re.compile(r"^make_\w*?(step|epoch|fn)\w*$")


def _decorator_traces(dec: ast.AST) -> bool:
    q = qualname(dec)
    if q in TRACER_DECORATORS:
        return True
    if isinstance(dec, ast.Call):
        fq = qualname(dec.func)
        if fq in TRACER_DECORATORS:
            return True
        # functools.partial(jax.jit, static_argnames=...) styles
        if fq in ("partial", "functools.partial") and dec.args:
            return qualname(dec.args[0]) in TRACER_DECORATORS
    return False


def traced_functions(ctx: ModuleCtx) -> Set[ast.AST]:
    """Function-def nodes whose bodies execute under a jax trace.

    Seeds: tracer decorators; function names (or ``self.X`` aliases of
    local defs) passed to TRACER_CALLS / ``jax.jit(...).lower``; defs
    RETURNED from a ``make_*step``/``make_*epoch`` factory (this repo's
    convention for step closures that the trainer jits later); and the
    project graph's externally-traced seeds — defs of THIS module that
    some other module hands to a tracer (directly, via a re-export, or
    as a factory whose returned closure gets jitted). Closure: defs
    lexically nested in a traced def, and same-module defs called by
    name from a traced body (one fixpoint).

    Memoized per file on the run's project handle: three rules ask for
    the same module's traced set, and the fixpoint is the single most
    expensive per-file pass in the suite."""
    cache = getattr(ctx.project, "_traced_fn_cache", None)
    if cache is None:
        cache = ctx.project._traced_fn_cache = {}
    ckey = os.path.abspath(ctx.path)
    if ckey in cache:
        return cache[ckey]
    tree = ctx.tree
    defs_by_name: Dict[str, List[ast.AST]] = {}
    parents = parents_map(tree)
    all_defs: List[ast.AST] = []
    for node in ctx.nodes():
        if isinstance(node, FuncNode):
            all_defs.append(node)
            defs_by_name.setdefault(node.name, []).append(node)

    def enclosing_func(node: ast.AST):
        p = parents.get(node)
        while p is not None and not isinstance(p, FuncNode):
            p = parents.get(p)
        return p

    def local_def(name: str, at: ast.AST):
        """The def ``name`` visible from node ``at``: nearest enclosing
        scope owning one, else a module-level one."""
        cands = defs_by_name.get(name)
        if not cands:
            return None
        scope = enclosing_func(at)
        while scope is not None:
            for d in cands:
                if enclosing_func(d) is scope:
                    return d
            scope = enclosing_func(scope)
        for d in cands:
            p = enclosing_func(d)
            if p is None and not isinstance(parents.get(d), ast.ClassDef):
                return d
        return None

    # self.X = <local def> aliases (the engine's self._fwd pattern)
    self_alias: Dict[str, ast.AST] = {}
    for node in ctx.nodes():
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Name)
            and node.value.id in defs_by_name
        ):
            for tgt in node.targets:
                q = qualname(tgt)
                if q and q.startswith("self."):
                    d = local_def(node.value.id, node)
                    if d is not None:
                        self_alias[q] = d

    traced: Set[ast.AST] = set(ctx.project.external_traced(ctx.path))

    def seed(fn_expr: ast.AST, at: ast.AST) -> None:
        if isinstance(fn_expr, ast.Lambda):
            return  # lambdas have no statements worth walking here
        q = qualname(fn_expr)
        if q is None:
            return
        if q in self_alias:
            traced.add(self_alias[q])
        elif "." not in q:
            d = local_def(q, at)
            if d is not None:
                traced.add(d)

    for node in ctx.nodes():
        if isinstance(node, FuncNode):
            if any(_decorator_traces(d) for d in node.decorator_list):
                traced.add(node)
            # `return step` from a make_*_step factory
            if _FACTORY_RE.match(node.name):
                for stmt in ast.walk(node):
                    if isinstance(stmt, ast.Return) and isinstance(
                        stmt.value, ast.Name
                    ):
                        d = local_def(stmt.value.id, stmt)
                        if d is not None and enclosing_func(d) is node:
                            traced.add(d)
        if isinstance(node, ast.Call):
            q = qualname(node.func)
            if q in TRACER_CALLS:
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    seed(arg, node)

    # lexical nesting + same-module call graph, to fixpoint
    changed = True
    while changed:
        changed = False
        for d in all_defs:
            if d in traced:
                continue
            p = enclosing_func(d)
            while p is not None:
                if p in traced:
                    traced.add(d)
                    changed = True
                    break
                p = enclosing_func(p)
        for t in list(traced):
            for node in walk_no_nested_funcs(t):
                if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Name
                ):
                    d = local_def(node.func.id, node)
                    if d is not None and d not in traced:
                        traced.add(d)
                        changed = True
    cache[ckey] = traced
    return traced


class Rule:
    name = "abstract"
    summary = ""

    def check(self, ctx: ModuleCtx) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, ctx: ModuleCtx, node: ast.AST, msg: str) -> Finding:
        return Finding(
            self.name, ctx.relpath,
            getattr(node, "lineno", 1), getattr(node, "col_offset", 0), msg,
        )


# ---------------------------------------------------------------------
# 1. jit-impurity
# ---------------------------------------------------------------------

_TIME_FNS = {
    "time", "perf_counter", "perf_counter_ns", "monotonic", "monotonic_ns",
    "sleep", "process_time", "time_ns",
}
_METRIC_MUTATORS = {"inc", "observe"}
_OS_SAFE_PREFIXES = ("os.path.", "os.environ.get", "os.getenv", "os.sep")


class JitImpurity(Rule):
    name = "jit-impurity"
    summary = (
        "side-effecting call (metrics, logging, time, I/O) inside a "
        "jax-traced function — it runs ONCE at trace time, then never "
        "again in the compiled program"
    )

    def _impure(self, call: ast.Call) -> Optional[str]:
        q = qualname(call.func)
        if q is None:
            # `.set(...)` etc. on computed receivers
            if isinstance(call.func, ast.Attribute):
                a = call.func.attr
                if a in _METRIC_MUTATORS:
                    return "metric %s()" % a
                if a == "set" and not self._is_at_set(call.func):
                    return "gauge/event .set()"
            return None
        last = q.rsplit(".", 1)[-1]
        if q == "print":
            return "print()"
        if q == "open":
            return "open()"
        if q.startswith("time.") and last in _TIME_FNS:
            return q + "()"
        if q.startswith("os.") and not q.startswith(_OS_SAFE_PREFIXES):
            return q + "()"
        if q.split(".", 1)[0] in ("log", "logger", "logging") and "." in q:
            return q + "()"
        if q in ("trace.span", "trace.instant") or q.endswith(
            (".trace.span", ".trace.instant")
        ):
            return q + "()"
        if last in _METRIC_MUTATORS and "." in q:
            return q + "()"
        if last == "set" and "." in q and not self._is_at_set(call.func):
            return q + "()"
        if last == "write" and "." in q:
            return q + "()"
        return None

    @staticmethod
    def _is_at_set(func: ast.Attribute) -> bool:
        """True for jax's functional update `x.at[i].set(v)`."""
        v = func.value
        return (
            isinstance(v, ast.Subscript)
            and isinstance(v.value, ast.Attribute)
            and v.value.attr == "at"
        )

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        out = []
        for fn in traced_functions(ctx):
            for node in walk_no_nested_funcs(fn):
                if isinstance(node, ast.Call):
                    why = self._impure(node)
                    if why:
                        out.append(
                            self.finding(
                                ctx, node,
                                "%s inside traced function %r runs once "
                                "at trace time, not per step — hoist it "
                                "to the host loop or use jax-native "
                                "callbacks" % (why, fn.name),
                            )
                        )
        return out


# ---------------------------------------------------------------------
# 2. prng-reuse
# ---------------------------------------------------------------------

_KEY_PRODUCERS = {
    "jax.random.PRNGKey", "random.PRNGKey", "jax.random.key",
    "jax.random.split", "random.split",
    "jax.random.fold_in", "random.fold_in",
}
_NONCONSUMING = {"jax.random.fold_in", "random.fold_in"}
_KEY_PARAM_RE = re.compile(r"^(key|rng|prng\w*|\w+_key|\w+_rng)$")


class PrngReuse(Rule):
    name = "prng-reuse"
    summary = (
        "a PRNG key consumed more than once without split/fold_in — the "
        "two draws are IDENTICAL (correlated randomness), the classic "
        "silent jax.random bug"
    )

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        out = []
        for node in ctx.nodes():
            if isinstance(node, FuncNode):
                out.extend(self._check_fn(ctx, node))
        return out

    def _check_fn(self, ctx: ModuleCtx, fn) -> List[Finding]:
        # a key-NAMED parameter is only tracked when the function shows
        # jax.random evidence for it (it appears inside a jax.random.*
        # call somewhere) — `put(self, key, val)` on a cache class must
        # not be mistaken for a PRNG key
        evidenced: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                q = qualname(node.func)
                if q and (
                    q.startswith("jax.random.") or q in _KEY_PRODUCERS
                ):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Name) and isinstance(
                            sub.ctx, ast.Load
                        ):
                            evidenced.add(sub.id)
        keys: Set[str] = set()
        args = fn.args
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        ):
            if _KEY_PARAM_RE.match(a.arg) and a.arg in evidenced:
                keys.add(a.arg)

        findings: List[Finding] = []
        flagged: Set[str] = set()

        def producer_targets(stmt) -> List[str]:
            """Names bound to fresh keys by this statement, or []."""
            if not isinstance(stmt, ast.Assign) or not isinstance(
                stmt.value, ast.Call
            ):
                return []
            q = qualname(stmt.value.func)
            if q not in _KEY_PRODUCERS:
                return []
            names = []
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    names.append(tgt.id)
                elif isinstance(tgt, (ast.Tuple, ast.List)):
                    names.extend(
                        e.id for e in tgt.elts if isinstance(e, ast.Name)
                    )
            return names

        def uses_in(node: ast.AST) -> Dict[str, List[ast.AST]]:
            """key-name -> consumption sites inside ``node`` (one
            statement / expression), honoring fold_in non-consumption.
            A reference inside a nested def counts once (closure
            capture)."""
            sites: Dict[str, List[ast.AST]] = {}

            def visit(n: ast.AST, in_nested: bool) -> None:
                if isinstance(n, ast.Call):
                    q = qualname(n.func)
                    skip_args = q in _NONCONSUMING
                    for child in ast.iter_child_nodes(n):
                        if skip_args and child is not n.func:
                            # fold_in derives; its key operand survives
                            for sub in ast.walk(child):
                                if (
                                    isinstance(sub, ast.Call)
                                ):
                                    visit(sub, in_nested)
                            continue
                        visit(child, in_nested)
                    return
                if isinstance(n, FuncNode + (ast.Lambda,)):
                    # closure capture counts once — but a name declared
                    # as a PARAMETER anywhere inside shadows the outer
                    # key and is that scope's own binding, not a use
                    shadowed: Set[str] = set()
                    for sub in ast.walk(n):
                        if isinstance(sub, FuncNode + (ast.Lambda,)):
                            sa = sub.args
                            for a in (
                                list(sa.posonlyargs)
                                + list(sa.args)
                                + list(sa.kwonlyargs)
                            ):
                                shadowed.add(a.arg)
                    seen: Set[str] = set()
                    for sub in ast.walk(n):
                        if (
                            isinstance(sub, ast.Name)
                            and isinstance(sub.ctx, ast.Load)
                            and sub.id in keys
                            and sub.id not in seen
                            and sub.id not in shadowed
                        ):
                            seen.add(sub.id)
                            sites.setdefault(sub.id, []).append(n)
                    return
                if (
                    isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)
                    and n.id in keys
                ):
                    sites.setdefault(n.id, []).append(n)
                for child in ast.iter_child_nodes(n):
                    visit(child, in_nested)

            visit(node, False)
            return sites

        def merge_max(a, b):
            out = dict(a)
            for k, v in b.items():
                if len(v) > len(out.get(k, [])):
                    out[k] = v
            return out

        def run_block(stmts, counts: Dict[str, List[ast.AST]]):
            """Sequential count of consumptions per key var; If branches
            merge by max (exclusive paths). Returns updated counts."""
            for stmt in stmts:
                if isinstance(stmt, ast.If):
                    counts = merge_max(
                        run_block(stmt.body, dict(counts)),
                        run_block(stmt.orelse, dict(counts)),
                    )
                    # the test itself may consume
                    counts = note(uses_in(stmt.test), counts, stmt)
                    continue
                if isinstance(stmt, (ast.For, ast.While)):
                    inner = (
                        [stmt.iter] if isinstance(stmt, ast.For)
                        else [stmt.test]
                    )
                    for e in inner:
                        counts = note(uses_in(e), counts, stmt)
                    counts = run_block(
                        list(stmt.body) + list(stmt.orelse), counts
                    )
                    continue
                if isinstance(stmt, ast.Try):
                    counts = run_block(stmt.body, counts)
                    for h in stmt.handlers:
                        counts = run_block(h.body, counts)
                    counts = run_block(
                        list(stmt.orelse) + list(stmt.finalbody), counts
                    )
                    continue
                if isinstance(stmt, ast.With):
                    for item in stmt.items:
                        counts = note(
                            uses_in(item.context_expr), counts, stmt
                        )
                    counts = run_block(stmt.body, counts)
                    continue
                fresh = producer_targets(stmt)
                # consumptions in this statement's expressions (for an
                # Assign, the value side — targets are stores)
                exprs = [stmt]
                if isinstance(stmt, ast.Assign):
                    exprs = [stmt.value]
                elif isinstance(stmt, ast.AugAssign):
                    exprs = [stmt.value]
                elif isinstance(stmt, ast.AnnAssign):
                    exprs = [stmt.value] if stmt.value else []
                for e in exprs:
                    counts = note(uses_in(e), counts, stmt)
                # rebinding resets the trail; fresh producer targets
                # (re)enter the key set
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    tgts = (
                        stmt.targets
                        if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                    for tgt in tgts:
                        names = (
                            [tgt]
                            if isinstance(tgt, ast.Name)
                            else [
                                e for e in getattr(tgt, "elts", [])
                                if isinstance(e, ast.Name)
                            ]
                        )
                        for nm in names:
                            counts.pop(nm.id, None)
                            if nm.id in fresh:
                                keys.add(nm.id)
                            elif (
                                isinstance(stmt, ast.Assign)
                                and isinstance(stmt.value, ast.Name)
                                and stmt.value.id in keys
                            ):
                                keys.add(nm.id)  # alias of a key
                            else:
                                keys.discard(nm.id)
            return counts

        def note(sites, counts, stmt):
            counts = dict(counts)
            for name, uses in sites.items():
                prior = counts.get(name, [])
                total = prior + uses
                if len(total) > 1 and name not in flagged:
                    flagged.add(name)
                    at = total[1]
                    findings.append(
                        self.finding(
                            ctx,
                            at if hasattr(at, "lineno") else stmt,
                            "PRNG key %r is consumed more than once in "
                            "%r without an intervening split/fold_in — "
                            "both draws see identical bits" % (
                                name, fn.name,
                            ),
                        )
                    )
                counts[name] = total
            return counts

        run_block(fn.body, {})
        return findings


# ---------------------------------------------------------------------
# 3. tracer-branch
# ---------------------------------------------------------------------

_JAX_VALUE_PREFIXES = (
    "jnp.", "jax.numpy.", "jax.lax.", "lax.", "jax.random.", "jsp.",
)


class TracerBranch(Rule):
    name = "tracer-branch"
    summary = (
        "Python if/while on a traced value inside a jax-traced function "
        "— raises ConcretizationTypeError or silently specializes at "
        "trace time; use jnp.where / lax.cond"
    )

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        out = []
        for fn in traced_functions(ctx):
            jax_valued: Set[str] = set()
            # first pass: names assigned from jnp/lax/random calls (or
            # expressions containing one / another jax-valued name)
            for node in walk_no_nested_funcs(fn):
                if isinstance(node, ast.Assign) and self._jaxish(
                    node.value, jax_valued
                ):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            jax_valued.add(tgt.id)
                        elif isinstance(tgt, (ast.Tuple, ast.List)):
                            jax_valued.update(
                                e.id for e in tgt.elts
                                if isinstance(e, ast.Name)
                            )
            for node in walk_no_nested_funcs(fn):
                if isinstance(node, (ast.If, ast.While)) and self._jaxish(
                    node.test, jax_valued, test_position=True
                ):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    out.append(
                        self.finding(
                            ctx, node,
                            "`%s` on a traced value inside traced "
                            "function %r — the branch is resolved ONCE "
                            "at trace time; use jnp.where / jax.lax.cond "
                            "/ lax.while_loop" % (kind, fn.name),
                        )
                    )
        return out

    def _jaxish(
        self, expr: ast.AST, jax_valued: Set[str], test_position=False
    ) -> bool:
        # `x is None` / `x is not None` identity tests are static even
        # when x later holds a tracer-producing default — never flag
        if (
            test_position
            and isinstance(expr, ast.Compare)
            and any(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops)
        ):
            return False
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                q = qualname(node.func)
                if q and q.startswith(_JAX_VALUE_PREFIXES):
                    return True
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in jax_valued
            ):
                return True
        return False


# ---------------------------------------------------------------------
# 4. host-sync
# ---------------------------------------------------------------------

_DEVICE_CALL_ATTRS = frozenset({
    "train_step", "eval_step", "train_epoch_fn", "eval_epoch_fn",
})
_HOST_FETCHERS = frozenset({
    "float", "int", "np.asarray", "np.array", "numpy.asarray",
    "numpy.array", "np.float32", "np.float64",
})


class HostSync(Rule):
    name = "host-sync"
    summary = (
        ".item()/float()/np.asarray() on a jax array on a hot path — "
        "any function reachable from the trainer step loop or engine "
        "dispatch (project call-graph reachability, seeds in "
        "project.HOT_SEEDS) — a hidden blocking D2H sync that stalls "
        "dispatch run-ahead (the reference's per-step .item() trap)"
    )

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        out = []
        for fn in ctx.project.hot_def_nodes(ctx.path):
            if isinstance(fn, FuncNode):
                out.extend(self._check_fn(ctx, fn))
        return out

    @staticmethod
    def _is_device_call(call: ast.Call) -> bool:
        f = call.func
        q = qualname(f)
        if q:
            if q == "jax.device_get":
                return False
            if q.startswith(("jnp.", "jax.numpy.", "jax.lax.")):
                return True
            if q.startswith("self.") and q.rsplit(".", 1)[-1] in (
                _DEVICE_CALL_ATTRS
            ):
                return True
        # self._compiled[b](...) — AOT executable dispatch
        if isinstance(f, ast.Subscript):
            sq = qualname(f.value)
            if sq and sq.endswith("_compiled"):
                return True
        return False

    def _check_fn(self, ctx: ModuleCtx, fn) -> List[Finding]:
        device: Set[str] = set()
        host: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                q = qualname(node.value.func)
                names = []
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        names.append(tgt.id)
                    elif isinstance(tgt, (ast.Tuple, ast.List)):
                        names.extend(
                            e.id for e in tgt.elts
                            if isinstance(e, ast.Name)
                        )
                if q == "jax.device_get":
                    host.update(names)
                elif self._is_device_call(node.value):
                    device.update(names)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Name
            ):
                if node.value.id in device:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            device.add(tgt.id)
        device -= host

        out = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            # any .item() in a hot function is a sync
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
            ):
                out.append(
                    self.finding(
                        ctx, node,
                        ".item() in hot function %r blocks on the device "
                        "— accumulate on device and fetch once with "
                        "jax.device_get" % fn.name,
                    )
                )
                continue
            q = qualname(node.func)
            if q not in _HOST_FETCHERS or not node.args:
                continue
            arg = node.args[0]
            sync = False
            if isinstance(arg, ast.Name) and arg.id in device:
                sync = True
            elif isinstance(arg, ast.Call) and self._is_device_call(arg):
                sync = True
            elif isinstance(arg, ast.Subscript):
                base = arg.value
                if isinstance(base, ast.Name) and base.id in device:
                    sync = True
            if sync:
                out.append(
                    self.finding(
                        ctx, node,
                        "%s() on a device value in hot function %r is a "
                        "hidden blocking transfer — route it through one "
                        "explicit jax.device_get at the sync point"
                        % (q, fn.name),
                    )
                )
        return out


# ---------------------------------------------------------------------
# 5. donation-misuse
# ---------------------------------------------------------------------

class DonationMisuse(Rule):
    name = "donation-misuse"
    summary = (
        "an argument donated via donate_argnums — or through a donating "
        "wrapper jit like dp.py's data_parallel_train_step/epoch, "
        "resolved from the wrapper's OWN AST through the import graph "
        "(aliases included) — is read again after the jitted call: the "
        "buffer was handed to XLA and may already hold the output "
        "(garbage reads, or the donate-same-buffer abort)"
    )

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        out = []
        for node in ctx.nodes():
            if isinstance(node, FuncNode):
                out.extend(self._check_fn(ctx, node))
        return out

    @staticmethod
    def _wrapper_for(
        ctx: ModuleCtx, qual: Optional[str], local_alias: Dict[str, str]
    ):
        """Donation info for a call target: (positions, gate param) or
        None. Follows function-local aliases (``f = wrapper; step =
        f(...)``) before resolving through the project graph — which
        itself follows module aliases, imports, and re-exports down to
        the wrapper def's ``jax.jit(..., donate_argnums=...)``."""
        if not qual:
            return None
        for _ in range(4):  # bounded local alias chain
            nxt = local_alias.get(qual)
            if nxt is None or nxt == qual:
                break
            qual = nxt
        return ctx.project.donating_wrapper(ctx.path, qual)

    @classmethod
    def _donated_positions(
        cls, ctx: ModuleCtx, call: ast.Call, local_alias: Dict[str, str]
    ) -> Optional[List[int]]:
        q = qualname(call.func)
        if q in ("jax.jit", "jit"):
            for kw in call.keywords:
                if kw.arg != "donate_argnums":
                    continue
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return [v.value]
                if isinstance(v, (ast.Tuple, ast.List)):
                    pos = []
                    for e in v.elts:
                        if isinstance(e, ast.Constant) and isinstance(
                            e.value, int
                        ):
                            pos.append(e.value)
                    return pos
            return None
        # donating wrapper jits (dp.py's data_parallel_*): positions and
        # the gate parameter come from the wrapper's own AST. The gate
        # (donate=False) turns donation off; any other value — a
        # variable, True — keeps the conservative default: donated.
        info = cls._wrapper_for(ctx, q, local_alias)
        if info is not None:
            positions, gate = info
            for kw in call.keywords:
                if (
                    gate is not None
                    and kw.arg == gate
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False
                ):
                    return None
            return list(positions)
        return None

    def _check_fn(self, ctx: ModuleCtx, fn) -> List[Finding]:
        donating: Dict[str, List[int]] = {}
        out: List[Finding] = []
        seen_sites: Set[Tuple[int, int, str]] = set()
        # function-local wrapper aliases: `f = data_parallel_train_step`
        local_alias: Dict[str, str] = {}
        for node in walk_no_nested_funcs(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, (ast.Name, ast.Attribute)
            ):
                vq = qualname(node.value)
                if vq is None:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        local_alias[tgt.id] = vq

        def scan_block(stmts):
            for i, stmt in enumerate(stmts):
                # record `g = jax.jit(f, donate_argnums=...)`
                if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Call
                ):
                    pos = self._donated_positions(
                        ctx, stmt.value, local_alias
                    )
                    if pos is not None:
                        for tgt in stmt.targets:
                            if isinstance(tgt, ast.Name):
                                donating[tgt.id] = pos
                # find calls of a donating function in this statement
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    if not isinstance(node.func, ast.Name):
                        continue
                    pos = donating.get(node.func.id)
                    if pos is None:
                        continue
                    donated_names = set()
                    for p in pos:
                        if p >= len(node.args):
                            continue
                        arg = node.args[p]
                        if isinstance(arg, ast.Name):
                            donated_names.add(arg.id)
                        elif isinstance(arg, (ast.Tuple, ast.List)):
                            # batch tuples: step(state, (images, labels),
                            # rng) donates every buffer in the pytree
                            donated_names.update(
                                e.id for e in arg.elts
                                if isinstance(e, ast.Name)
                            )
                    if not donated_names:
                        continue
                    # names STORED anywhere inside the same statement
                    # subtree are rebound by the call's own result (the
                    # `state, m = step(state, ...)` idiom — including
                    # inside a for-loop statement) and are safe to read
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Name) and isinstance(
                            sub.ctx, ast.Store
                        ):
                            donated_names.discard(sub.id)
                    for f in self._reads_after(
                        ctx, stmts[i + 1:], donated_names, node.func.id
                    ):
                        site = (f.line, f.col, f.message)
                        if site not in seen_sites:
                            # the nested-block rescans below revisit the
                            # same call with a shorter tail — dedupe
                            seen_sites.add(site)
                            out.append(f)
                # recurse into nested blocks for the donating-call scan
                for attr in ("body", "orelse", "finalbody"):
                    inner = getattr(stmt, attr, None)
                    if inner:
                        scan_block(inner)

        scan_block(fn.body)
        return out

    def _reads_after(
        self, ctx, later_stmts, names: Set[str], fname: str
    ) -> List[Finding]:
        out = []
        live = set(names)
        for stmt in later_stmts:
            if not live:
                break
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name) and node.id in live:
                    if isinstance(node.ctx, ast.Load):
                        out.append(
                            self.finding(
                                ctx, node,
                                "%r was donated to %s() above and may "
                                "already be overwritten — reading it "
                                "here is undefined; keep a copy or "
                                "don't donate" % (node.id, fname),
                            )
                        )
                        live.discard(node.id)
                    else:
                        live.discard(node.id)  # rebound: safe again
        return out


# ---------------------------------------------------------------------
# 6. unlocked-shared-mutation
# ---------------------------------------------------------------------

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}
_EVENT_CTORS = {"threading.Event", "Event"}
_CONTAINER_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "clear", "add", "discard", "update", "setdefault",
})


class UnlockedSharedMutation(Rule):
    name = "unlocked-shared-mutation"
    summary = (
        "attribute of a thread-shared class mutated outside its lock — "
        "shared = mutated by the background thread, guarded elsewhere, "
        "or a Thread handle; `_locked`-suffixed methods assert the "
        "caller holds the lock"
    )

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        out = []
        for node in ctx.nodes():
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(ctx, node))
        return out

    def _check_class(self, ctx: ModuleCtx, cls: ast.ClassDef):
        methods = {
            n.name: n for n in cls.body if isinstance(n, FuncNode)
        }
        lock_attrs: Set[str] = set()
        event_attrs: Set[str] = set()
        thread_attrs: Set[str] = set()
        spawns_thread = False
        thread_entries: List[ast.AST] = []  # defs run by the thread

        local_defs: Dict[Tuple[str, str], ast.AST] = {}
        for mname, m in methods.items():
            for node in ast.walk(m):
                if isinstance(node, FuncNode) and node is not m:
                    local_defs[(mname, node.name)] = node

        for mname, m in methods.items():
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    q = qualname(node.value.func)
                    attrs = [
                        qualname(t)
                        for t in node.targets
                        if qualname(t) and qualname(t).startswith("self.")
                    ]
                    names = [a.split(".", 1)[1] for a in attrs]
                    if q in _LOCK_CTORS:
                        lock_attrs.update(names)
                    elif q in _EVENT_CTORS:
                        event_attrs.update(names)
                    elif q in ("threading.Thread", "Thread"):
                        thread_attrs.update(names)
                if isinstance(node, ast.Call) and qualname(node.func) in (
                    "threading.Thread", "Thread",
                ):
                    spawns_thread = True
                    for kw in node.keywords:
                        if kw.arg != "target":
                            continue
                        tq = qualname(kw.value)
                        if tq and tq.startswith("self."):
                            entry = methods.get(tq.split(".", 1)[1])
                            if entry is not None:
                                thread_entries.append(entry)
                        elif isinstance(kw.value, ast.Name):
                            d = local_defs.get((mname, kw.value.id))
                            if d is not None:
                                thread_entries.append(d)
        if not spawns_thread and not lock_attrs:
            return []

        # close thread-reachable set over self.method() calls
        reachable = list(thread_entries)
        seen = set(id(n) for n in reachable)
        i = 0
        while i < len(reachable):
            node = reachable[i]
            i += 1
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    q = qualname(sub.func)
                    if q and q.startswith("self."):
                        m = methods.get(q.split(".", 1)[1])
                        if m is not None and id(m) not in seen:
                            seen.add(id(m))
                            reachable.append(m)

        def mutations(node, under_lock: bool, out_list):
            """Collect (attr, node) mutations of self attrs in ``node``,
            honoring `with self.<lock>:` scoping."""
            if isinstance(node, ast.With):
                locked = under_lock or any(
                    (q := qualname(item.context_expr)) is not None
                    and q.startswith("self.")
                    and q.split(".", 1)[1] in lock_attrs
                    or (
                        isinstance(item.context_expr, ast.Call)
                        and (cq := qualname(item.context_expr.func))
                        is not None
                        and cq.startswith("self.")
                        and cq.split(".", 2)[1] in lock_attrs
                    )
                    for item in node.items
                )
                for child in node.body:
                    mutations(child, locked, out_list)
                for item in node.items:
                    mutations(item.context_expr, under_lock, out_list)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in tgts:
                    base = tgt
                    if isinstance(base, (ast.Tuple, ast.List)):
                        for e in base.elts:
                            q = qualname(e)
                            if q and q.startswith("self."):
                                out_list.append(
                                    (q.split(".", 1)[1], e, under_lock)
                                )
                        continue
                    if isinstance(base, ast.Subscript):
                        base = base.value
                    q = qualname(base)
                    if q and q.startswith("self."):
                        out_list.append(
                            (q.split(".", 1)[1], tgt, under_lock)
                        )
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                if node.func.attr in _CONTAINER_MUTATORS:
                    q = qualname(node.func.value)
                    if q and q.startswith("self."):
                        out_list.append(
                            (q.split(".", 1)[1], node, under_lock)
                        )
            for child in ast.iter_child_nodes(node):
                mutations(child, under_lock, out_list)

        # shared set: mutated by thread-reachable code, accessed under a
        # lock anywhere, or a Thread handle
        shared: Set[str] = set(thread_attrs)
        for entry in reachable:
            muts: List = []
            mutations(entry, False, muts)
            shared.update(a for a, _, _ in muts)
        for mname, m in methods.items():
            muts = []
            mutations(m, False, muts)
            shared.update(a for a, _, locked in muts if locked)
        shared -= lock_attrs
        shared -= event_attrs
        if not shared:
            return []

        findings = []
        for mname, m in methods.items():
            if mname == "__init__" or mname.endswith("_locked"):
                # __init__ runs before the object is published;
                # *_locked methods document "caller holds the lock"
                continue
            muts = []
            mutations(m, False, muts)
            flagged_nodes = set()
            for attr, node, locked in muts:
                if locked or attr not in shared:
                    continue
                if id(node) in flagged_nodes:
                    continue
                flagged_nodes.add(id(node))
                findings.append(
                    self.finding(
                        ctx, node,
                        "%s.%s mutates thread-shared attribute %r "
                        "outside a lock — wrap it in `with self.<lock>` "
                        "(or move it to a *_locked method whose callers "
                        "hold the lock)" % (cls.name, mname, attr),
                    )
                )
        return findings


# ---------------------------------------------------------------------
# 7. compat-bypass
# ---------------------------------------------------------------------

# module suffix -> the APIs it is the sanctioned shim for
_SHIM_MODULES = {
    "parallel/dp.py": {"shard_map"},
    "parallel/mesh.py": {"is_initialized"},
    "pytorch_cifar_tpu/__init__.py": {"xla_flags"},
    "tests/conftest.py": {"xla_flags"},  # the probe-gated bootstrap
}


class CompatBypass(Rule):
    name = "compat-bypass"
    summary = (
        "direct use of a version-gated API (jax.shard_map, "
        "jax.distributed.is_initialized, raw XLA_FLAGS writes) instead "
        "of the probing shims — on the wrong jaxlib these abort the "
        "process or AttributeError every entry point"
    )

    def _allowed(self, ctx: ModuleCtx, what: str) -> bool:
        path = ctx.relpath.replace("\\", "/")
        for suffix, grants in _SHIM_MODULES.items():
            if path.endswith(suffix) and what in grants:
                return True
        return False

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        out = []
        for node in ctx.nodes():
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                names = {a.name for a in node.names}
                if (
                    mod in ("jax.experimental.shard_map",)
                    or (mod == "jax" and "shard_map" in names)
                ) and not self._allowed(ctx, "shard_map"):
                    out.append(
                        self.finding(
                            ctx, node,
                            "import shard_map from parallel/dp.py (the "
                            "check_vma/check_rep version shim), never "
                            "from jax directly",
                        )
                    )
            if isinstance(node, ast.Attribute):
                q = qualname(node)
                if q == "jax.shard_map" and not self._allowed(
                    ctx, "shard_map"
                ):
                    out.append(
                        self.finding(
                            ctx, node,
                            "jax.shard_map does not exist on jax < 0.5 — "
                            "use parallel.dp.shard_map (the version shim)",
                        )
                    )
                if q == "jax.distributed.is_initialized" and not (
                    self._allowed(ctx, "is_initialized")
                ):
                    out.append(
                        self.finding(
                            ctx, node,
                            "jax.distributed.is_initialized landed after "
                            "jaxlib 0.4.x — use parallel.mesh."
                            "_distributed_is_initialized (the probing "
                            "shim)",
                        )
                    )
            # os.environ["XLA_FLAGS"] = ... (store / setdefault)
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if self._is_environ_xla_flags(tgt) and not (
                        self._allowed(ctx, "xla_flags")
                    ):
                        out.append(
                            self.finding(
                                ctx, tgt,
                                "raw os.environ['XLA_FLAGS'] write: an "
                                "UNKNOWN flag hard-aborts every process "
                                "(parse_flags_from_env.cc) — gate new "
                                "flags behind pytorch_cifar_tpu."
                                "_xla_supports_flag / use "
                                "xla_collective_timeout_flags()",
                            )
                        )
            if isinstance(node, ast.Call):
                q = qualname(node.func)
                if (
                    q == "os.environ.setdefault"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value == "XLA_FLAGS"
                    and not self._allowed(ctx, "xla_flags")
                ):
                    out.append(
                        self.finding(
                            ctx, node,
                            "raw os.environ XLA_FLAGS mutation — probe "
                            "flag support first (compat shims in "
                            "pytorch_cifar_tpu/__init__.py)",
                        )
                    )
        return out

    @staticmethod
    def _is_environ_xla_flags(tgt: ast.AST) -> bool:
        return (
            isinstance(tgt, ast.Subscript)
            and qualname(tgt.value) == "os.environ"
            and isinstance(tgt.slice, ast.Constant)
            and tgt.slice.value == "XLA_FLAGS"
        )


# ---------------------------------------------------------------------
# 8. flag-config-drift
# ---------------------------------------------------------------------

_CFG_BUILDERS = {
    "parse_config": "TrainConfig",
    "parse_serve_config": "ServeConfig",
    "TrainConfig": "TrainConfig",
    "ServeConfig": "ServeConfig",
}
# dataclass machinery + stdlib attrs that are always legal
_CFG_ALWAYS_OK = frozenset({"__class__", "__dict__", "__dataclass_fields__"})


class FlagConfigDrift(Rule):
    name = "flag-config-drift"
    summary = (
        "TrainConfig/ServeConfig attribute access (or constructor kwarg) "
        "that matches no declared field — config/CLI drift: argparse "
        "flags are GENERATED from the dataclass fields, so a phantom "
        "attribute silently has no flag (and vice versa)"
    )

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        fields = ctx.project.config_fields()
        if not fields:
            fields = parse_own_config(ctx)
        if not fields:
            return []
        out = []
        out.extend(self._check_structural(ctx))
        tracked = self._tracked_exprs(ctx)
        if not tracked:
            return out
        union_ok = set().union(*fields.values()) | _CFG_ALWAYS_OK
        for node in ctx.nodes():
            # constructor kwargs: TrainConfig(bogus=1)
            if isinstance(node, ast.Call):
                q = qualname(node.func)
                cls = _CFG_BUILDERS.get((q or "").rsplit(".", 1)[-1])
                if cls in ("TrainConfig", "ServeConfig") and (
                    q or ""
                ).rsplit(".", 1)[-1] in ("TrainConfig", "ServeConfig"):
                    ok = fields.get(cls, union_ok)
                    for kw in node.keywords:
                        if kw.arg is not None and kw.arg not in ok:
                            out.append(
                                self.finding(
                                    ctx, node,
                                    "%s(%s=...) matches no declared "
                                    "field — config/flag drift"
                                    % (cls, kw.arg),
                                )
                            )
            if not isinstance(node, ast.Attribute):
                continue
            base_q = qualname(node.value)
            if base_q is None:
                continue
            cls = tracked.get(base_q)
            if cls is None:
                continue
            ok = fields.get(cls) or union_ok
            ok = ok | _CFG_ALWAYS_OK
            if node.attr not in ok and not node.attr.startswith("__"):
                out.append(
                    self.finding(
                        ctx, node,
                        "%s has no field %r (checked against the "
                        "dataclass in config.py, which GENERATES the "
                        "CLI flags) — config/flag drift"
                        % (cls, node.attr),
                    )
                )
        return out

    def _tracked_exprs(self, ctx: ModuleCtx) -> Dict[str, str]:
        """Expression qualname -> config class, for names/attrs known to
        hold a TrainConfig/ServeConfig: ``cfg = parse_config()``,
        annotated params ``config: TrainConfig``, ``self.config = cfg``,
        and simple aliases of any of those."""
        tracked: Dict[str, str] = {}
        for node in ctx.nodes():
            if isinstance(node, FuncNode):
                for a in node.args.args + node.args.kwonlyargs:
                    ann = a.annotation
                    q = qualname(ann) if ann is not None else None
                    if q and q.rsplit(".", 1)[-1] in (
                        "TrainConfig", "ServeConfig",
                    ):
                        tracked[a.arg] = q.rsplit(".", 1)[-1]
        changed = True
        while changed:
            changed = False
            for node in ctx.nodes():
                if not isinstance(node, ast.Assign):
                    continue
                cls = None
                if isinstance(node.value, ast.Call):
                    q = qualname(node.value.func)
                    cls = _CFG_BUILDERS.get((q or "").rsplit(".", 1)[-1])
                else:
                    vq = qualname(node.value)
                    if vq is not None:
                        cls = tracked.get(vq)
                if cls is None:
                    continue
                for tgt in node.targets:
                    tq = qualname(tgt)
                    if tq is not None and tracked.get(tq) != cls:
                        tracked[tq] = cls
                        changed = True
        return tracked

    def _check_structural(self, ctx: ModuleCtx) -> List[Finding]:
        """Inside config.py itself: parse_config/parse_serve_config must
        still route through _add_args (the field->flag generator — a
        hand-rolled parser is how drift starts), and field-name string
        literals special-cased in _add_args must exist as fields."""
        path = ctx.relpath.replace("\\", "/")
        if not path.endswith("config.py"):
            return []
        fields = parse_config_fields_from_tree(ctx.tree)
        if not fields:
            return []
        union = set().union(*fields.values())
        out = []
        for node in ctx.nodes():
            if isinstance(node, FuncNode) and node.name in (
                "parse_config", "parse_serve_config",
            ):
                calls = {
                    qualname(c.func)
                    for c in ast.walk(node)
                    if isinstance(c, ast.Call)
                }
                if "_add_args" not in calls:
                    out.append(
                        self.finding(
                            ctx, node,
                            "%s() no longer routes through _add_args — "
                            "flags must stay GENERATED from the "
                            "dataclass fields or they drift" % node.name,
                        )
                    )
            if isinstance(node, FuncNode) and node.name == "_add_args":
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Compare):
                        continue
                    # only field-NAME comparisons (`f.name == ...` /
                    # `f.name in (...)`); `f.type == "bool"` etc. compare
                    # other metadata and must not be cross-checked
                    lq = qualname(sub.left)
                    if not (lq and lq.endswith(".name")):
                        continue
                    names = [
                        c.value
                        for c in ast.walk(sub)
                        if isinstance(c, ast.Constant)
                        and isinstance(c.value, str)
                    ]
                    for nm in names:
                        if nm.isidentifier() and nm not in union:
                            out.append(
                                self.finding(
                                    ctx, sub,
                                    "_add_args special-cases field %r "
                                    "which no config class declares — "
                                    "stale after a rename?" % nm,
                                )
                            )
        return out


def parse_config_fields_from_tree(tree: ast.Module) -> Dict[str, set]:
    out: Dict[str, set] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name in (
            "TrainConfig", "ServeConfig",
        ):
            names = set()
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    names.add(stmt.target.id)
                elif isinstance(stmt, ast.FunctionDef):
                    names.add(stmt.name)
            out[node.name] = names
    return out


def parse_own_config(ctx: ModuleCtx) -> Dict[str, set]:
    """Fixture fallback: a standalone file defining the config classes."""
    return parse_config_fields_from_tree(ctx.tree)


# ---------------------------------------------------------------------
# 9. thread-collective
# ---------------------------------------------------------------------


class ThreadCollective(Rule):
    name = "thread-collective"
    summary = (
        "a host collective (broadcast_pytree / process_allgather / "
        "barrier ...) is reachable from a Thread(target=...) entry — a "
        "background thread makes per-process timing decisions, so its "
        "collective can strand every peer at the barrier (the async "
        "checkpoint writer's multihost supersede bug shape). A module "
        "may declare GRAFTCHECK_SANCTIONED_COLLECTIVE_ENTRIES "
        "{'Cls.method': 'reason'} for a single-initiator lock-step "
        "protocol loop (the mesh replica dispatch shape) — the declared "
        "entry's closure is exempt, anything reachable from any OTHER "
        "thread entry still fires, and a reasonless or stale "
        "declaration is itself a finding"
    )

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        reach = ctx.project.thread_reachable(ctx.path)
        out = []
        # malformed/stale sanction declarations (unknown def, missing
        # reason): same mandatory-reason policy as inline noqa
        for node, message in ctx.project.sanction_issues(ctx.path):
            out.append(self.finding(ctx, node, message))
        for fn, entry in reach.items():
            if not isinstance(fn, FuncNode):
                continue
            for node in walk_no_nested_funcs(fn):
                if not isinstance(node, ast.Call):
                    continue
                q = qualname(node.func)
                if q and q.rsplit(".", 1)[-1] in HOST_COLLECTIVES:
                    out.append(
                        self.finding(
                            ctx, node,
                            "%s() is reachable from thread entry %s — a "
                            "collective on a background thread decides "
                            "its own timing per process, so peers can "
                            "be left waiting at the barrier forever; "
                            "run collectives on the main thread (the "
                            "sharded checkpoint publish uses a "
                            "FILESYSTEM barrier for exactly this "
                            "reason)" % (q, entry),
                        )
                    )
        return out


# ---------------------------------------------------------------------
# 10. atomic-publish
# ---------------------------------------------------------------------

_RENAME_FNS = ("os.replace", "os.rename")


class AtomicPublish(Rule):
    name = "atomic-publish"
    summary = (
        "a file that is later the SOURCE of an os.replace/os.rename was "
        "written without an fsync (tmp+rename without the fsync is "
        "atomic for readers but NOT durable: the journal can commit the "
        "rename before the data blocks, leaving a complete-looking "
        "empty file after a crash), or a commit-marker sidecar is "
        "written before its payload — route publishes through the "
        "sanctioned tmp+fsync+rename helpers (checkpoint._atomic_write)"
    )

    @staticmethod
    def _write_key(expr: ast.AST) -> str:
        if isinstance(expr, ast.Name):
            return expr.id
        return ast.dump(expr)

    @classmethod
    def _written_paths(cls, fn) -> Dict[str, ast.AST]:
        """Path-expression keys this function writes inline: open(p,'w'),
        p.write_bytes()/write_text(), shutil.copyfile(src, p)."""
        out: Dict[str, ast.AST] = {}
        for node in walk_no_nested_funcs(fn):
            if not isinstance(node, ast.Call):
                continue
            q = qualname(node.func)
            if q == "open" and len(node.args) >= 2:
                mode = node.args[1]
                if isinstance(mode, ast.Constant) and isinstance(
                    mode.value, str
                ) and ("w" in mode.value or "a" in mode.value):
                    out[cls._write_key(node.args[0])] = node
            elif q and q.rsplit(".", 1)[-1] in (
                "write_bytes", "write_text"
            ) and isinstance(node.func, ast.Attribute):
                out[cls._write_key(node.func.value)] = node
            elif q in ("shutil.copyfile", "shutil.copy") and (
                len(node.args) >= 2
            ):
                out[cls._write_key(node.args[1])] = node
        return out

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        out = []
        for fn in ctx.nodes():
            if isinstance(fn, FuncNode):
                out.extend(self._check_rename(ctx, fn))
                out.extend(self._check_marker_order(ctx, fn))
        return out

    def _check_rename(self, ctx: ModuleCtx, fn) -> List[Finding]:
        """Statement-position-aware write→fsync→rename ordering: each
        rename of an in-function-written source needs an fsync that runs
        AFTER the write and BEFORE the rename. Mere fsync presence is
        not enough — `write; rename; fsync` journals the rename first
        and publishes a complete-looking torn file after a crash (the
        flow-insensitivity known-limit PR 8 documented, closed here)."""
        written = self._written_paths(fn)
        if not written:
            return []
        fsync_lines = sorted(
            n.lineno
            for n in walk_no_nested_funcs(fn)
            if isinstance(n, ast.Call)
            and (qualname(n.func) or "").rsplit(".", 1)[-1] == "fsync"
        )
        out = []
        for node in walk_no_nested_funcs(fn):
            if not isinstance(node, ast.Call):
                continue
            if qualname(node.func) not in _RENAME_FNS or not node.args:
                continue
            src = self._write_key(node.args[0])
            wnode = written.get(src)
            if wnode is None:
                continue
            ordered = any(
                wnode.lineno <= fl <= node.lineno for fl in fsync_lines
            )
            if not ordered:
                why = (
                    "was written with no fsync"
                    if not fsync_lines
                    else "has no fsync BETWEEN the write (line %d) and "
                    "this rename — an fsync after the rename is too "
                    "late, the rename is already journaled"
                    % wnode.lineno
                )
                out.append(
                    self.finding(
                        ctx, node,
                        "%r is renamed into place but %s — the rename "
                        "can hit the journal before the data blocks do, "
                        "publishing a complete-looking empty/torn file "
                        "after a crash; use the tmp+fsync+rename shape "
                        "(train/checkpoint._atomic_write)" % (src, why),
                    )
                )
        return out

    def _check_marker_order(self, ctx: ModuleCtx, fn) -> List[Finding]:
        """Within one publish function, a commit-marker write —
        ``<helper>(meta_path(D, N), ...)`` — must come AFTER the payload
        write for the same (D, N) (``os.path.join(D, N)``): a reader
        trusts whatever the marker describes, so a marker published
        first describes bytes that are not on disk yet."""
        # resolve simple local names to their assigned expression once
        assigned: Dict[str, ast.AST] = {}
        for node in walk_no_nested_funcs(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    assigned[tgt.id] = node.value

        def path_expr(e: ast.AST) -> ast.AST:
            if isinstance(e, ast.Name) and e.id in assigned:
                return assigned[e.id]
            return e

        markers: List[Tuple[int, str, ast.AST]] = []
        payloads: List[Tuple[int, str]] = []
        for node in walk_no_nested_funcs(fn):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            q = qualname(node.func) or ""
            if q.rsplit(".", 1)[-1] not in (
                "_atomic_write", "atomic_write",
            ):
                continue
            p = path_expr(node.args[0])
            if not (isinstance(p, ast.Call) and len(p.args) >= 2):
                continue
            pq = qualname(p.func) or ""
            key = "%s|%s" % (
                ast.dump(p.args[0]), ast.dump(p.args[1])
            )
            if pq.rsplit(".", 1)[-1] == "meta_path":
                markers.append((node.lineno, key, node))
            elif pq in ("os.path.join", "path.join"):
                payloads.append((node.lineno, key))
        out = []
        for mline, mkey, mnode in markers:
            later_payload = [
                pl for pl, pkey in payloads if pkey == mkey and pl > mline
            ]
            if later_payload:
                out.append(
                    self.finding(
                        ctx, mnode,
                        "commit marker (meta_path sidecar) is written "
                        "BEFORE its payload — a reader that trusts the "
                        "marker can see a commit describing bytes not "
                        "yet on disk; the marker must be the LAST "
                        "publish step (format v3's torn-publish "
                        "invisibility depends on it)",
                    )
                )
        return out


# ---------------------------------------------------------------------
# 11. thread-join
# ---------------------------------------------------------------------


class ThreadJoin(Rule):
    name = "thread-join"
    summary = (
        "a started Thread with no join() on any exit path — a leaked "
        "worker outlives its owner (shutdown hangs, interleaved "
        "teardown writes); every PR 6-7 thread owner had to pin "
        "no-thread-leak by hand, this rule makes it a checked invariant"
    )

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        out = []
        for node in ctx.nodes():
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(ctx, node))
        for fn in ctx.nodes():
            if isinstance(fn, FuncNode):
                out.extend(self._check_local(ctx, fn))
        return out

    @staticmethod
    def _is_thread_ctor(call: ast.AST) -> bool:
        return isinstance(call, ast.Call) and qualname(call.func) in (
            "threading.Thread", "Thread",
        )

    def _check_class(self, ctx: ModuleCtx, cls: ast.ClassDef):
        """Thread handles stored on self must be joined by SOME method
        (directly or via a ``t = self._thread; t.join()`` alias)."""
        thread_attrs: Dict[str, ast.AST] = {}  # attr -> ctor node
        joined: Set[str] = set()
        started: Set[str] = set()
        for m in (n for n in cls.body if isinstance(n, FuncNode)):
            local_threads: Set[str] = set()
            attr_alias: Dict[str, str] = {}  # local name -> self attr
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and self._is_thread_ctor(
                    node.value
                ):
                    for tgt in node.targets:
                        tq = qualname(tgt)
                        if tq and tq.startswith("self."):
                            thread_attrs.setdefault(
                                tq.split(".", 1)[1], node.value
                            )
                        elif isinstance(tgt, ast.Name):
                            local_threads.add(tgt.id)
                elif isinstance(node, ast.Assign):
                    vq = qualname(node.value)
                    for tgt in node.targets:
                        tq2 = qualname(tgt)
                        if isinstance(tgt, ast.Name):
                            if vq and vq.startswith("self."):
                                attr_alias[tgt.id] = vq.split(".", 1)[1]
                            elif isinstance(
                                node.value, ast.Name
                            ) and node.value.id in local_threads:
                                local_threads.add(tgt.id)
                        elif tq2 and tq2.startswith("self.") and (
                            isinstance(node.value, ast.Name)
                            and node.value.id in local_threads
                        ):
                            # t = Thread(...); ...; self._thread = t
                            thread_attrs.setdefault(
                                tq2.split(".", 1)[1], node.value
                            )
                            started.add(tq2.split(".", 1)[1])
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("join", "start")
                ):
                    rq = qualname(node.func.value)
                    tgt_set = joined if node.func.attr == "join" else started
                    if rq and rq.startswith("self."):
                        tgt_set.add(rq.split(".", 1)[1])
                    elif isinstance(node.func.value, ast.Name):
                        a = attr_alias.get(node.func.value.id)
                        if a is not None:
                            tgt_set.add(a)
        out = []
        for attr, ctor in thread_attrs.items():
            if attr in joined or attr not in started:
                continue
            out.append(
                self.finding(
                    ctx, ctor,
                    "%s stores a Thread on self.%s but no method ever "
                    "joins it — a leaked worker outlives close()/stop(); "
                    "join the handle on every exit path (timeout is "
                    "fine)" % (cls.name, attr),
                )
            )
        return out

    def _check_local(self, ctx: ModuleCtx, fn) -> List[Finding]:
        """Function-local threads (not stored on self / a container /
        returned) must be joined in the same function."""
        local: Dict[str, ast.AST] = {}
        escaped: Set[str] = set()
        joined: Set[str] = set()
        started: Set[str] = set()
        started_inline: List[ast.AST] = []
        for node in walk_no_nested_funcs(fn):
            if isinstance(node, ast.Assign) and self._is_thread_ctor(
                node.value
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        local[tgt.id] = node.value
                    # self.X targets are the class check's business
            elif isinstance(node, ast.Call):
                # Thread(...).start() with no handle at all
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "start"
                    and self._is_thread_ctor(node.func.value)
                ):
                    started_inline.append(node)
                if isinstance(node.func, ast.Attribute) and isinstance(
                    node.func.value, ast.Name
                ):
                    if node.func.attr == "join":
                        joined.add(node.func.value.id)
                    elif node.func.attr == "start":
                        started.add(node.func.value.id)
                # passed elsewhere (registered with an owner): escapes
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if isinstance(arg, ast.Name):
                        escaped.add(arg.id)
            elif isinstance(node, ast.Return) and isinstance(
                node.value, ast.Name
            ):
                escaped.add(node.value.id)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Name
            ):
                for tgt in node.targets:
                    tq = qualname(tgt)
                    if tq and "." in tq:  # self.X / obj.attr = t
                        escaped.add(node.value.id)
        out = []
        for name, ctor in local.items():
            if name not in started or name in joined or name in escaped:
                continue
            out.append(
                self.finding(
                    ctx, ctor,
                    "local Thread %r in %r is started but never joined "
                    "in this function and never handed to an owner — "
                    "it leaks past every exit path" % (name, fn.name),
                )
            )
        for node in started_inline:
            out.append(
                self.finding(
                    ctx, node,
                    "Thread(...).start() without keeping the handle in "
                    "%r — nothing can ever join it (thread leak by "
                    "construction)" % fn.name,
                )
            )
        return out


# ---------------------------------------------------------------------
# 12. subprocess-lifecycle
# ---------------------------------------------------------------------


class SubprocessLifecycle(Rule):
    name = "subprocess-lifecycle"
    summary = (
        "a subprocess.Popen whose handle is never waited/terminated and "
        "never handed to an owner — an orphan child outlives its parent "
        "(the zombie-replica shape the elastic fleet controller's "
        "decommission path must never produce: a drained process must "
        "ALWAYS be reaped, a spawned one always owned); wait()/"
        "communicate() reap, kill()/terminate() end, escape to an owner "
        "transfers the obligation"
    )

    # calls that discharge the obligation on a handle: reaping (wait/
    # communicate) or termination (kill/terminate — their call sites in
    # this repo are always followed by a wait, and requiring the pair
    # flow-insensitively would just push people to one-liners)
    _HANDLED = frozenset({"wait", "communicate", "kill", "terminate"})

    @staticmethod
    def _is_popen_ctor(call: ast.AST) -> bool:
        if not isinstance(call, ast.Call):
            return False
        q = qualname(call.func)
        return q is not None and (q == "Popen" or q.endswith(".Popen"))

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        out = []
        for node in ctx.nodes():
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(ctx, node))
        for fn in ctx.nodes():
            if isinstance(fn, FuncNode):
                out.extend(self._check_local(ctx, fn))
        # fire-and-forget at module level or anywhere: a Popen whose
        # handle is dropped on the floor can never be reaped
        for node in ctx.nodes():
            if isinstance(node, ast.Expr) and self._is_popen_ctor(
                node.value
            ):
                out.append(
                    self.finding(
                        ctx, node.value,
                        "Popen(...) without keeping the handle — nothing "
                        "can ever wait or terminate this child (orphan "
                        "by construction)",
                    )
                )
        return out

    def _check_class(self, ctx: ModuleCtx, cls: ast.ClassDef):
        """Popen handles stored on self must be waited/terminated by
        SOME method (directly or via a ``p = self.proc; p.wait()``
        alias) — the owner that holds the child must also be able to
        end and reap it."""
        proc_attrs: Dict[str, ast.AST] = {}  # attr -> ctor node
        handled: Set[str] = set()
        for m in (n for n in cls.body if isinstance(n, FuncNode)):
            local_procs: Set[str] = set()
            attr_alias: Dict[str, str] = {}  # local name -> self attr
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and self._is_popen_ctor(
                    node.value
                ):
                    for tgt in node.targets:
                        tq = qualname(tgt)
                        if tq and tq.startswith("self."):
                            proc_attrs.setdefault(
                                tq.split(".", 1)[1], node.value
                            )
                        elif isinstance(tgt, ast.Name):
                            local_procs.add(tgt.id)
                elif isinstance(node, ast.Assign):
                    vq = qualname(node.value)
                    for tgt in node.targets:
                        tq2 = qualname(tgt)
                        if isinstance(tgt, ast.Name):
                            if vq and vq.startswith("self."):
                                attr_alias[tgt.id] = vq.split(".", 1)[1]
                        elif tq2 and tq2.startswith("self.") and (
                            isinstance(node.value, ast.Name)
                            and node.value.id in local_procs
                        ):
                            # p = Popen(...); ...; self.proc = p
                            proc_attrs.setdefault(
                                tq2.split(".", 1)[1], node.value
                            )
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._HANDLED
                ):
                    rq = qualname(node.func.value)
                    if rq and rq.startswith("self."):
                        handled.add(rq.split(".", 1)[1])
                    elif isinstance(node.func.value, ast.Name):
                        a = attr_alias.get(node.func.value.id)
                        if a is not None:
                            handled.add(a)
        out = []
        for attr, ctor in proc_attrs.items():
            if attr in handled:
                continue
            out.append(
                self.finding(
                    ctx, ctor,
                    "%s stores a Popen on self.%s but no method ever "
                    "waits or terminates it — the child outlives (or "
                    "zombifies under) its owner; reap the handle on "
                    "every exit path (wait/communicate, kill as the "
                    "backstop)" % (cls.name, attr),
                )
            )
        return out

    def _check_local(self, ctx: ModuleCtx, fn) -> List[Finding]:
        """Function-local Popen handles (not stored on self / a
        container, not returned, not passed to an owner) must be waited
        or terminated in the same function."""
        local: Dict[str, ast.AST] = {}
        escaped: Set[str] = set()
        handled: Set[str] = set()
        for node in walk_no_nested_funcs(fn):
            if isinstance(node, ast.Assign) and self._is_popen_ctor(
                node.value
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        local[tgt.id] = node.value
                    # self.X / container targets are ownership transfers
            elif isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) and isinstance(
                    node.func.value, ast.Name
                ):
                    if node.func.attr in self._HANDLED:
                        handled.add(node.func.value.id)
                # passed elsewhere (an owner takes it): escapes
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if isinstance(arg, ast.Name):
                        escaped.add(arg.id)
            elif isinstance(node, ast.Return) and isinstance(
                node.value, ast.Name
            ):
                escaped.add(node.value.id)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Name
            ):
                for tgt in node.targets:
                    tq = qualname(tgt)
                    if (tq and "." in tq) or isinstance(
                        tgt, ast.Subscript
                    ):
                        # self.X = p / obj.attr = p / procs[i] = p
                        escaped.add(node.value.id)
        out = []
        for name, ctor in local.items():
            if name in handled or name in escaped:
                continue
            out.append(
                self.finding(
                    ctx, ctor,
                    "local Popen %r in %r is never waited or terminated "
                    "in this function and never handed to an owner — "
                    "the child leaks past every exit path (the orphan-"
                    "replica shape)" % (name, fn.name),
                )
            )
        return out


# ---------------------------------------------------------------------
# 13-16. concurrency-protocol rules (lint/locks.py: the lock-effect
# analysis + whole-project held-set propagation they all ride on)
# ---------------------------------------------------------------------


class _LockRule(Rule):
    """Shared shape: ask the memoized lock analysis for this module's
    findings — the expensive pass runs once per lint run, not per rule
    per file."""

    provider = ""  # LockAnalysis method name

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        analysis = ctx.project.lock_analysis()
        return [
            Finding(self.name, ctx.relpath, line, col, msg)
            for line, col, msg in getattr(analysis, self.provider)(ctx.path)
        ]


class LockOrderInversion(_LockRule):
    name = "lock-order-inversion"
    provider = "cycle_findings_for"
    summary = (
        "a cycle in the whole-project lock-order graph: two call paths "
        "acquire the same locks in opposite order (nested `with`, or an "
        "acquisition hiding behind cross-module calls) — one bad "
        "interleaving deadlocks both threads; reported once, at the "
        "cycle's smallest acquisition site"
    )


class BlockingUnderLock(_LockRule):
    name = "blocking-under-lock"
    provider = "blocking_findings_for"
    summary = (
        "an unbounded blocking call — join()/queue.get() without a "
        "timeout, socket/HTTP I/O, subprocess, jax.device_get/"
        "block_until_ready — while a lock is held (locally, or via the "
        "held-set callers propagate through the call graph): the stall "
        "freezes every thread contending for that lock"
    )


class CondWaitDiscipline(_LockRule):
    name = "cond-wait-discipline"
    provider = "cond_findings_for"
    summary = (
        "Condition.wait() outside a while-predicate loop (spurious "
        "wakeups and missed notifies are legal — re-check or use "
        "wait_for), or wait()/notify()/notify_all() without the "
        "condition held (RuntimeError at runtime; an unheld notify is "
        "a lost wakeup)"
    )


class LockLeak(_LockRule):
    name = "lock-leak"
    provider = "leak_findings_for"
    summary = (
        "acquire()/release() imbalance on some path: a lock acquired "
        "but never released, or an early return/raise that skips the "
        "release with no covering try/finally — every later acquirer "
        "deadlocks; prefer `with`, or release in a finally"
    )


# ---------------------------------------------------------------------
# 17. metric-name-drift
# ---------------------------------------------------------------------

_METRIC_FACTORIES = frozenset({"counter", "gauge", "histogram"})
_METRIC_NAME_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")


def parse_metric_doc_names(md_text: str) -> Set[str]:
    """Metric names documented in OBSERVABILITY.md's tables: the
    backticked tokens of each table row's FIRST cell. A token starting
    with '.' continues the previous full name's prefix (the
    ``serve.reload.reloads`` / ``.skipped`` doc idiom); tokens that are
    not dotted lowercase identifiers (paths, ``<code>`` templates,
    flags) are ignored."""
    names: Set[str] = set()
    for line in md_text.splitlines():
        if not line.lstrip().startswith("|"):
            continue
        cells = line.split("|")
        if len(cells) < 2:
            continue
        first = cells[1]
        prev: Optional[str] = None
        for tok in re.findall(r"`([^`]+)`", first):
            tok = tok.strip()
            if tok.startswith(".") and prev is not None:
                tok = prev.rsplit(".", 1)[0] + tok
            if _METRIC_NAME_RE.match(tok):
                names.add(tok)
                prev = tok
    return names


def metric_literals(tree: ast.AST) -> List[Tuple[str, ast.AST]]:
    """Every ``<registry>.counter/gauge/histogram("literal")`` call in
    ``tree``. Dynamic names (f-strings like ``serve.http_{code}``) are
    skipped — only literals can be doc-checked."""
    out = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _METRIC_FACTORIES
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            out.append((node.args[0].value, node))
    return out


def metric_dynamic_prefixes(tree: ast.AST) -> List[str]:
    """Literal PREFIXES of dynamically named metrics — the
    ``counter(f"serve.reload.{event}")`` idiom. The `--docs` doc→code
    check treats a documented name covered by such a prefix as created
    (it cannot verify the suffix statically; that stays a known
    limit)."""
    out = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _METRIC_FACTORIES
            and node.args
            and isinstance(node.args[0], ast.JoinedStr)
            and node.args[0].values
        ):
            first = node.args[0].values[0]
            if (
                isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and first.value
            ):
                out.append(first.value)
    return out


class MetricNameDrift(Rule):
    name = "metric-name-drift"
    summary = (
        "a registry.counter/gauge/histogram(\"name\") literal that "
        "appears in no OBSERVABILITY.md metric table — the obs docs rot "
        "silently otherwise; `tools/lint.py --docs` warns in the other "
        "direction (documented names no code creates)"
    )

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        doc = ctx.project.metric_doc_names()
        if doc is None:
            return []  # no OBSERVABILITY.md at the repo root: fixtures
        out = []
        for name, node in metric_literals(ctx.tree):
            if name not in doc:
                out.append(
                    self.finding(
                        ctx, node,
                        "metric %r is created here but documented in no "
                        "OBSERVABILITY.md table — add a row (name | "
                        "kind | meaning) or rename to a documented "
                        "metric" % name,
                    )
                )
        return out


# ---------------------------------------------------------------------
# 18. blocking-in-event-loop
# ---------------------------------------------------------------------

# socket ops from locks._BLOCKING_ATTRS that stop blocking once the
# module has put its sockets in non-blocking mode — exempted when ANY
# `.setblocking(False)` call appears in the module (the event-loop edge
# convention: every socket the loop touches is non-blocking, so these
# return EWOULDBLOCK instead of stalling). Deliberately module-coarse:
# per-object tracking would be flow analysis, and a selectors loop with
# a BLOCKING socket is already broken before lint gets involved.
_LOOP_SOCKET_ATTRS = frozenset({
    "accept", "recv", "recvfrom", "sendall", "connect",
})


class BlockingInEventLoop(Rule):
    name = "blocking-in-event-loop"
    summary = (
        "an unbounded blocking call (bare lock.acquire(), zero-arg "
        "queue get()/join()/wait()/result(), time.sleep, subprocess "
        "waits, jax.device_get, blocking socket/HTTP I/O) is reachable "
        "from a selectors callback — a function registered as the data "
        "of <selector>.register/.modify. The loop thread multiplexes "
        "EVERY connection: one stalled callback stalls them all, which "
        "is precisely the failure the event-loop edge exists to avoid. "
        "Hand blocking work to a worker thread and re-arm the "
        "completion through the wakeup pipe (serve/edge.py's "
        "_worker/_on_wakeup shape). Socket ops are exempt in modules "
        "that call .setblocking(False) — non-blocking sockets return "
        "EWOULDBLOCK rather than stall"
    )

    @staticmethod
    def _classify(node: ast.Call) -> Optional[str]:
        q = qualname(node.func)
        if q in ("time.sleep", "sleep"):
            return "time.sleep() (stalls the loop for the full duration)"
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            n_args = len(node.args) + len(node.keywords)
            if attr == "acquire" and not n_args:
                # acquire(False) / acquire(timeout=...) are bounded;
                # the bare call parks the loop behind whoever holds it
                return "acquire() without a timeout"
            if attr in ("wait", "result") and not n_args:
                # Event.wait()/Condition.wait()/Future.result() with no
                # bound — waits forever for a producer that may be a
                # worker this very loop is supposed to keep feeding
                return "%s() without a timeout" % attr
        return _classify_blocking(node)

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        reach = ctx.project.loop_callback_reachable(ctx.path)
        if not reach:
            return []
        nonblocking_sockets = any(
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "setblocking"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value is False
            for node in ctx.nodes()
        )
        out = []
        for fn, entry in reach.items():
            if not isinstance(fn, FuncNode):
                continue
            for node in walk_no_nested_funcs(fn):
                if not isinstance(node, ast.Call):
                    continue
                label = self._classify(node)
                if label is None:
                    continue
                if (
                    nonblocking_sockets
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _LOOP_SOCKET_ATTRS
                ):
                    continue
                out.append(
                    self.finding(
                        ctx, node,
                        "%s is reachable from selectors callback %s — "
                        "the loop thread holds every connection, so one "
                        "stalled callback stalls them all; dispatch the "
                        "blocking work to a worker thread and post the "
                        "completion back through the wakeup pipe"
                        % (label, entry),
                    )
                )
        return out


# ---------------------------------------------------------------------
# 19. journal-write-ordering
# ---------------------------------------------------------------------

# the actuations a controller journal exists to make durable: child
# spawns, process signals, and router traffic shifts
_JOURNAL_ACTUATION_QUALNAMES = (
    "subprocess.Popen", "os.kill", "os.killpg",
)
_JOURNAL_ACTUATION_ATTRS = (
    "add_replica", "remove_replica", "decommission",
    "send_signal", "terminate", "kill",
)


class JournalWriteOrdering(Rule):
    name = "journal-write-ordering"
    summary = (
        "a control-plane journal append that is not fsync'd before it "
        "returns, an actuation (process spawn/signal, router traffic "
        "shift) taken BEFORE the journal append that records it, or a "
        "journal snapshot commit marker written before its payload — "
        "each breaks the replay contract: a relaunched controller "
        "trusts the journal, so evidence must be durable before the "
        "action, and the marker must be the LAST snapshot step "
        "(serve/journal.py's append/compact shape)"
    )

    @staticmethod
    def _is_journal_append(node: ast.AST) -> bool:
        """A call that durably records a control-plane action: the
        ``<journal>.append(...)`` method, or a wrapper named for it
        (``self._journal(...)``, ``append_journal(...)``)."""
        if not isinstance(node, ast.Call):
            return False
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr == "append":
                recv = qualname(func.value) or ast.dump(func.value)
                return "journal" in recv.lower()
            name = func.attr
        elif isinstance(func, ast.Name):
            name = func.id
        else:
            return False
        low = name.lower()
        return low == "_journal" or (
            "journal" in low and "append" in low
        )

    @staticmethod
    def _actuation_label(node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        q = qualname(node.func) or ""
        if q in _JOURNAL_ACTUATION_QUALNAMES:
            return q
        if isinstance(node.func, ast.Attribute) and (
            node.func.attr in _JOURNAL_ACTUATION_ATTRS
        ):
            return q or node.func.attr
        return None

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        out = []
        for node in ctx.nodes():
            if isinstance(node, ast.ClassDef) and (
                "journal" in node.name.lower()
            ):
                out.extend(self._check_append_durability(ctx, node))
        for fn in ctx.nodes():
            if isinstance(fn, FuncNode):
                out.extend(self._check_actuation_order(ctx, fn))
                out.extend(self._check_snapshot_marker(ctx, fn))
        return out

    def _check_append_durability(
        self, ctx: ModuleCtx, cls: ast.ClassDef
    ) -> List[Finding]:
        """Inside a *Journal* class, an ``append``/``record``/``log``
        method that writes must fsync AT OR AFTER its last write — a
        flush alone leaves the record in the page cache, and the caller
        actuates the moment append returns: a crash then loses the only
        durable evidence of an action that already happened."""
        out = []
        for fn in cls.body:
            if not isinstance(fn, FuncNode):
                continue
            if not fn.name.lower().lstrip("_").startswith(
                ("append", "record", "log")
            ):
                continue
            writes = [
                n
                for n in walk_no_nested_funcs(fn)
                if isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("write", "writelines")
            ]
            if not writes:
                continue
            last_write = max(w.lineno for w in writes)
            fsynced = any(
                isinstance(n, ast.Call)
                and (qualname(n.func) or "").rsplit(".", 1)[-1]
                == "fsync"
                and n.lineno >= last_write
                for n in walk_no_nested_funcs(fn)
            )
            if not fsynced:
                out.append(
                    self.finding(
                        ctx, writes[-1],
                        "journal method %s.%s writes a record with no "
                        "fsync after the write — the append must be "
                        "durable BEFORE the caller actuates, or a crash "
                        "loses the only record of an action that "
                        "already happened (flush alone stops at the "
                        "page cache)" % (cls.name, fn.name),
                    )
                )
        return out

    def _check_actuation_order(
        self, ctx: ModuleCtx, fn
    ) -> List[Finding]:
        """In a function that journals AND actuates, every actuation
        must come after the first journal append: journal-then-act can
        at worst journal an action that never happened (replay probes
        reality and reaps it); act-then-journal can take an action the
        journal never heard of — the replayed controller double-spawns
        or orphans it."""
        appends = sorted(
            n.lineno
            for n in walk_no_nested_funcs(fn)
            if self._is_journal_append(n)
        )
        if not appends:
            return []
        out = []
        for node in walk_no_nested_funcs(fn):
            label = self._actuation_label(node)
            if label is None or node.lineno >= appends[0]:
                continue
            out.append(
                self.finding(
                    ctx, node,
                    "%s runs BEFORE this function's first journal "
                    "append (line %d) — the actuation outruns its own "
                    "durable record, so a crash in between leaves an "
                    "action the replayed controller never heard of "
                    "(double-spawn / orphan on recovery); append first, "
                    "act second" % (label, appends[0]),
                )
            )
        return out

    def _check_snapshot_marker(
        self, ctx: ModuleCtx, fn
    ) -> List[Finding]:
        """Journal snapshot publishes — ``<helper>(base + SUFFIX, ...)``
        atomic writes — must write the commit marker LAST: replay
        trusts whatever a verified marker describes, so a marker
        published before its payload describes bytes not yet on disk
        (same contract atomic-publish pins for meta_path sidecars)."""
        markers: List[Tuple[int, str, ast.AST]] = []
        payloads: List[Tuple[int, str]] = []
        for node in walk_no_nested_funcs(fn):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            q = (qualname(node.func) or "").rsplit(".", 1)[-1]
            if q not in ("_atomic_write", "atomic_write"):
                continue
            path = node.args[0]
            if not (
                isinstance(path, ast.BinOp)
                and isinstance(path.op, ast.Add)
                and isinstance(path.right, ast.Name)
            ):
                continue
            key = ast.dump(path.left)
            suffix = path.right.id.lower()
            if "marker" in suffix or "commit" in suffix:
                markers.append((node.lineno, key, node))
            else:
                payloads.append((node.lineno, key))
        out = []
        for mline, mkey, mnode in markers:
            if any(pk == mkey and pl > mline for pl, pk in payloads):
                out.append(
                    self.finding(
                        ctx, mnode,
                        "journal snapshot commit marker is written "
                        "BEFORE its payload — replay trusts a verified "
                        "marker, so it must be the LAST publish step "
                        "(payload, fsync, then marker)",
                    )
                )
        return out


# ---------------------------------------------------------------------
# 20-21. exception-flow rules (lint/exceptions.py: the whole-project
# may-raise fixpoint they both ride on)
# ---------------------------------------------------------------------


class _ExceptionRule(Rule):
    """Shared shape (same as _LockRule): ask the memoized exception-flow
    analysis for this module's findings — the fixpoint runs once per
    lint run, not per rule per file."""

    provider = ""  # ExceptionFlow method name

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        analysis = ctx.project.exception_flow()
        return [
            Finding(self.name, ctx.relpath, line, col, msg)
            for line, col, msg in getattr(analysis, self.provider)(ctx.path)
        ]


class UnmappedEdgeException(_ExceptionRule):
    name = "unmapped-edge-exception"
    provider = "edge_findings_for"
    summary = (
        "an exception that can escape a frontend/edge dispatch entry "
        "(a selectors loop callback or do_GET/do_POST handler) with no "
        "status-code mapping in the handler chain — the loop's "
        "dispatch-site `except Exception` only logs, so the client "
        "gets a wedged connection instead of an error response (the "
        "PR 16 shed-429 parser-mid-state TypeError: the next keep-"
        "alive request crashed the callback); the OSError family is "
        "exempt — a dead socket has no client left to answer"
    )


class RaiseBeforeCleanup(_ExceptionRule):
    name = "raise-before-cleanup"
    provider = "cleanup_findings_for"
    summary = (
        "a may-raise call on a stop/close/drain-shaped path positioned "
        "BEFORE a resource-releasing call with no shared try/finally — "
        "the raise skips the release (PR 17: the drain banner's "
        "`print(..., file=sys.stderr)` raised BrokenPipeError before "
        "`frontend.stop()`, hanging shutdown 62s); move the release "
        "into a finally or catch the exception around the call"
    )


# ---------------------------------------------------------------------
# 22. fd-lifecycle (lint/fdlife.py: rule 17's escape analysis
# generalized from Popen handles to fds)
# ---------------------------------------------------------------------


class FdLifecycle(Rule):
    name = "fd-lifecycle"
    summary = (
        "a socket/os.pipe/os.open/open/selector acquisition that never "
        "reaches close/unregister on any path, is not with-scoped, and "
        "is never handed to an owner that reaps it (class attr closed "
        "by some method, the `s = self._sock` alias, a container) — "
        "one fd leaked per iteration is how the PR 16 `Connection: "
        "close` socket bled the edge"
    )

    def check(self, ctx: ModuleCtx) -> List[Finding]:
        analysis = ctx.project.fd_lifecycle()
        return [
            Finding(self.name, ctx.relpath, line, col, msg)
            for line, col, msg in analysis.findings_for(ctx.path)
        ]


RULES = (
    JitImpurity(),
    PrngReuse(),
    TracerBranch(),
    HostSync(),
    DonationMisuse(),
    UnlockedSharedMutation(),
    CompatBypass(),
    FlagConfigDrift(),
    ThreadCollective(),
    AtomicPublish(),
    ThreadJoin(),
    SubprocessLifecycle(),
    LockOrderInversion(),
    BlockingUnderLock(),
    CondWaitDiscipline(),
    LockLeak(),
    MetricNameDrift(),
    BlockingInEventLoop(),
    JournalWriteOrdering(),
    UnmappedEdgeException(),
    RaiseBeforeCleanup(),
    FdLifecycle(),
)


def rule_names() -> Tuple[str, ...]:
    return tuple(r.name for r in RULES)


def rules_by_name(names: Sequence[str]):
    by = {r.name: r for r in RULES}
    missing = [n for n in names if n not in by]
    if missing:
        raise KeyError(missing)
    return tuple(by[n] for n in names)
