"""graftcheck lock-effect analysis: who holds what, and what happens then.

PRs 6-10 turned this repo into a heavily threaded serving fleet — lock
and condition sites across the frontend handler threads, the router's
probe thread, the batcher lanes, the canary poll+shadow workers, and the
async checkpoint writer — and every one of those PRs shipped at least
one hand-found threading bug. The whole-project call graph (PR 8) sees
*which* code runs on which thread; this module closes its documented
known-limit by computing *what happens while a lock is held*:

- **Lock identity.** Every ``threading.Lock/RLock/Condition`` the tree
  constructs is keyed by where it lives: ``(module.Class, attr)`` for
  ``self._lock`` attributes, ``(module, name)`` for module-level locks,
  ``(module:function, name)`` for function-locals closed over by
  workers. ``Event`` is tracked (its ``wait`` matters below) but is not
  a lock.
- **Per-function lock summaries.** A block-structured walk of every def
  computes, flow-sensitively per statement: which locks are acquired
  (``with self._lock:``, explicit ``acquire()``/``release()``), the
  held-set at every resolved call site, every *blocking* call
  (``join()``, bare ``queue.get()``, socket/HTTP I/O, ``subprocess``,
  ``jax.device_get``/``block_until_ready``, unbounded ``wait()``), and
  every ``Condition`` ``wait``/``notify`` site.
- **Whole-project propagation.** Held-sets flow through the PR 8
  cross-module call graph: a callee inherits the union of its callers'
  held-sets at their call sites (``*_locked`` methods of a one-lock
  class are assumed entered with that lock held — the repo's own
  caller-holds-the-lock convention), and each function's transitively
  *acquired* set flows back up to order edges at the call site.
- **The lock-order graph.** Acquiring B while holding A is the edge
  A→B, whether the acquisition is lexical (nested ``with``) or hiding
  three calls deep in another module. A cycle is the deadlock shape:
  two call paths that take the same locks in opposite order only need
  one bad interleaving.

Rules in :mod:`pytorch_cifar_tpu.lint.rules` consume this through
``ctx.project.lock_analysis()``: ``lock-order-inversion``,
``blocking-under-lock``, ``cond-wait-discipline`` and ``lock-leak``.
Pure stdlib ``ast``; resolution stays conservative (an unresolvable
receiver contributes nothing) — the self-run must not cry wolf.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pytorch_cifar_tpu.lint.project import (
    FuncNode,
    ModuleInfo,
    parents_map,
    qualname,
    walk_no_nested_funcs,
)

# ctor qualname -> kind; Event is deliberately "event", not a lock: its
# wait() is a blocking primitive but holding no one's critical section
_CTOR_KINDS = {
    "threading.Lock": "lock",
    "Lock": "lock",
    "threading.RLock": "rlock",
    "RLock": "rlock",
    "threading.Condition": "cond",
    "Condition": "cond",
    "threading.Event": "event",
    "Event": "event",
}
_LOCK_KINDS = frozenset({"lock", "rlock", "cond"})

# blocking calls: the stall-under-lock shapes this repo has actually
# paid for (a frontend handler or the canary controller frozen behind a
# lock). Matched conservatively — see _classify_blocking.
_BLOCKING_SIMPLE = {
    "jax.device_get": "jax.device_get (a blocking D2H sync)",
    "device_get": "device_get (a blocking D2H sync)",
    "urllib.request.urlopen": "urlopen (network I/O)",
    "request.urlopen": "urlopen (network I/O)",
    "urlopen": "urlopen (network I/O)",
    "socket.create_connection": "socket connect (network I/O)",
}
_BLOCKING_SUBPROCESS = frozenset({
    "run", "call", "check_call", "check_output", "Popen",
})
# attribute calls that block regardless of receiver type
_BLOCKING_ATTRS = frozenset({
    "getresponse", "accept", "recv", "recvfrom", "sendall", "connect",
    "communicate", "block_until_ready",
})

LockKey = Tuple[str, str]  # (owner, attr/name)


def fmt_key(key: LockKey) -> str:
    """Human name for a lock key: ``MicroBatcher._cond`` for class
    attrs, ``faults._lock`` for module/function locals."""
    owner, attr = key
    return "%s.%s" % (owner.rsplit(".", 1)[-1].rsplit(":", 1)[-1], attr)


class _FnLocks:
    """One function's lock summary (see module docstring)."""

    __slots__ = (
        "path", "key", "node",
        "acquisitions",   # [(lock key, ast node, held-before tuple)]
        "calls",          # [((callee path, callee key), node, held tuple)]
        "blocking",       # [(node, label, held tuple)]
        "waits",          # [(key, node, held, in_while, is_wait_for)]
        "notifies",       # [(key, node, held, method name)]
        "leaks",          # [(node, message)]
    )

    def __init__(self, path: str, key: str, node: ast.AST):
        self.path = path
        self.key = key
        self.node = node
        self.acquisitions = []
        self.calls = []
        self.blocking = []
        self.waits = []
        self.notifies = []
        self.leaks = []


class _ModuleLockDecls:
    """Where this module's locks live: ctor-evidence tables keyed the
    same way the use-site resolver looks them up."""

    def __init__(self, m: ModuleInfo):
        self.m = m
        self.class_attr: Dict[Tuple[str, str], str] = {}  # (cls, attr)->kind
        self.module_vars: Dict[str, str] = {}
        self.func_local: Dict[Tuple[str, str], str] = {}  # (fnkey, name)
        self._scan()

    def _scan(self) -> None:
        m = self.m
        parents = parents_map(m.tree)

        def enclosing(node):
            p = parents.get(node)
            while p is not None and not isinstance(
                p, FuncNode + (ast.ClassDef,)
            ):
                p = parents.get(p)
            return p

        for node in ast.walk(m.tree):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
            ):
                continue
            kind = _CTOR_KINDS.get(qualname(node.value.func) or "")
            if kind is None:
                continue
            for tgt in node.targets:
                tq = qualname(tgt)
                if tq and tq.startswith("self.") and tq.count(".") == 1:
                    # attribute of the enclosing class (walk up past the
                    # defining method to its ClassDef)
                    p = enclosing(node)
                    while p is not None and not isinstance(p, ast.ClassDef):
                        p = enclosing(p)
                    if p is not None:
                        self.class_attr[(p.name, tq.split(".", 1)[1])] = kind
                elif isinstance(tgt, ast.Name):
                    scope = enclosing(node)
                    while scope is not None and not isinstance(
                        scope, FuncNode
                    ):
                        scope = enclosing(scope)
                    if scope is None:
                        self.module_vars[tgt.id] = kind
                    else:
                        fk = m.key_of.get(id(scope))
                        if fk is not None:
                            self.func_local[(fk, tgt.id)] = kind

    def resolve(
        self, fkey: str, cls: Optional[str], q: str
    ) -> Optional[Tuple[LockKey, str]]:
        """The lock key + kind a dotted use-site name refers to, or None
        when it is not a ctor-evidenced lock of this module."""
        if q.startswith("self."):
            attr = q.split(".", 1)[1]
            if "." in attr or cls is None:
                return None
            kind = self.class_attr.get((cls, attr))
            if kind is None:
                return None
            return ((self.m.name + "." + cls, attr), kind)
        if "." in q:
            return None  # obj.attr locks: type unknown, contribute nothing
        scope = fkey
        while scope:
            kind = self.func_local.get((scope, q))
            if kind is not None:
                return ((self.m.name + ":" + scope, q), kind)
            scope = (
                scope.rpartition(".<locals>.")[0]
                if ".<locals>." in scope
                else ""
            )
        kind = self.module_vars.get(q)
        if kind is not None:
            return ((self.m.name, q), kind)
        return None


def _call_args(call: ast.Call):
    return list(call.args) + [kw.value for kw in call.keywords]


def _classify_blocking(call: ast.Call) -> Optional[str]:
    """Label when ``call`` is an unbounded blocking operation; None
    otherwise. Bounded variants (``join(timeout)``, ``wait(t)``,
    ``get(..., timeout=...)``) are deliberately not flagged."""
    q = qualname(call.func)
    if q is not None:
        label = _BLOCKING_SIMPLE.get(q)
        if label is not None:
            return label
        head, _, last = q.rpartition(".")
        if head.split(".")[-1] == "subprocess" and (
            last in _BLOCKING_SUBPROCESS
        ):
            return "subprocess.%s (child-process wait)" % last
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    if attr in _BLOCKING_ATTRS and attr != "connect":
        return "%s() (blocking I/O)" % attr
    if attr == "connect" and not _call_args(call):
        return None  # zero-arg connect is not the socket shape
    has_args = bool(_call_args(call))
    if attr == "join" and not has_args:
        # str.join/os.path.join always take an argument, so a zero-arg
        # .join() is a thread/process join — unbounded
        return "join() without a timeout"
    if attr == "get" and not has_args:
        # dict.get/os.environ.get need a key: a zero-arg .get() is a
        # queue.Queue.get() that blocks until a producer shows up
        return "queue get() without a timeout"
    return None


class LockAnalysis:
    """The whole-run lock pass. Built lazily by ``ProjectGraph.locks()``
    the first time a concurrency rule asks; every product is memoized."""

    def __init__(self, graph):
        self.graph = graph
        self.decls: Dict[str, _ModuleLockDecls] = {}
        self.fns: Dict[Tuple[str, str], _FnLocks] = {}
        self._node_of: Dict[Tuple[str, str], ast.AST] = {}
        self._by_path: Dict[str, List[_FnLocks]] = {}
        self._cycles: Optional[List[dict]] = None
        self._entry_held: Optional[Dict] = None
        self._blocking_findings: Optional[Dict[str, list]] = None
        graph._analyze()  # the call graph the propagation rides on
        for m in list(graph.by_path.values()):
            self._analyze_module(m)

    # -- per-module extraction ----------------------------------------

    def _analyze_module(self, m: ModuleInfo) -> None:
        decls = _ModuleLockDecls(m)
        self.decls[m.path] = decls
        parents = parents_map(m.tree)
        for key, d in m.defs.items():
            if not isinstance(d, FuncNode):
                continue
            fn = self._walk_fn(m, decls, parents, key, d)
            self.fns[(m.path, key)] = fn
            self._node_of[(m.path, key)] = d
            self._by_path.setdefault(m.path, []).append(fn)

    def _walk_fn(
        self,
        m: ModuleInfo,
        decls: _ModuleLockDecls,
        parents: Dict[ast.AST, ast.AST],
        fkey: str,
        d: ast.AST,
    ) -> _FnLocks:
        fn = _FnLocks(m.path, fkey, d)
        cls = m.cls_of.get(id(d))
        graph = self.graph
        # frozensets of keys released by enclosing finally blocks: an
        # early return/raise is covered when every explicitly-held lock
        # appears in one of these
        protected: List[frozenset] = []
        # acquire nodes already flagged by an exit-path leak — the
        # end-of-function sweep must not report the same acquire twice
        leaked_origins: set = set()

        def lock_of(expr: ast.AST) -> Optional[Tuple[LockKey, str]]:
            q = qualname(expr)
            if q is None:
                return None
            return decls.resolve(fkey, cls, q)

        def held_keys(held) -> Tuple[LockKey, ...]:
            return tuple(k for k, _origin in held)

        def in_while(node: ast.AST) -> bool:
            p = parents.get(node)
            while p is not None and p is not d:
                if isinstance(p, ast.While):
                    return True
                if isinstance(p, FuncNode):
                    return False
                p = parents.get(p)
            return False

        def visit_call(call: ast.Call, held) -> None:
            hk = held_keys(held)
            r = graph._resolve_callable(m, parents, call, call.func)
            if r is not None:
                fn.calls.append(((r[0].path, r[1]), call, hk))
            f = call.func
            if isinstance(f, ast.Attribute):
                recv = lock_of(f.value)
                if f.attr in ("wait", "wait_for") and recv is not None:
                    key, kind = recv
                    if kind == "cond":
                        fn.waits.append(
                            (key, call, hk, in_while(call),
                             f.attr == "wait_for")
                        )
                        return  # a condition wait is never re-classified
                    if kind == "event":
                        if not _call_args(call):
                            fn.blocking.append(
                                (call,
                                 "Event.wait() without a timeout", hk)
                            )
                        return
                if f.attr in ("notify", "notify_all") and recv is not None:
                    key, kind = recv
                    if kind == "cond":
                        fn.notifies.append((key, call, hk, f.attr))
                        return
                if f.attr == "wait" and recv is None and not _call_args(
                    call
                ):
                    fn.blocking.append(
                        (call, "unbounded wait()", hk)
                    )
                    return
            label = _classify_blocking(call)
            if label is not None:
                fn.blocking.append((call, label, hk))

        def scan_exprs(node: ast.AST, held) -> None:
            """In-order visit of every Call in ``node``'s subtree, not
            descending into nested defs/lambdas (their bodies run later,
            under whatever locks their own callers hold)."""
            if isinstance(node, FuncNode + (ast.Lambda,)):
                return
            if isinstance(node, ast.Call):
                visit_call(node, held)
            for child in ast.iter_child_nodes(node):
                scan_exprs(child, held)

        def acquire_release_in(stmt: ast.AST, held: list) -> list:
            """Apply explicit ``acquire()``/``release()`` calls inside
            one statement to the running held list."""
            for node in walk_no_nested_funcs(stmt):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("acquire", "release")
                ):
                    continue
                recv = lock_of(node.func.value)
                if recv is None or recv[1] not in _LOCK_KINDS:
                    continue
                key = recv[0]
                if node.func.attr == "acquire":
                    fn.acquisitions.append((key, node, held_keys(held)))
                    held = held + [(key, node)]
                else:
                    for i in range(len(held) - 1, -1, -1):
                        if held[i][0] == key:
                            held = held[:i] + held[i + 1:]
                            break
            return held

        def exit_leaks(stmt: ast.AST, held) -> None:
            cover = frozenset().union(*protected) if protected else (
                frozenset()
            )
            for key, origin in held:
                if not isinstance(origin, ast.Call):
                    continue  # with-blocks release on every exit path
                if key in cover:
                    continue
                kind = (
                    "return" if isinstance(stmt, ast.Return) else "raise"
                )
                leaked_origins.add(id(origin))
                fn.leaks.append(
                    (stmt,
                     "early %s while %s is still held (acquired at line "
                     "%d with no covering try/finally release) — every "
                     "later acquirer deadlocks; use `with` or release in "
                     "a finally" % (kind, fmt_key(key), origin.lineno))
                )

        def finally_released(finalbody) -> frozenset:
            out = set()
            for stmt in finalbody:
                for node in walk_no_nested_funcs(stmt):
                    if (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "release"
                    ):
                        recv = lock_of(node.func.value)
                        if recv is not None:
                            out.add(recv[0])
            return frozenset(out)

        def do_block(stmts: Sequence[ast.stmt], held: list) -> list:
            for stmt in stmts:
                held = do_stmt(stmt, held)
            return held

        def do_stmt(stmt: ast.stmt, held: list) -> list:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                newly = []
                for item in stmt.items:
                    scan_exprs(item.context_expr, held)
                    lk = lock_of(item.context_expr)
                    if (
                        lk is not None
                        and lk[1] in _LOCK_KINDS
                        and lk[0] not in held_keys(held)
                    ):
                        fn.acquisitions.append(
                            (lk[0], item.context_expr, held_keys(held))
                        )
                        newly.append((lk[0], "with"))
                do_block(stmt.body, held + newly)
                return held
            if isinstance(stmt, ast.If):
                scan_exprs(stmt.test, held)
                h1 = do_block(stmt.body, list(held))
                h2 = do_block(stmt.orelse, list(held))
                k2 = held_keys(h2)
                return [e for e in h1 if e[0] in k2]
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                scan_exprs(
                    stmt.iter if isinstance(stmt, (ast.For, ast.AsyncFor))
                    else stmt.test,
                    held,
                )
                do_block(list(stmt.body) + list(stmt.orelse), list(held))
                return held  # loop-internal imbalance is caught per-exit
            if isinstance(stmt, ast.Try):
                fin = finally_released(stmt.finalbody)
                protected.append(fin)
                h = do_block(stmt.body, list(held))
                for handler in stmt.handlers:
                    do_block(handler.body, list(held))
                h = do_block(stmt.orelse, h)
                protected.pop()
                # the finally runs on the fall-through path too
                return do_block(stmt.finalbody, h)
            if isinstance(stmt, (ast.Return, ast.Raise)):
                if stmt_value := getattr(stmt, "value", None):
                    scan_exprs(stmt_value, held)
                if isinstance(stmt, ast.Raise) and stmt.exc is not None:
                    scan_exprs(stmt.exc, held)
                exit_leaks(stmt, held)
                return held
            if isinstance(stmt, FuncNode + (ast.ClassDef,)):
                return held  # nested defs are their own analysis units
            scan_exprs(stmt, held)
            return acquire_release_in(stmt, held)

        end_held = do_block(d.body, [])
        for key, origin in end_held:
            if isinstance(origin, ast.Call) and id(origin) not in (
                leaked_origins
            ):
                fn.leaks.append(
                    (origin,
                     "%s is acquired here but no path through %r releases "
                     "it — every later acquirer deadlocks; use `with` or "
                     "pair it with release() in a finally"
                     % (fmt_key(key), fkey.rsplit(".", 1)[-1]))
                )
        return fn

    # -- whole-project propagation -------------------------------------

    def _acquired_closure(self) -> Dict[Tuple[str, str], Set[LockKey]]:
        """fn -> every lock it (or any transitive callee) acquires."""
        if getattr(self, "_acq_closure", None) is not None:
            return self._acq_closure
        acq: Dict[Tuple[str, str], Set[LockKey]] = {}
        for nk, fn in self.fns.items():
            acq[nk] = {k for k, _n, _h in fn.acquisitions}
        changed = True
        while changed:
            changed = False
            for nk, fn in self.fns.items():
                mine = acq[nk]
                for callee, _node, _held in fn.calls:
                    extra = acq.get(callee)
                    if extra and not extra.issubset(mine):
                        mine |= extra
                        changed = True
        self._acq_closure = acq
        return acq

    def entry_held(self) -> Dict[Tuple[str, str], Dict[LockKey, str]]:
        """fn -> {lock key: provenance} for locks held by some caller at
        a resolved call site (transitively). ``*_locked`` methods of a
        class owning exactly one lock/condition are seeded with that
        lock — the repo's caller-holds-the-lock convention."""
        if self._entry_held is not None:
            return self._entry_held
        entry: Dict[Tuple[str, str], Dict[LockKey, str]] = {
            nk: {} for nk in self.fns
        }
        # the *_locked convention seed
        for (path, key), fn in self.fns.items():
            base = key.rsplit(".", 1)[-1]
            if not base.endswith("_locked"):
                continue
            cls = None
            m = self.graph.by_path.get(path)
            if m is not None:
                cls = m.cls_of.get(id(fn.node))
            if cls is None:
                continue
            decls = self.decls.get(path)
            if decls is None:
                continue
            owned = [
                ((m.name + "." + c, a), kind)
                for (c, a), kind in decls.class_attr.items()
                if c == cls and kind in _LOCK_KINDS
            ]
            if len(owned) == 1:
                entry[(path, key)][owned[0][0]] = (
                    "the %s caller-holds-the-lock convention" % base
                )
        changed = True
        while changed:
            changed = False
            for nk, fn in self.fns.items():
                caller_entry = entry[nk]
                for callee, node, held in fn.calls:
                    if callee not in entry:
                        continue
                    tgt = entry[callee]
                    for k in held:
                        if k not in tgt:
                            tgt[k] = "%s (%s:%d)" % (
                                fn.key.rsplit(".", 1)[-1],
                                os.path.basename(fn.path),
                                node.lineno,
                            )
                            changed = True
                    for k, why in caller_entry.items():
                        if k not in tgt:
                            tgt[k] = why
                            changed = True
        self._entry_held = entry
        return entry

    # -- rule products --------------------------------------------------

    def order_edges(self) -> Dict[Tuple[LockKey, LockKey], Tuple[str, int, str]]:
        """(held, acquired) -> one witness site (path, line, fn name).
        Local nesting and interprocedural acquisition both contribute;
        the witness is the smallest (path, line) for determinism."""
        if getattr(self, "_edges", None) is not None:
            return self._edges
        acq = self._acquired_closure()
        entry = self.entry_held()
        edges: Dict[Tuple[LockKey, LockKey], Tuple[str, int, str]] = {}

        def add(a: LockKey, b: LockKey, path: str, line: int, fname: str):
            if a == b:
                return  # reentrancy is the cond/RLock idiom, not an order
            site = (path, line, fname)
            cur = edges.get((a, b))
            if cur is None or site[:2] < cur[:2]:
                edges[(a, b)] = site
        for nk, fn in self.fns.items():
            fname = fn.key.rsplit(".", 1)[-1]
            ent = tuple(entry.get(nk, ()))
            for key, node, held in fn.acquisitions:
                for h in tuple(held) + ent:
                    add(h, key, fn.path, node.lineno, fname)
            for callee, node, held in fn.calls:
                inner = acq.get(callee)
                if not inner:
                    continue
                for h in tuple(held) + ent:
                    for key in inner:
                        add(h, key, fn.path, node.lineno, fname)
        self._edges = edges
        return edges

    def cycles(self) -> List[dict]:
        """Elementary lock-order cycles, each reported once: a sorted
        list of {keys, edges, witness} dicts. Tarjan SCCs first, then
        one deterministic cycle per SCC."""
        if self._cycles is not None:
            return self._cycles
        edges = self.order_edges()
        succ: Dict[LockKey, List[LockKey]] = {}
        for (a, b) in edges:
            succ.setdefault(a, []).append(b)
            succ.setdefault(b, [])
        for k in succ:
            succ[k].sort()
        index: Dict[LockKey, int] = {}
        low: Dict[LockKey, int] = {}
        on: Set[LockKey] = set()
        stack: List[LockKey] = []
        sccs: List[List[LockKey]] = []
        counter = [0]

        def strongconnect(v: LockKey) -> None:
            # iterative Tarjan (fixture graphs are tiny, but recursion
            # depth must not depend on linted input)
            work = [(v, iter(succ[v]))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(succ[w])))
                        advanced = True
                        break
                    if w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        sccs.append(sorted(comp))

        for v in sorted(succ):
            if v not in index:
                strongconnect(v)

        out = []
        for comp in sccs:
            comp_set = set(comp)
            # one deterministic elementary cycle per SCC: BFS from each
            # successor of the smallest key back to it, shortest wins
            start = comp[0]
            path = None
            for w in succ[start]:
                if w not in comp_set:
                    continue
                prev: Dict[LockKey, Optional[LockKey]] = {w: None}
                frontier = [w]
                while frontier and start not in prev:
                    nxt_frontier = []
                    for n in frontier:
                        for v in succ[n]:
                            if v in comp_set and v not in prev:
                                prev[v] = n
                                nxt_frontier.append(v)
                    frontier = nxt_frontier
                if start not in prev:
                    continue
                nodes = [start]
                n = start
                while prev[n] is not None:
                    n = prev[n]
                    nodes.append(n)
                cand = [start] + list(reversed(nodes[1:]))
                if path is None or len(cand) < len(path):
                    path = cand
            if path is None:
                continue
            cyc_edges = []
            for i, a in enumerate(path):
                b = path[(i + 1) % len(path)]
                site = edges.get((a, b))
                if site is not None:
                    cyc_edges.append((a, b, site))
            if len(cyc_edges) < 2:
                continue
            witness = min(cyc_edges, key=lambda e: e[2][:2])
            out.append({
                "keys": path,
                "edges": cyc_edges,
                "witness": witness,
            })
        out.sort(key=lambda c: c["witness"][2][:2])
        self._cycles = out
        return out

    def cycle_findings_for(self, path: str) -> List[Tuple[int, int, str]]:
        """(line, col, message) per cycle whose witness edge sits in the
        module at ``path`` — each cycle is reported exactly once, at its
        deterministic witness site."""
        ap = os.path.abspath(path)
        out = []
        for cyc in self.cycles():
            a, b, (wpath, wline, wfn) = cyc["witness"]
            if os.path.abspath(wpath) != ap:
                continue
            others = [
                "%s -> %s at %s:%d (in %s)" % (
                    fmt_key(x), fmt_key(y),
                    os.path.basename(sp), sl, sf,
                )
                for x, y, (sp, sl, sf) in cyc["edges"]
                if (x, y) != (a, b)
            ]
            msg = (
                "lock-order inversion: %s is acquired while %s is held "
                "(here, in %s), but the opposite order exists — %s — so "
                "two threads interleaving these paths deadlock; pick ONE "
                "global order for %s"
                % (
                    fmt_key(b), fmt_key(a), wfn,
                    "; ".join(others),
                    " and ".join(sorted({fmt_key(k) for k in cyc["keys"]})),
                )
            )
            out.append((wline, 0, msg))
        return out

    def blocking_findings_for(self, path: str) -> List[Tuple[int, int, str]]:
        """(line, col, message) for every blocking call in ``path`` made
        while a lock is held — locally, or via the held-sets its callers
        propagate through the call graph."""
        if self._blocking_findings is None:
            entry = self.entry_held()
            by_path: Dict[str, list] = {}
            for nk, fn in self.fns.items():
                ent = entry.get(nk, {})
                for node, label, held in fn.blocking:
                    if held:
                        lock = held[-1]
                        why = "held here in %s" % fn.key.rsplit(".", 1)[-1]
                    elif ent:
                        lock = sorted(ent)[0]
                        why = "held by a caller: %s" % ent[lock]
                    else:
                        continue
                    msg = (
                        "%s while %s is %s — the stall freezes every "
                        "thread contending for that lock (frontend "
                        "handlers, the canary poll, the batcher worker); "
                        "move the blocking call outside the critical "
                        "section or bound it with a timeout"
                        % (label, fmt_key(lock), why)
                    )
                    by_path.setdefault(fn.path, []).append(
                        (node.lineno, node.col_offset, msg)
                    )
            self._blocking_findings = by_path
        return sorted(self._blocking_findings.get(os.path.abspath(path), []))

    def cond_findings_for(self, path: str) -> List[Tuple[int, int, str]]:
        """Condition-discipline findings for ``path``: wait() without
        the condition held, wait() outside a while-predicate loop, and
        notify()/notify_all() without the condition held."""
        ap = os.path.abspath(path)
        out = []
        entry = self.entry_held()
        for nk, fn in self.fns.items():
            if os.path.abspath(fn.path) != ap:
                continue
            ent = entry.get(nk, {})
            for key, node, held, in_loop, is_wait_for in fn.waits:
                if key not in held and key not in ent:
                    out.append((
                        node.lineno, node.col_offset,
                        "%s.wait() without holding %s — raises "
                        "RuntimeError('cannot wait on un-acquired lock') "
                        "at runtime; wrap it in `with %s:`"
                        % (fmt_key(key), fmt_key(key), fmt_key(key)),
                    ))
                    continue
                if not is_wait_for and not in_loop:
                    out.append((
                        node.lineno, node.col_offset,
                        "%s.wait() outside a while-predicate loop — "
                        "spurious wakeups and missed notifies are both "
                        "legal, so the predicate must be re-checked: "
                        "`while not <pred>: cond.wait()` (or use "
                        "wait_for)" % fmt_key(key),
                    ))
            for key, node, held, meth in fn.notifies:
                if key not in held and key not in ent:
                    out.append((
                        node.lineno, node.col_offset,
                        "%s.%s() without holding %s — raises "
                        "RuntimeError at runtime, and a notify racing "
                        "the waiter's predicate check is a lost wakeup; "
                        "hold the condition to notify"
                        % (fmt_key(key), meth, fmt_key(key)),
                    ))
        return sorted(out)

    def leak_findings_for(self, path: str) -> List[Tuple[int, int, str]]:
        ap = os.path.abspath(path)
        out = []
        for fn in self._by_path.get(ap, ()):  # insertion order is stable
            for node, msg in fn.leaks:
                out.append((node.lineno, node.col_offset, msg))
        return sorted(out)
