"""graftcheck whole-project analysis: import graph + cross-module call graph.

PR 5's engine was deliberately single-module, and STATIC_ANALYSIS.md's
"Known limits" named the escapes that bought: a closure traced in ANOTHER
module, an aliased dp wrapper (``f = data_parallel_train_step``), a
collective reachable only through a helper. This module closes them with
one whole-tree pass:

- every file is parsed ONCE per run (the engine's ``_Project`` AST cache
  is shared, so a rule walking ``ctx.tree`` and the graph walking the
  same module see the *same* node objects — seed sets are plain node
  sets, no name matching);
- ``import``/``from-import``/``as``-alias/re-export bindings are resolved
  into an import graph (``to_json`` backs ``tools/lint.py --graph``,
  ``reverse_dependents`` backs the graph-aware ``--changed``);
- a cross-module call graph (``self.method``, local defs, imported
  functions) feeds three reachability analyses rules consume through
  ``ctx.project``: externally-traced closures (jit-impurity /
  tracer-branch / prng-reuse), hot-path scoping from the trainer step
  loop and engine dispatch (host-sync), and thread-entry reachability
  (thread-collective);
- the donation table is DERIVED from ``parallel/dp.py``'s own AST (the
  ``jax.jit(..., donate_argnums=...)`` expression, including its
  ``donate`` gate) instead of a hand-synced name table.

Everything here is pure stdlib ``ast`` over source text — linted code is
never imported. Resolution is deliberately conservative: an unresolvable
binding simply contributes nothing (rules under-approximate rather than
cry wolf). Modules imported from outside the linted file set (e.g. a
fixture that imports ``pytorch_cifar_tpu.parallel``) are loaded on demand
from the repo this lint package ships in, so fixtures see the real
wrapper definitions.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)

# entry points whose function-valued arguments get traced by jax
TRACER_CALLS = {
    "jax.jit", "jit",
    "jax.vmap", "vmap",
    "jax.grad", "jax.value_and_grad",
    "jax.checkpoint", "jax.remat",
    "jax.lax.scan", "lax.scan",
    "jax.lax.cond", "lax.cond",
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.map", "lax.map",
    "shard_map", "jax.experimental.shard_map.shard_map",
    "pl.pallas_call", "pallas_call",
}
TRACER_DECORATORS = {
    "jax.jit", "jit", "jax.checkpoint", "jax.remat", "jax.vmap", "vmap",
}

# host-side cross-process collectives: every participant must arrive, so
# calling one from a thread that makes its own local timing decisions can
# strand the peers at the barrier (the thread-collective rule's set)
HOST_COLLECTIVES = frozenset({
    "broadcast_pytree", "broadcast_one_to_all", "process_allgather",
    "allgather_merged", "sync_global_devices", "barrier",
})

# host-sync hot-path SEEDS: (path suffix, function basenames). Everything
# CALLED from a seed — helpers included, across modules — becomes hot via
# call-graph reachability, replacing PR 5's hand-maintained per-function
# table (its blind spot: a sync hidden in a helper the table never named).
HOT_SEEDS: Sequence[Tuple[str, frozenset]] = (
    ("train/trainer.py",
     frozenset({"fit", "train_epoch", "eval_epoch", "finish"})),
    ("serve/engine.py", frozenset({"predict"})),
    # the batcher's whole dispatch path: formation, the continuous-
    # admission slack pass, and staged assembly all run per device call
    # — seeded explicitly so a worker refactor cannot silently drop them
    # out of host-sync scope
    ("serve/batcher.py",
     frozenset({"_worker", "_admit_slack_locked", "_assemble"})),
    # the zoo's request path: routing + admission + the eviction drain
    # all sit in front of every tenant's device call — seeded explicitly
    # so a tenancy refactor cannot silently drop them out of host-sync
    # scope (same rationale as the batcher worker seeds above)
    ("serve/tenancy.py",
     frozenset({"submit", "predict", "_ensure_resident", "_evict"})),
)

_THREAD_CTORS = ("threading.Thread", "Thread")

# sanctioned collective-thread entries (STATIC_ANALYSIS.md
# "thread-collective"): a module may declare, at top level,
#
#   GRAFTCHECK_SANCTIONED_COLLECTIVE_ENTRIES = {
#       "Class.method": "why a collective on this thread is safe",
#   }
#
# naming a def in the SAME module. A declared entry is removed from the
# thread-reachability seeds, so collectives inside it — and in helpers
# reachable ONLY through it — stop firing; everything reachable from any
# UNDECLARED Thread target still fires, including helpers the sanctioned
# entry shares with one. The reason is mandatory (same policy as noqa),
# and a declaration naming a def the module does not define is itself a
# finding — a rename cannot silently widen the sanction. The intended
# (and only current) legitimate shape is a single-initiator lock-step
# protocol loop: exactly one thread in the whole job starts collectives,
# peers are pure responders on their main thread (the serve mesh
# replica's dispatch loop).
_SANCTION_DECL = "GRAFTCHECK_SANCTIONED_COLLECTIVE_ENTRIES"

# where the real package lives (this file is pytorch_cifar_tpu/lint/...):
# the on-demand fallback root for imports of modules outside the linted set
_LINT_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_DP_MODULE = "pytorch_cifar_tpu.parallel.dp"


def qualname(node: ast.AST) -> Optional[str]:
    """Dotted name of a Name/Attribute chain ('jax.random.fold_in',
    'self._lock'); None for anything not a plain chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def walk_no_nested_funcs(node: ast.AST) -> List[ast.AST]:
    """``node``'s subtree without descending into nested function
    definitions (they are analyzed as their own traced/untraced units).

    The flattened list is memoized ON the node: a def is re-walked by
    a dozen rules per run, the tree is immutable for the run's
    lifetime, and the memo dies with the node — no cache to invalidate."""
    cached = getattr(node, "_graftcheck_wnnf", None)
    if cached is not None:
        return cached
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        out.append(child)
        if not isinstance(child, FuncNode + (ast.Lambda,)):
            stack.extend(ast.iter_child_nodes(child))
    node._graftcheck_wnnf = out
    return out


def parents_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    """child -> parent for every node under ``tree``, built once and
    memoized on the tree (three independent passes used to rebuild it
    per module: the graph, the lock analysis, and traced_functions)."""
    cached = getattr(tree, "_graftcheck_parents", None)
    if cached is None:
        cached = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                cached[child] = node
        tree._graftcheck_parents = cached
    return cached


class ModuleInfo:
    """One parsed module: name bindings + indexed function defs."""

    def __init__(self, name: str, path: str, tree: ast.Module):
        self.name = name          # dotted, graph-root-relative
        self.path = path          # absolute
        self.tree = tree
        is_init = os.path.basename(path) == "__init__.py"
        self.package = name if is_init else name.rpartition(".")[0]
        # local name -> (dotted module target, symbol | None for modules)
        self.import_bindings: Dict[str, Tuple[str, Optional[str]]] = {}
        self.raw_imports: Set[str] = set()   # every dotted import target
        self.aliases: Dict[str, str] = {}    # module-level `f = g` chains
        self.defs: Dict[str, ast.AST] = {}   # 'f' / 'Cls.m' / 'f.<locals>.g'
        self.key_of: Dict[int, str] = {}     # id(def node) -> key
        self.cls_of: Dict[int, Optional[str]] = {}  # id(def) -> class name
        self._index()

    # -- indexing ------------------------------------------------------

    def _index(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.raw_imports.add(alias.name)
                    if alias.asname:
                        self.import_bindings[alias.asname] = (
                            alias.name, None
                        )
                    else:
                        first = alias.name.split(".", 1)[0]
                        self.import_bindings.setdefault(first, (first, None))
            elif isinstance(node, ast.ImportFrom):
                target = self._from_target(node)
                if target is None:
                    continue
                self.raw_imports.add(target)
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    self.import_bindings[alias.asname or alias.name] = (
                        target, alias.name
                    )
        for stmt in self.tree.body:  # module-level simple aliases only
            if (
                isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, (ast.Name, ast.Attribute))
            ):
                vq = qualname(stmt.value)
                if vq is None:
                    continue
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        self.aliases[tgt.id] = vq

        def rec(owner: ast.AST, prefix: str, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(owner):
                if isinstance(child, FuncNode):
                    key = prefix + child.name
                    self.defs[key] = child
                    self.key_of[id(child)] = key
                    self.cls_of[id(child)] = cls
                    rec(child, key + ".<locals>.", cls)
                elif isinstance(child, ast.ClassDef):
                    rec(child, prefix + child.name + ".", child.name)
                else:
                    rec(child, prefix, cls)

        rec(self.tree, "", None)

    def _from_target(self, node: ast.ImportFrom) -> Optional[str]:
        if not node.level:
            return node.module
        base = self.package.split(".") if self.package else []
        drop = node.level - 1
        if drop > len(base):
            return None
        base = base[: len(base) - drop]
        if node.module:
            base.append(node.module)
        return ".".join(base) if base else None

    def top_level_def(self, name: str) -> Optional[ast.AST]:
        d = self.defs.get(name)
        return d if d is not None and "." not in name else d


class ProjectGraph:
    """The whole-tree pass. Built lazily by the engine's ``_Project`` the
    first time a rule asks; every analysis below is memoized."""

    def __init__(self, root: Optional[str], files: Sequence[str], loader):
        """``loader(path) -> (source, tree)`` is the shared AST cache
        (may raise OSError/SyntaxError — such files are skipped)."""
        self._loader = loader
        files = [os.path.abspath(f) for f in files]
        if root:
            self.root = os.path.abspath(root)
        elif files:
            common = os.path.commonpath(files)
            self.root = common if os.path.isdir(common) else (
                os.path.dirname(common)
            )
        else:
            self.root = os.getcwd()
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_path: Dict[str, ModuleInfo] = {}
        self._module_miss: Set[str] = set()
        self._analyzed = False
        for f in files:
            self._add_file(f)

    # -- module loading ------------------------------------------------

    def _module_name(self, path: str) -> str:
        try:
            rel = os.path.relpath(path, self.root)
        except ValueError:
            rel = os.path.basename(path)
        if rel.startswith(".."):
            rel = os.path.basename(path)
        name = rel[:-3] if rel.endswith(".py") else rel
        name = name.replace(os.sep, ".").replace("/", ".")
        if name.endswith(".__init__"):
            name = name[: -len(".__init__")]
        return name

    def _add_file(self, path: str) -> Optional[ModuleInfo]:
        path = os.path.abspath(path)
        if path in self.by_path:
            return self.by_path[path]
        try:
            _, tree = self._loader(path)
        except (OSError, SyntaxError, ValueError):
            return None
        info = ModuleInfo(self._module_name(path), path, tree)
        self.modules.setdefault(info.name, info)
        self.by_path[path] = info
        return info

    def module_for_target(
        self, dotted: str, external: bool = True
    ) -> Optional[ModuleInfo]:
        """The ModuleInfo a dotted import target refers to: exact graph
        key first, then a unique-suffix match, then (``external``) an
        on-demand load from the graph root or this lint package's repo."""
        if not dotted:
            return None
        m = self.modules.get(dotted)
        if m is not None:
            return m
        suffix = "." + dotted
        cands = [k for k in self.modules if k.endswith(suffix)]
        if len(cands) == 1:
            return self.modules[cands[0]]
        # the graph rooted BELOW the import's package (linting a subtree
        # or a fixture mini-package): 'pkg.util' resolves to module 'util'
        cands = [k for k in self.modules if dotted.endswith("." + k)]
        if len(cands) == 1:
            return self.modules[cands[0]]
        if not external or dotted in self._module_miss:
            return None
        relparts = dotted.split(".")
        for root in (self.root, _LINT_REPO_ROOT):
            base = os.path.join(root, *relparts)
            for cand in (base + ".py", os.path.join(base, "__init__.py")):
                if os.path.isfile(cand):
                    if cand in self.by_path:
                        return self.by_path[cand]
                    try:
                        _, tree = self._loader(cand)
                    except (OSError, SyntaxError, ValueError):
                        continue
                    info = ModuleInfo(dotted, cand, tree)
                    self.modules.setdefault(dotted, info)
                    self.by_path[cand] = info
                    return info
        self._module_miss.add(dotted)
        return None

    # -- name resolution -----------------------------------------------

    def resolve(
        self, m: ModuleInfo, qual: str, _depth: int = 0
    ) -> Optional[Tuple[ModuleInfo, str, ast.AST]]:
        """Resolve a dotted name as seen from module ``m`` to the
        function def it ultimately binds — following module-level
        aliases, import bindings, and re-export chains. Returns
        (defining module, top-level def key, def node) or None."""
        if _depth > 8 or not qual:
            return None
        head, _, rest = qual.partition(".")
        if head in m.aliases and m.aliases[head] != qual:
            target = m.aliases[head] + (("." + rest) if rest else "")
            return self.resolve(m, target, _depth + 1)
        if not rest:
            d = m.defs.get(head)
            if d is not None and "." not in head:
                return (m, head, d)
        if head in m.import_bindings:
            mod, sym = m.import_bindings[head]
            if sym is not None:
                if rest:  # attribute access on an imported function
                    return None
                m2 = self.module_for_target(mod)
                if m2 is None:
                    return None
                return self._resolve_symbol(m2, sym, _depth + 1)
            return self._resolve_in_module_path(mod, rest, _depth + 1)
        # plain dotted path that IS a module path (import a.b.c style)
        if rest:
            parts = qual.split(".")
            for cut in range(len(parts) - 1, 0, -1):
                if ".".join(parts[:cut]) in m.raw_imports:
                    return self._resolve_in_module_path(
                        ".".join(parts[:cut]),
                        ".".join(parts[cut:]),
                        _depth + 1,
                    )
        return None

    def _resolve_in_module_path(
        self, mod: str, rest: str, depth: int
    ) -> Optional[Tuple[ModuleInfo, str, ast.AST]]:
        if not rest:
            return None
        parts = rest.split(".")
        while len(parts) > 1:  # descend submodules: pkg.sub.f
            nxt = mod + "." + parts[0]
            if self.module_for_target(nxt) is None:
                break
            mod, parts = nxt, parts[1:]
        if len(parts) != 1:
            return None
        m2 = self.module_for_target(mod)
        if m2 is None:
            return None
        return self._resolve_symbol(m2, parts[0], depth)

    def _resolve_symbol(
        self, m: ModuleInfo, sym: str, depth: int
    ) -> Optional[Tuple[ModuleInfo, str, ast.AST]]:
        if depth > 8:
            return None
        d = m.defs.get(sym)
        if d is not None and "." not in sym:
            return (m, sym, d)
        if sym in m.aliases:
            return self.resolve(m, m.aliases[sym], depth + 1)
        if sym in m.import_bindings:  # re-export chain
            mod, s2 = m.import_bindings[sym]
            if s2 is None:
                return None
            m2 = self.module_for_target(mod)
            if m2 is None:
                return None
            return self._resolve_symbol(m2, s2, depth + 1)
        return None

    # -- donation wrappers ---------------------------------------------

    @staticmethod
    def _positions_from(node: ast.AST) -> Optional[Tuple[int, ...]]:
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return (node.value,)
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for e in node.elts:
                if not (
                    isinstance(e, ast.Constant) and isinstance(e.value, int)
                ):
                    return None
                out.append(e.value)
            return tuple(out)
        return None

    @classmethod
    def wrapper_info(cls, fdef: ast.AST) -> Optional[Tuple[Tuple[int, ...], Optional[str]]]:
        """(donated positions, gate-parameter name) when ``fdef`` builds a
        donating jit — i.e. its body contains ``jax.jit(...,
        donate_argnums=X)`` where X is a literal, or ``LIT if gate else
        ()`` with ``gate`` one of fdef's own parameters. This is how the
        dp.py donation table is DERIVED instead of hand-synced: change
        dp.py's donate_argnums and the rule follows automatically."""
        if not isinstance(fdef, FuncNode):
            return None
        params = {
            a.arg
            for a in (
                list(fdef.args.posonlyargs)
                + list(fdef.args.args)
                + list(fdef.args.kwonlyargs)
            )
        }
        for node in walk_no_nested_funcs(fdef):
            if not isinstance(node, ast.Call):
                continue
            if qualname(node.func) not in ("jax.jit", "jit"):
                continue
            for kw in node.keywords:
                if kw.arg != "donate_argnums":
                    continue
                v = kw.value
                gate = None
                pos = cls._positions_from(v)
                if pos is None and isinstance(v, ast.IfExp):
                    body = cls._positions_from(v.body)
                    orelse = cls._positions_from(v.orelse)
                    pos = body or orelse
                    tq = qualname(v.test)
                    if tq in params:
                        gate = tq
                if pos:
                    return (pos, gate)
        return None

    def _dp_name_table(self) -> Dict[str, Tuple[Tuple[int, ...], Optional[str]]]:
        """Fallback for unresolvable imports: the donating-wrapper table
        derived from the REAL dp.py's AST, keyed by def name. Name-keyed
        matching is the last resort (same reach as PR 6's hand table,
        minus the hand-sync); resolution through the import graph is what
        catches aliases and renames."""
        if getattr(self, "_dp_table", None) is None:
            self._dp_table = {}
            m = self.module_for_target(_DP_MODULE)
            if m is not None:
                for key, d in m.defs.items():
                    if "." in key:
                        continue
                    info = self.wrapper_info(d)
                    if info:
                        self._dp_table[key] = info
        return self._dp_table

    def resolve_donating_wrapper(
        self, path: str, qual: str
    ) -> Optional[Tuple[Tuple[int, ...], Optional[str]]]:
        """Donation info for a call to ``qual`` as written in the module
        at ``path``: (positions, gate param) or None."""
        m = self.by_path.get(os.path.abspath(path))
        if m is not None:
            r = self.resolve(m, qual)
            if r is not None:
                info = self.wrapper_info(r[2])
                if info:
                    return info
        return self._dp_name_table().get(qual.rsplit(".", 1)[-1])

    # -- whole-tree analyses (traced seeds, call graph, threads) --------

    def _analyze(self) -> None:
        if self._analyzed:
            return
        self._analyzed = True
        self._traced_seeds: Dict[str, Set[ast.AST]] = {}
        self._edges: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        self._node_of: Dict[Tuple[str, str], ast.AST] = {}
        self._thread_entries: List[Tuple[str, str, str]] = []
        self._loop_entries: List[Tuple[str, str, str]] = []
        self._sanctioned: Dict[Tuple[str, str], str] = {}
        self._sanction_issues: Dict[str, List[Tuple[ast.AST, str]]] = {}
        self._tracer_wrapper_cache: Dict[int, bool] = {}
        # snapshot: resolution may fault in external modules mid-loop
        for m in list(self.by_path.values()):
            self._analyze_module(m)

    def _is_tracer_wrapper(self, fdef: ast.AST) -> bool:
        """True when ``fdef`` passes one of its OWN parameters into a
        TRACER_CALL (the dp.py wrapper shape: ``shard_map(step_fn, ...)``)
        — calling it traces the callable you hand it."""
        cached = self._tracer_wrapper_cache.get(id(fdef))
        if cached is not None:
            return cached
        out = False
        if isinstance(fdef, FuncNode):
            params = {
                a.arg
                for a in (
                    list(fdef.args.posonlyargs)
                    + list(fdef.args.args)
                    + list(fdef.args.kwonlyargs)
                )
            }
            for node in walk_no_nested_funcs(fdef):
                if isinstance(node, ast.Call) and (
                    qualname(node.func) in TRACER_CALLS
                ):
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        if isinstance(arg, ast.Name) and arg.id in params:
                            out = True
        self._tracer_wrapper_cache[id(fdef)] = out
        return out

    @staticmethod
    def _returned_local_defs(m: ModuleInfo, fkey: str) -> List[ast.AST]:
        """Defs local to ``fkey`` that it returns (factory closures)."""
        fdef = m.defs.get(fkey)
        if fdef is None:
            return []
        out = []
        for node in walk_no_nested_funcs(fdef):
            if isinstance(node, ast.Return) and isinstance(
                node.value, ast.Name
            ):
                d = m.defs.get(f"{fkey}.<locals>.{node.value.id}")
                if d is not None:
                    out.append(d)
        return out

    def _enclosing_key(self, m: ModuleInfo, parents, node) -> Optional[str]:
        p = parents.get(node)
        while p is not None and not isinstance(p, FuncNode):
            p = parents.get(p)
        return m.key_of.get(id(p)) if p is not None else None

    def _local_def(self, m: ModuleInfo, scope_key: Optional[str], name: str):
        """The def ``name`` visible from inside ``scope_key``: nearest
        enclosing ``<locals>`` scope, else a top-level def."""
        key = scope_key
        while key:
            d = m.defs.get(f"{key}.<locals>.{name}")
            if d is not None:
                return d, f"{key}.<locals>.{name}"
            key = key.rpartition(".<locals>.")[0] if ".<locals>." in key else ""
        d = m.defs.get(name)
        if d is not None and "." not in name:
            return d, name
        return None, None

    def _resolve_callable(
        self, m: ModuleInfo, parents, call_node, func_expr
    ) -> Optional[Tuple[ModuleInfo, str, ast.AST]]:
        """Where a call/reference lands: self.method, lexically visible
        local def, module def, or an import-resolved def elsewhere."""
        q = qualname(func_expr)
        if q is None:
            return None
        scope_key = self._enclosing_key(m, parents, call_node)
        if q.startswith("self."):
            rest = q.split(".", 1)[1]
            if "." in rest:
                return None  # self.obj.method: type unknown
            scope = scope_key or ""
            cls = None
            d = m.defs.get(scope) if scope else None
            if d is not None:
                cls = m.cls_of.get(id(d))
            if cls:
                mk = f"{cls}.{rest}"
                md = m.defs.get(mk)
                if md is not None:
                    return (m, mk, md)
            return None
        if "." not in q:
            d, key = self._local_def(m, scope_key, q)
            if d is not None:
                return (m, key, d)
        return self.resolve(m, q)

    def _resolve_value(
        self, m: ModuleInfo, parents, at_node, expr, _depth=0
    ):
        """What a Name/Attribute ARGUMENT refers to, following simple
        function-local assignment chains: returns ('def', resolved) for a
        direct function reference or ('factory', resolved) when the value
        is the RESULT of calling a resolved function."""
        if _depth > 5 or not isinstance(expr, (ast.Name, ast.Attribute)):
            return None
        direct = self._resolve_callable(m, parents, at_node, expr)
        if direct is not None:
            return ("def", direct)
        if not isinstance(expr, ast.Name):
            return None
        # function-local `x = factory(...)` / `x = other_name`
        scope_key = self._enclosing_key(m, parents, at_node)
        scope = m.defs.get(scope_key) if scope_key else m.tree
        if scope is None:
            scope = m.tree
        for node in walk_no_nested_funcs(scope):
            if not isinstance(node, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == expr.id
                for t in node.targets
            ):
                continue
            v = node.value
            if isinstance(v, ast.Call):
                r = self._resolve_callable(m, parents, node, v.func)
                if r is not None:
                    return ("factory", r)
            elif isinstance(v, (ast.Name, ast.Attribute)):
                return self._resolve_value(
                    m, parents, node, v, _depth + 1
                )
        return None

    def _collect_sanctions(self, m: ModuleInfo) -> None:
        """Parse a module's _SANCTION_DECL (see its comment above):
        well-formed entries land in ``_sanctioned``; malformed ones —
        non-dict value, non-string key/reason, empty reason, a key
        naming no def in this module — become per-module issues the
        thread-collective rule reports as findings."""
        issues = self._sanction_issues.setdefault(m.path, [])
        for stmt in m.tree.body:
            if not (
                isinstance(stmt, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == _SANCTION_DECL
                    for t in stmt.targets
                )
            ):
                continue
            if not isinstance(stmt.value, ast.Dict):
                issues.append(
                    (stmt, f"{_SANCTION_DECL} must be a literal dict of "
                     "{'def name': 'reason'}")
                )
                continue
            for k, v in zip(stmt.value.keys, stmt.value.values):
                if not (
                    isinstance(k, ast.Constant) and isinstance(k.value, str)
                ):
                    issues.append(
                        (k or stmt, f"{_SANCTION_DECL} keys must be "
                         "string def names")
                    )
                    continue
                if k.value not in m.defs:
                    issues.append(
                        (k, f"{_SANCTION_DECL} names {k.value!r}, which "
                         f"this module does not define — stale after a "
                         f"rename? (the sanction would silently widen)")
                    )
                    continue
                if not (
                    isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                    and v.value.strip()
                ):
                    issues.append(
                        (v if v is not None else k,
                         f"{_SANCTION_DECL} entry {k.value!r} has no "
                         "reason — sanctioning a collective thread "
                         "entry requires stating WHY the lock-step "
                         "protocol makes it safe (same policy as noqa)")
                    )
                    continue
                self._sanctioned[(m.path, k.value)] = v.value

    def sanction_issues_for(self, path: str) -> List[Tuple[ast.AST, str]]:
        """Malformed/stale sanction declarations in ``path`` (findings
        for the thread-collective rule)."""
        self._analyze()
        return self._sanction_issues.get(os.path.abspath(path), [])

    def _analyze_module(self, m: ModuleInfo) -> None:
        self._collect_sanctions(m)
        parents = parents_map(m.tree)
        # event-loop callback entries (blocking-in-event-loop): in a
        # module that imports ``selectors``, any function passed as the
        # data argument of ``<selector>.register(fileobj, events, cb)``
        # or ``.modify(...)`` is dispatched from the loop thread — the
        # repo's loop convention (serve/edge.py) registers the callback
        # AS the key data precisely so this resolution is static
        imports_selectors = any(
            (isinstance(n, ast.Import)
             and any(a.name == "selectors" for a in n.names))
            or (isinstance(n, ast.ImportFrom) and n.module == "selectors")
            for n in ast.walk(m.tree)
        )
        if imports_selectors:
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.Call):
                    continue
                q = qualname(node.func)
                if q is None or q.rsplit(".", 1)[-1] not in (
                    "register", "modify",
                ) or "." not in q:
                    continue
                data_arg = None
                if len(node.args) >= 3:
                    data_arg = node.args[2]
                for kw in node.keywords:
                    if kw.arg == "data":
                        data_arg = kw.value
                if data_arg is None:
                    continue
                r = self._resolve_value(m, parents, node, data_arg)
                if r is not None and r[0] == "def":
                    m2, k2, d2 = r[1]
                    self._node_of[(m2.path, k2)] = d2
                    self._loop_entries.append(
                        (m2.path, k2, f"{m.name}:{k2}")
                    )
        # call-graph edges + thread entries + external-trace seeds
        for key, d in m.defs.items():
            nk = (m.path, key)
            self._node_of[nk] = d
            edges = self._edges.setdefault(nk, set())
            for node in walk_no_nested_funcs(d):
                if not isinstance(node, ast.Call):
                    continue
                r = self._resolve_callable(m, parents, node, node.func)
                if r is not None:
                    m2, k2, d2 = r
                    self._node_of[(m2.path, k2)] = d2
                    edges.add((m2.path, k2))
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            q = qualname(node.func)
            if q in _THREAD_CTORS:
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    r = self._resolve_value(m, parents, node, kw.value)
                    if r is not None and r[0] == "def":
                        m2, k2, d2 = r[1]
                        self._node_of[(m2.path, k2)] = d2
                        self._thread_entries.append(
                            (m2.path, k2, f"{m.name}:{k2}")
                        )
                continue
            # tracer call (jax.jit/scan/... or a resolved tracer wrapper
            # like the dp jits): its callable arguments are traced, even
            # when they live in another module
            is_tracer = q in TRACER_CALLS
            if not is_tracer and q is not None:
                r = self._resolve_callable(m, parents, node, node.func)
                if r is not None and self._is_tracer_wrapper(r[2]):
                    is_tracer = True
            if not is_tracer:
                continue
            for arg in list(node.args) + [
                kw.value for kw in node.keywords
            ]:
                rv = self._resolve_value(m, parents, node, arg)
                if rv is None:
                    continue
                kind, (m2, k2, d2) = rv
                if kind == "def":
                    if isinstance(d2, FuncNode):
                        self._traced_seeds.setdefault(
                            m2.path, set()
                        ).add(d2)
                else:  # factory result: its returned closures trace
                    for inner in self._returned_local_defs(m2, k2):
                        self._traced_seeds.setdefault(
                            m2.path, set()
                        ).add(inner)

    def _closure(self, seeds: Set[Tuple[str, str]]) -> Set[Tuple[str, str]]:
        self._analyze()
        seen = set(seeds)
        work = list(seeds)
        while work:
            nk = work.pop()
            for nxt in self._edges.get(nk, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    work.append(nxt)
        return seen

    # -- rule-facing API -----------------------------------------------

    def traced_seeds_for(self, path: str) -> Set[ast.AST]:
        """Defs in the module at ``path`` that some OTHER call site (any
        module) hands to a tracer — union these into the per-module
        traced_functions fixpoint."""
        self._analyze()
        return self._traced_seeds.get(os.path.abspath(path), set())

    def hot_def_nodes(self, path: str) -> Set[ast.AST]:
        """Defs in ``path`` on a hot path: reachable from the trainer
        step loop / engine dispatch / batcher worker seeds (HOT_SEEDS)
        through the cross-module call graph."""
        self._analyze()
        if getattr(self, "_hot", None) is None:
            seeds = set()
            for m in list(self.by_path.values()):
                p = m.path.replace(os.sep, "/")
                for suffix, names in HOT_SEEDS:
                    if not p.endswith(suffix):
                        continue
                    for key, d in m.defs.items():
                        if key.split(".")[-1] in names:
                            seeds.add((m.path, key))
            self._hot = self._closure(seeds)
        ap = os.path.abspath(path)
        return {
            self._node_of[nk] for nk in self._hot
            if nk[0] == ap and nk in self._node_of
        }

    def thread_reachable_for(self, path: str) -> Dict[ast.AST, str]:
        """{def node in ``path``: thread-entry label} for every def
        reachable from a ``Thread(target=...)`` entry anywhere in the
        linted tree. Entries declared in a module's _SANCTION_DECL are
        excluded from the seeds — their closures are sanctioned — but a
        def also reachable from an UNDECLARED thread entry still
        appears (under-approximation never widens: the sanction removes
        one entry's taint, not a shared helper's)."""
        self._analyze()
        if getattr(self, "_thread_reach", None) is None:
            reach: Dict[Tuple[str, str], str] = {}
            for epath, ekey, label in self._thread_entries:
                if (epath, ekey) in self._sanctioned:
                    continue
                for nk in self._closure({(epath, ekey)}):
                    reach.setdefault(nk, label)
            self._thread_reach = reach
        ap = os.path.abspath(path)
        return {
            self._node_of[nk]: label
            for nk, label in self._thread_reach.items()
            if nk[0] == ap and nk in self._node_of
        }

    def loop_callback_reachable_for(self, path: str) -> Dict[ast.AST, str]:
        """{def node in ``path``: loop-entry label} for every def
        reachable from a selectors-callback registration anywhere in the
        linted tree (the ``register``/``modify`` data argument — see
        ``_analyze_module``). The blocking-in-event-loop rule flags
        unbounded blocking calls inside these defs: one stalled callback
        stalls EVERY connection the loop holds."""
        self._analyze()
        if getattr(self, "_loop_reach", None) is None:
            reach: Dict[Tuple[str, str], str] = {}
            for epath, ekey, label in self._loop_entries:
                for nk in self._closure({(epath, ekey)}):
                    reach.setdefault(nk, label)
            self._loop_reach = reach
        ap = os.path.abspath(path)
        return {
            self._node_of[nk]: label
            for nk, label in self._loop_reach.items()
            if nk[0] == ap and nk in self._node_of
        }

    # -- lock-effect analysis (lint/locks.py) ---------------------------

    def locks(self):
        """The whole-run lock-effect pass (held-set propagation, the
        lock-order graph, blocking/cond/leak findings) — built lazily on
        first use by a concurrency rule, memoized for the run."""
        if getattr(self, "_locks", None) is None:
            from pytorch_cifar_tpu.lint.locks import LockAnalysis

            self._locks = LockAnalysis(self)
        return self._locks

    # -- exception-flow analysis (lint/exceptions.py) -------------------

    def exceptions(self):
        """The whole-run exception-flow pass (may-raise fixpoint,
        unmapped-edge-exception + raise-before-cleanup findings) —
        built lazily on first use by an exception rule, memoized."""
        if getattr(self, "_exceptions", None) is None:
            from pytorch_cifar_tpu.lint.exceptions import ExceptionFlow

            self._exceptions = ExceptionFlow(self)
        return self._exceptions

    # -- fd/socket lifecycle analysis (lint/fdlife.py) ------------------

    def fds(self):
        """The whole-run fd-lifecycle pass (socket/pipe/open/selector
        escape analysis) — built lazily on first use, memoized."""
        if getattr(self, "_fds", None) is None:
            from pytorch_cifar_tpu.lint.fdlife import FdAnalysis

            self._fds = FdAnalysis(self)
        return self._fds

    # -- import graph (CLI: --graph, graph-aware --changed) -------------

    def _import_edges(self) -> Dict[str, Set[str]]:
        """module name -> imported module names, restricted to modules in
        the linted set (external deps like jax are not edges)."""
        if getattr(self, "_imports", None) is None:
            linted = {m.path for m in self.by_path.values()}
            out: Dict[str, Set[str]] = {}
            for m in list(self.by_path.values()):
                deps: Set[str] = set()
                for target in sorted(m.raw_imports):
                    t = self.module_for_target(target, external=False)
                    if t is None:
                        # `from pkg.mod import f` resolved as pkg/__init__?
                        # also try the parent package for dotted targets
                        t = self.module_for_target(
                            target.rpartition(".")[0], external=False
                        )
                    if t is not None and t.path in linted and (
                        t.path != m.path
                    ):
                        deps.add(t.name)
                # a `from pkg import name` binding may reach THROUGH the
                # package __init__ into a submodule: count the submodule
                for mod, sym in m.import_bindings.values():
                    if sym is None:
                        continue
                    r = self._resolve_symbol_module(mod, sym)
                    if r is not None and r.path in linted and (
                        r.path != m.path
                    ):
                        deps.add(r.name)
                out[m.name] = deps
            self._imports = out
        return self._imports

    def _resolve_symbol_module(
        self, mod: str, sym: str
    ) -> Optional[ModuleInfo]:
        m2 = self.module_for_target(mod, external=False)
        if m2 is None:
            return None
        r = self._resolve_symbol(m2, sym, 0)
        return r[0] if r is not None else m2

    def to_json(self) -> dict:
        edges = self._import_edges()
        mods = {}
        for name in sorted(edges):
            m = self.modules.get(name)
            if m is None:
                continue
            try:
                rel = os.path.relpath(m.path, self.root)
            except ValueError:
                rel = m.path
            mods[name] = {
                "path": rel.replace(os.sep, "/"),
                "imports": sorted(edges[name]),
            }
        return {"version": 1, "root": self.root, "modules": mods}

    def reverse_dependents(self, changed_paths: Sequence[str]) -> List[str]:
        """Paths of linted modules whose import closure reaches any of
        ``changed_paths`` — the files a change can break at a distance
        (what ``--changed`` must re-lint along with the change itself)."""
        changed = {os.path.abspath(p) for p in changed_paths}
        changed_names = {
            m.name for m in self.by_path.values() if m.path in changed
        }
        if not changed_names:
            return []
        edges = self._import_edges()
        # reverse closure: importer -> ... -> changed
        rev: Dict[str, Set[str]] = {}
        for src, deps in edges.items():
            for dep in deps:
                rev.setdefault(dep, set()).add(src)
        hit: Set[str] = set()
        work = list(changed_names)
        while work:
            name = work.pop()
            for importer in rev.get(name, ()):
                if importer not in hit and importer not in changed_names:
                    hit.add(importer)
                    work.append(importer)
        return sorted(
            self.modules[n].path for n in hit if n in self.modules
        )
