"""graftcheck — a JAX-aware static-analysis pass for this repo.

Four PRs in, every hard bug in this codebase has been an *invariant
violation no unit test caught until runtime*: the seed suite hard-aborting
on unprobed XLA flags, the persistent compile cache mis-executing donated
buffers, gloo aborting on variable-size broadcasts, trace-time-only side
effects. Production stacks encode such invariants in a custom lint layer
so regressions are caught at review time; this package is that layer.

- :mod:`pytorch_cifar_tpu.lint.engine` — the rule runner: file walking,
  inline suppressions (``# graftcheck: noqa[rule] -- reason``), baseline
  matching, JSON/human output, the shared one-parse-per-file AST cache.
- :mod:`pytorch_cifar_tpu.lint.project` — the whole-project pass: import
  graph, cross-module call graph, reachability views (hot paths, thread
  entries, externally-traced closures), and the dp.py donation table
  derived from dp.py's own AST.
- :mod:`pytorch_cifar_tpu.lint.locks` — the lock-effect analysis riding
  that call graph: per-function held-lock sets, whole-project held-set
  propagation, and the lock-order graph behind the concurrency-protocol
  rules (lock-order-inversion, blocking-under-lock,
  cond-wait-discipline, lock-leak).
- :mod:`pytorch_cifar_tpu.lint.rules` — the rules themselves, each
  grounded in a failure mode this repo has actually hit (the catalog with
  one real-world example per rule is STATIC_ANALYSIS.md).

CLI: ``python tools/lint.py`` (``--changed`` for the pre-commit inner
loop). Tier-1 enforcement: tests/test_lint.py runs the full engine over
``pytorch_cifar_tpu/`` and asserts zero unsuppressed findings.
"""

from pytorch_cifar_tpu.lint.engine import (  # noqa: F401
    BaselineError,
    Finding,
    LintRun,
    collect_python_files,
    lint_file,
    lint_paths,
    load_baseline,
    match_baseline,
    write_baseline,
)
from pytorch_cifar_tpu.lint.project import ProjectGraph  # noqa: F401
from pytorch_cifar_tpu.lint.rules import RULES, rule_names  # noqa: F401
