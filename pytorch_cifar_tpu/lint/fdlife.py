"""graftcheck fd/socket lifecycle analysis (rule 22).

Rule 17 ``subprocess-lifecycle`` proved the shape: an acquired handle
must be released on some path, be scope-managed, or be handed to an
owner that releases it — everything else is a leak the process pays
for later. PR 16 paid it with a socket: the ``Connection: close``
path dropped an accepted connection without closing it, and the edge
bled one fd per shed client. This module generalizes the escape
analysis from ``Popen`` to every fd-holding acquisition the serve
stack uses:

- ``socket.socket(...)`` / ``socket.create_connection(...)`` and the
  ``conn, addr = sock.accept()`` unpack (in socket-importing modules);
- ``os.pipe()`` (both ends tracked through the tuple unpack) and
  ``os.open(...)``, released by ``os.close(fd)``;
- builtin ``open(...)`` bound by plain assignment (``with open(...)
  as f`` is scope-managed and never tracked);
- ``selectors.DefaultSelector()`` — plus a module-coarse registration
  check: a module that ``register``\\ s fileobjs on a selector it owns
  must somewhere ``unregister`` or ``close`` that selector.

Discharge mirrors rule 17 exactly:

- **function-local**: ``x.close()``/``x.detach()``/``os.close(x)`` in
  the same function, or escape to an owner (passed as a call argument,
  returned, stored on ``self.X``/``obj.attr``/a container);
- **class-attr**: ``self.X = <ctor>`` must be closed by SOME method —
  directly, through a ``p = self._sock; p.close()`` alias (the idiom
  the thread-join rule already handles), or through the
  ``for fd in (self._wake_r, self._wake_w): os.close(fd)`` loop the
  edge's wake-pipe teardown uses;
- **fire-and-forget**: an acquisition whose handle is dropped on the
  floor (a bare expression statement) can never be closed.

Flow-insensitive by design: ONE closing site anywhere discharges the
obligation, so a path that skips it is invisible here — that half of
the problem belongs to rule 21 ``raise-before-cleanup``. Pure stdlib
``ast``; linted code is never imported.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from pytorch_cifar_tpu.lint.project import (
    FuncNode,
    ModuleInfo,
    qualname,
    walk_no_nested_funcs,
)

_CLOSE_ATTRS = frozenset({"close", "detach"})


def _ctor_kind(call: ast.AST, socket_mod: bool) -> Optional[str]:
    """What fd-holding resource a call acquires: 'socket' / 'pipe' /
    'fd' / 'file' / 'selector' / 'accept', or None."""
    if not isinstance(call, ast.Call):
        return None
    q = qualname(call.func)
    if q is None:
        return None
    if q in ("socket.socket", "socket.create_connection"):
        return "socket"
    if q == "os.pipe":
        return "pipe"
    if q == "os.open":
        return "fd"
    if q == "open":
        return "file"
    if q == "selectors.DefaultSelector" or q.endswith(".DefaultSelector"):
        return "selector"
    if socket_mod and q.endswith(".accept") and "." in q:
        return "accept"
    return None


class FdAnalysis:
    """The whole-run fd-lifecycle pass. Built lazily by
    ``ProjectGraph.fds()`` on first use, memoized per module."""

    def __init__(self, graph):
        self.graph = graph
        self._cache: Dict[str, List[Tuple[int, int, str]]] = {}
        self._sites: Dict[str, List[Tuple[int, str, str]]] = {}

    def _module(self, path: str) -> Optional[ModuleInfo]:
        return self.graph.by_path.get(os.path.abspath(path))

    @staticmethod
    def _imports_socket(m: ModuleInfo) -> bool:
        return "socket" in m.raw_imports

    def findings_for(self, path: str) -> List[Tuple[int, int, str]]:
        ap = os.path.abspath(path)
        if ap not in self._cache:
            self._analyze_path(ap)
        return self._cache.get(ap, [])

    def tracked_sites(self, path: str) -> List[Tuple[int, str, str]]:
        """(line, kind, owner) for every acquisition this pass tracked
        in ``path`` — the non-vacuity pin for the self-run tests."""
        ap = os.path.abspath(path)
        if ap not in self._cache:
            self._analyze_path(ap)
        return self._sites.get(ap, [])

    def _analyze_path(self, ap: str) -> None:
        out: List[Tuple[int, int, str]] = []
        sites: List[Tuple[int, str, str]] = []
        self._cache[ap] = out
        self._sites[ap] = sites
        m = self._module(ap)
        if m is None:
            return
        socket_mod = self._imports_socket(m)
        for node in ast.walk(m.tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(m, node, socket_mod, out, sites)
            elif isinstance(node, FuncNode):
                self._check_local(m, node, socket_mod, out, sites)
            elif isinstance(node, ast.Expr):
                kind = _ctor_kind(node.value, socket_mod)
                if kind is not None and kind != "accept":
                    out.append((
                        node.value.lineno, node.value.col_offset,
                        f"{kind} acquired and dropped on the floor — "
                        f"nothing holds the handle, so nothing can "
                        f"ever close it",
                    ))
        self._check_selector_registration(m, out)

    # -- class-attr obligations ---------------------------------------

    def _check_class(self, m, cls, socket_mod, out, sites) -> None:
        fd_attrs: Dict[str, Tuple[ast.AST, str]] = {}  # attr -> (ctor, kind)
        handled: Set[str] = set()
        for meth in (n for n in cls.body if isinstance(n, FuncNode)):
            local_fds: Set[str] = set()
            attr_alias: Dict[str, str] = {}        # local -> self attr
            loop_alias: Dict[str, Set[str]] = {}   # loop var -> attrs
            for node in ast.walk(meth):
                if isinstance(node, ast.Assign):
                    kind = _ctor_kind(node.value, socket_mod)
                    if kind is not None:
                        for tgt in node.targets:
                            self._track_targets(
                                tgt, kind, node.value, fd_attrs,
                                local_fds,
                            )
                        continue
                    vq = qualname(node.value)
                    for tgt in node.targets:
                        tq = qualname(tgt)
                        if isinstance(tgt, ast.Name):
                            if vq and vq.startswith("self."):
                                attr_alias[tgt.id] = vq.split(".", 1)[1]
                        elif tq and tq.startswith("self.") and (
                            isinstance(node.value, ast.Name)
                            and node.value.id in local_fds
                        ):
                            # s = socket.socket(); ...; self._sock = s
                            fd_attrs.setdefault(
                                tq.split(".", 1)[1],
                                (node.value, "socket"),
                            )
                elif isinstance(node, ast.For) and isinstance(
                    node.target, ast.Name
                ) and isinstance(node.iter, (ast.Tuple, ast.List)):
                    # for fd in (self._wake_r, self._wake_w): ...
                    attrs = set()
                    for e in node.iter.elts:
                        eq = qualname(e)
                        if eq and eq.startswith("self."):
                            attrs.add(eq.split(".", 1)[1])
                    if attrs:
                        loop_alias[node.target.id] = attrs
                if isinstance(node, ast.Call):
                    self._note_close(
                        node, handled, attr_alias, loop_alias
                    )
        for attr, (ctor, kind) in fd_attrs.items():
            sites.append((ctor.lineno, kind, f"{cls.name}.self.{attr}"))
            if attr in handled:
                continue
            out.append((
                ctor.lineno, ctor.col_offset,
                f"{cls.name} stores a {kind} on self.{attr} but no "
                f"method ever closes it — the fd outlives its owner "
                f"(the PR 16 leaked-socket shape); close it on every "
                f"teardown path",
            ))

    @staticmethod
    def _track_targets(tgt, kind, ctor, fd_attrs, local_fds) -> None:
        """Route a ctor's assignment targets: ``self.X`` becomes a
        class obligation, a plain name a local one; ``os.pipe()`` and
        ``accept()`` unpacks track each element (the accepted socket
        is element 0, but closing EITHER element of a pipe pair is not
        enough, so both are tracked)."""
        if isinstance(tgt, (ast.Tuple, ast.List)):
            elts = tgt.elts
            if kind == "accept":
                elts = elts[:1]  # (conn, addr): only conn holds an fd
            for e in elts:
                FdAnalysis._track_targets(
                    e, kind, ctor, fd_attrs, local_fds
                )
            return
        tq = qualname(tgt)
        if tq and tq.startswith("self.") and tq.count(".") == 1:
            fd_attrs.setdefault(tq.split(".", 1)[1], (ctor, kind))
        elif isinstance(tgt, ast.Name):
            local_fds.add(tgt.id)

    @staticmethod
    def _note_close(node, handled, attr_alias, loop_alias) -> None:
        if isinstance(node.func, ast.Attribute) and (
            node.func.attr in _CLOSE_ATTRS
        ):
            rq = qualname(node.func.value)
            if rq and rq.startswith("self."):
                handled.add(rq.split(".", 1)[1])
            elif isinstance(node.func.value, ast.Name):
                a = attr_alias.get(node.func.value.id)
                if a is not None:
                    handled.add(a)
        if qualname(node.func) == "os.close" and node.args:
            arg = node.args[0]
            aq = qualname(arg)
            if aq and aq.startswith("self."):
                handled.add(aq.split(".", 1)[1])
            elif isinstance(arg, ast.Name):
                handled.update(loop_alias.get(arg.id, ()))
                a = attr_alias.get(arg.id)
                if a is not None:
                    handled.add(a)

    # -- function-local obligations -----------------------------------

    def _check_local(self, m, fn, socket_mod, out, sites) -> None:
        local: Dict[str, Tuple[ast.AST, str]] = {}
        escaped: Set[str] = set()
        handled: Set[str] = set()
        for node in walk_no_nested_funcs(fn):
            if isinstance(node, ast.Assign):
                kind = _ctor_kind(node.value, socket_mod)
                if kind is not None:
                    for tgt in node.targets:
                        self._track_local_targets(
                            tgt, kind, node.value, local
                        )
                    continue
                if isinstance(node.value, ast.Name):
                    for tgt in node.targets:
                        tq = qualname(tgt)
                        if (tq and "." in tq) or isinstance(
                            tgt, ast.Subscript
                        ):
                            # self.X = s / obj.attr = s / conns[fd] = s:
                            # ownership transferred
                            escaped.add(node.value.id)
            elif isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) and isinstance(
                    node.func.value, ast.Name
                ) and node.func.attr in _CLOSE_ATTRS:
                    handled.add(node.func.value.id)
                if qualname(node.func) == "os.close" and node.args:
                    if isinstance(node.args[0], ast.Name):
                        handled.add(node.args[0].id)
                # passed elsewhere (an owner takes it): escapes
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if isinstance(arg, ast.Name):
                        escaped.add(arg.id)
            elif isinstance(node, ast.Return) and isinstance(
                node.value, ast.Name
            ):
                escaped.add(node.value.id)
        for name, (ctor, kind) in local.items():
            sites.append((ctor.lineno, kind, f"{fn.name}:{name}"))
            if name in handled or name in escaped:
                continue
            out.append((
                ctor.lineno, ctor.col_offset,
                f"local {kind} {name!r} in {fn.name!r} is never "
                f"closed in this function and never handed to an "
                f"owner — the fd leaks past every exit path (the "
                f"PR 16 leaked-socket shape); use `with`, close it, "
                f"or store it on an owner that does",
            ))

    @staticmethod
    def _track_local_targets(tgt, kind, ctor, local) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            elts = tgt.elts[:1] if kind == "accept" else tgt.elts
            for e in elts:
                FdAnalysis._track_local_targets(e, kind, ctor, local)
            return
        if isinstance(tgt, ast.Name):
            local[tgt.id] = (ctor, kind)
        # self.X / container targets are ownership transfers; the
        # class pass picks up self.X obligations

    # -- selector registration (module-coarse) ------------------------

    def _check_selector_registration(self, m, out) -> None:
        """A module that registers fileobjs on a selector it OWNS must
        somewhere unregister them or close the selector (closing the
        selector releases every registration at once — the teardown
        idiom serve/edge.py uses)."""
        sel_names: Set[str] = set()  # 'sel' or 'self._sel' qualnames
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Assign):
                continue
            if _ctor_kind(node.value, False) != "selector":
                continue
            for tgt in node.targets:
                tq = qualname(tgt)
                if tq:
                    sel_names.add(tq)
        if not sel_names:
            return
        # normalize: 'self._sel' and '_sel'-on-an-alias both count by
        # their last segment, so a `sel = self._sel` alias still hits
        last = {q.rsplit(".", 1)[-1] for q in sel_names}
        first_register = None
        released = False
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Attribute
            ):
                continue
            rq = qualname(node.func.value)
            if rq is None or rq.rsplit(".", 1)[-1] not in last:
                continue
            if node.func.attr == "register":
                if first_register is None:
                    first_register = node
            elif node.func.attr in ("unregister", "close"):
                released = True
        if first_register is not None and not released:
            out.append((
                first_register.lineno, first_register.col_offset,
                "this module registers fileobjs on a selector it owns "
                "but never unregisters them or closes the selector — "
                "every registration (and its fd reference) leaks at "
                "teardown; close the selector on the loop's exit path",
            ))
