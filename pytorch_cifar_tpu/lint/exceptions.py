"""graftcheck exception-flow analysis (rules 20-21).

The serve stack's three worst recent bugs were exception-escape shapes
no AST-local rule can see: PR 16's load-shed 429 left the edge parser
mid-state, so the NEXT keep-alive request crashed the loop callback
with an unmapped ``TypeError`` (the client saw a silent hang, not a
status code); PR 17's drain path raised ``BrokenPipeError`` from a
stderr ``print`` BEFORE ``frontend.stop()``, hanging shutdown for 62s.
Both are *flow* facts: which exceptions can reach which frames, and
what stands between a raise and the cleanup it skips.

This module computes, per function def across the whole linted tree:

- **may-raise sets** — exception class names from explicit ``raise``
  sites plus callee propagation over the PR 8 cross-module call graph,
  filtered at every level through the enclosing ``try`` context
  (``except``-clause narrowing, handler subsumption resolved against
  the AST class hierarchy: repo-defined exceptions like ``QueueFull``/
  ``UnknownModel``/``DeadlineExceeded`` AND the stdlib builtin
  hierarchy). A handler whose body re-raises (bare ``raise`` or
  ``raise e`` of its own asname) is *transparent* — it narrates, it
  does not discharge.

Two rule providers ride the fixpoint:

- ``edge_findings_for`` (rule 20 ``unmapped-edge-exception``): an
  exception that can escape a frontend/edge *dispatch entry* — a
  selectors loop callback or a ``do_GET``/``do_POST`` handler in
  ``serve/frontend.py``/``serve/edge.py`` — with no status-code
  mapping anywhere in the handler chain. The loop's dispatch-site
  ``except Exception: log.exception(...)`` is a crash logger, not a
  mapping: the request gets no response and the connection wedges
  (exactly the PR 16 ``_feed_body`` TypeError). The ``OSError``
  family is excluded — socket errors are the loop's normal weather,
  handled by dropping the connection.
- ``cleanup_findings_for`` (rule 21 ``raise-before-cleanup``): on a
  stop/close/drain-shaped path, a may-raise CALL positioned before a
  resource-releasing call with no shared try/finally — the raise
  skips the release (the PR 17 ``print`` → ``BrokenPipeError`` →
  ``frontend.stop()`` never runs shape). ``print(..., file=...)`` is
  modeled as raising ``OSError`` (a dead stderr pipe raises
  ``BrokenPipeError`` mid-drain); guard ``raise`` statements written
  directly in the cleanup def itself are sanctioned idiom and do not
  count.

Under-approximation is deliberate (STATIC_ANALYSIS.md "Known limits"):
dynamic dispatch through non-``self`` receivers contributes nothing,
and C-level raises (``int()``, ``dict[...]``, struct unpacks) are not
modeled — the only builtin raiser in the table is ``print`` with a
``file=`` argument. Pure stdlib ``ast``; linted code is never imported.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from pytorch_cifar_tpu.lint.project import (
    FuncNode,
    ModuleInfo,
    qualname,
)

# stdlib exception hierarchy (simple name -> direct bases), enough to
# resolve handler subsumption for every exception this repo raises or
# catches. Repo-defined classes are layered on top from their ClassDefs.
_BUILTIN_BASES: Dict[str, Tuple[str, ...]] = {
    "BaseException": (),
    "Exception": ("BaseException",),
    "SystemExit": ("BaseException",),
    "KeyboardInterrupt": ("BaseException",),
    "GeneratorExit": ("BaseException",),
    "ArithmeticError": ("Exception",),
    "ZeroDivisionError": ("ArithmeticError",),
    "OverflowError": ("ArithmeticError",),
    "FloatingPointError": ("ArithmeticError",),
    "AssertionError": ("Exception",),
    "AttributeError": ("Exception",),
    "BufferError": ("Exception",),
    "EOFError": ("Exception",),
    "ImportError": ("Exception",),
    "ModuleNotFoundError": ("ImportError",),
    "LookupError": ("Exception",),
    "IndexError": ("LookupError",),
    "KeyError": ("LookupError",),
    "MemoryError": ("Exception",),
    "NameError": ("Exception",),
    "UnboundLocalError": ("NameError",),
    "OSError": ("Exception",),
    "IOError": ("OSError",),
    "BlockingIOError": ("OSError",),
    "ChildProcessError": ("OSError",),
    "ConnectionError": ("OSError",),
    "BrokenPipeError": ("ConnectionError",),
    "ConnectionAbortedError": ("ConnectionError",),
    "ConnectionRefusedError": ("ConnectionError",),
    "ConnectionResetError": ("ConnectionError",),
    "FileExistsError": ("OSError",),
    "FileNotFoundError": ("OSError",),
    "InterruptedError": ("OSError",),
    "IsADirectoryError": ("OSError",),
    "NotADirectoryError": ("OSError",),
    "PermissionError": ("OSError",),
    "ProcessLookupError": ("OSError",),
    "TimeoutError": ("OSError",),
    "ReferenceError": ("Exception",),
    "RuntimeError": ("Exception",),
    "NotImplementedError": ("RuntimeError",),
    "RecursionError": ("RuntimeError",),
    "StopIteration": ("Exception",),
    "StopAsyncIteration": ("Exception",),
    "SyntaxError": ("Exception",),
    "IndentationError": ("SyntaxError",),
    "SystemError": ("Exception",),
    "TypeError": ("Exception",),
    "ValueError": ("Exception",),
    "UnicodeError": ("ValueError",),
    "UnicodeDecodeError": ("UnicodeError",),
    "UnicodeEncodeError": ("UnicodeError",),
}

# rule 20: families an edge entry is ALLOWED to leak. OSError and kin
# mean the socket died — the loop's answer is dropping the connection,
# there is no client left to send a status code to. The BaseException-
# only family is control flow, not failure.
_EDGE_EXEMPT_ROOTS = frozenset({
    "OSError", "SystemExit", "KeyboardInterrupt", "GeneratorExit",
    "StopIteration",
})

# rule 21: attribute names whose call releases/retires a resource, and
# the def-name tokens that mark a cleanup-shaped path
_RELEASE_ATTRS = frozenset({
    "stop", "close", "shutdown", "join", "unregister", "terminate",
    "kill", "decommission", "disconnect",
})
_CLEANUP_TOKENS = frozenset({
    "stop", "close", "drain", "shutdown", "teardown", "finish",
    "cleanup", "exit", "quit",
})
_CLEANUP_EXACT = frozenset({"__exit__", "__del__", "__aexit__"})

# ctx element: tuple of (handler type names, transparent?) per handler
_Handlers = Tuple[Tuple[Tuple[str, ...], bool], ...]
_NodeKey = Tuple[str, str]  # (abs path, def key)


def _exc_name(expr: Optional[ast.AST]) -> Optional[str]:
    """Simple class name of a raised/caught exception expression:
    ``raise QueueFull(...)`` / ``raise wire.WireError`` -> the last
    dotted segment; anything dynamic -> None."""
    if expr is None:
        return None
    if isinstance(expr, ast.Call):
        expr = expr.func
    q = qualname(expr)
    if q is None:
        return None
    return q.rsplit(".", 1)[-1]


class ExceptionFlow:
    """The whole-run may-raise fixpoint + the two rule providers.
    Built lazily by ``ProjectGraph.exceptions()`` on first use by an
    exception rule, memoized for the run."""

    def __init__(self, graph):
        self.graph = graph
        self._built = False

    # -- class hierarchy ----------------------------------------------

    def _build_hierarchy(self) -> None:
        self._bases: Dict[str, Tuple[str, ...]] = dict(_BUILTIN_BASES)
        for m in list(self.graph.by_path.values()):
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                bases = []
                for b in node.bases:
                    bq = qualname(b)
                    if bq:
                        bases.append(bq.rsplit(".", 1)[-1])
                if bases:
                    self._bases.setdefault(node.name, tuple(bases))

    def ancestors(self, name: str) -> Set[str]:
        """Transitive base-class names of ``name`` (simple names),
        including ``name`` itself; just {name} when unknown."""
        out: Set[str] = set()
        work = [name]
        while work:
            n = work.pop()
            if n in out:
                continue
            out.add(n)
            work.extend(self._bases.get(n, ()))
        return out

    def subsumes(self, handler: str, exc: str) -> bool:
        """Does ``except handler:`` catch an ``exc`` instance?"""
        if handler == "BaseException":
            return True
        anc = self.ancestors(exc)
        if handler == "Exception":
            # everything is an Exception unless it roots in the
            # BaseException-only family
            return not (
                {"SystemExit", "KeyboardInterrupt", "GeneratorExit"}
                & anc
            ) or "Exception" in anc
        return handler in anc

    # -- per-def skeletons --------------------------------------------

    @staticmethod
    def _handler_types(h: ast.ExceptHandler) -> Tuple[str, ...]:
        if h.type is None:
            return ("BaseException",)  # bare except
        if isinstance(h.type, ast.Tuple):
            names = [_exc_name(e) for e in h.type.elts]
            return tuple(n for n in names if n) or ("BaseException",)
        n = _exc_name(h.type)
        return (n,) if n else ("BaseException",)

    @staticmethod
    def _handler_transparent(h: ast.ExceptHandler) -> bool:
        """A handler that re-raises what it caught does not discharge:
        bare ``raise`` or ``raise e`` of its own asname anywhere in the
        handler body (nested defs excluded)."""
        stack = list(h.body)
        while stack:
            node = stack.pop()
            if isinstance(node, FuncNode + (ast.Lambda,)):
                continue
            if isinstance(node, ast.Raise):
                if node.exc is None:
                    return True
                if (
                    h.name
                    and isinstance(node.exc, ast.Name)
                    and node.exc.id == h.name
                ):
                    return True
            stack.extend(ast.iter_child_nodes(node))
        return False

    def _resolve_call(
        self, m: ModuleInfo, key: str, fdef, q: str
    ) -> Optional[_NodeKey]:
        """Where a call written as ``q`` inside def ``key`` lands.
        Calls inside nested defs are never collected here (they are
        their own analysis units), so the enclosing scope is always
        ``key`` itself — no parents map needed, and module-level
        resolution is cached per (module, qualname)."""
        if q.startswith("self."):
            rest = q.split(".", 1)[1]
            if "." in rest:
                return None  # self.obj.method: type unknown
            cls = m.cls_of.get(id(fdef))
            if cls:
                mk = f"{cls}.{rest}"
                if mk in m.defs:
                    return (m.path, mk)
            return None
        if "." not in q:
            d, k = self.graph._local_def(m, key, q)
            if d is not None:
                return (m.path, k)
        ck = (m.path, q)
        if ck in self._resolve_cache:
            return self._resolve_cache[ck]
        r = self.graph.resolve(m, q)
        out = (r[0].path, r[1]) if r is not None else None
        self._resolve_cache[ck] = out
        return out

    def _collect_def(self, m: ModuleInfo, key: str, fdef) -> None:
        """One recursive walk of ``fdef`` carrying the enclosing-try
        context: raise sites, call sites (resolved through the project
        graph), release calls, and try/finally coverage."""
        nk = (m.path, key)
        raises: List[Tuple[Tuple[str, ...], int, _Handlers]] = []
        calls: List[tuple] = []  # (line, col, callee nk|None, printf, ctx, fins)
        releases: List[tuple] = []  # (line, recv, attr, in_finals)

        def record_call(node: ast.Call, ctx, fins, in_finals) -> None:
            recv = None
            attr = None
            fq = qualname(node.func)
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                recv = qualname(node.func.value)
            is_release = (
                attr in _RELEASE_ATTRS
                and recv is not None
                # `os.path.join(...)` is string plumbing, not a thread
                # join — a path-ish receiver never releases anything
                and recv.rsplit(".", 1)[-1] not in ("path", "sep")
            ) or fq == "os.close"
            if is_release:
                releases.append(
                    (node.lineno, recv or "os", attr or "close", in_finals)
                )
            printf = False
            if (
                isinstance(node.func, ast.Name)
                and node.func.id == "print"
                and any(kw.arg == "file" for kw in node.keywords)
            ):
                printf = True
            callee = None
            if fq is not None:
                callee = self._resolve_call(m, key, fdef, fq)
            if printf or callee is not None:
                calls.append(
                    (node.lineno, node.col_offset, callee, printf,
                     ctx, fins, is_release)
                )

        def visit(node, ctx: _Handlers, fins, in_finals) -> None:
            if isinstance(node, FuncNode + (ast.Lambda,)):
                return  # nested defs are their own analysis units
            if isinstance(node, ast.Try):
                hinfo = tuple(
                    (self._handler_types(h), self._handler_transparent(h))
                    for h in node.handlers
                )
                inner = ctx + (hinfo,) if hinfo else ctx
                tfin = fins + ((id(node),) if node.finalbody else ())
                for s in node.body:
                    visit(s, inner, tfin, in_finals)
                for h in node.handlers:
                    # a raise inside a handler is NOT caught by its own
                    # try; the finally still covers it
                    for s in h.body:
                        visit(s, ctx, tfin, in_finals)
                for s in node.orelse:
                    # orelse runs after the body completed: the
                    # handlers no longer apply, the finally still does
                    visit(s, ctx, tfin, in_finals)
                for s in node.finalbody:
                    visit(s, ctx, fins, in_finals + (id(node),))
                return
            if isinstance(node, ast.Raise):
                n = _exc_name(node.exc)
                if n is not None:
                    raises.append(((n,), node.lineno, ctx))
                # bare raise: handled via handler transparency
            if isinstance(node, ast.Call):
                record_call(node, ctx, fins, in_finals)
            for child in ast.iter_child_nodes(node):
                visit(child, ctx, fins, in_finals)

        for stmt in ast.iter_child_nodes(fdef):
            visit(stmt, (), (), ())
        self._raises[nk] = raises
        self._calls[nk] = calls
        self._releases[nk] = releases

    # -- fixpoint ------------------------------------------------------

    def _survives(self, exc: str, ctx: _Handlers) -> bool:
        for handlers in ctx:
            for names, transparent in handlers:
                if transparent:
                    continue
                if any(self.subsumes(h, exc) for h in names):
                    return False
        return True

    def _ensure(self) -> None:
        if self._built:
            return
        self._built = True
        self.graph._analyze()
        self._build_hierarchy()
        self._raises = {}
        self._calls = {}
        self._releases = {}
        self._resolve_cache: Dict[Tuple[str, str], Optional[_NodeKey]] = {}
        for m in list(self.graph.by_path.values()):
            for key, d in m.defs.items():
                self._collect_def(m, key, d)
        # escaping-set fixpoint: exc name -> (origin path, key, line).
        # Monotone grow-only over a finite name set, so it terminates;
        # recursion cycles just stop adding.
        esc: Dict[_NodeKey, Dict[str, Tuple[str, str, int]]] = {
            nk: {} for nk in self._raises
        }
        changed = True
        while changed:
            changed = False
            for nk, raises in self._raises.items():
                cur = esc[nk]
                for names, line, ctx in raises:
                    for n in names:
                        if n not in cur and self._survives(n, ctx):
                            cur[n] = (nk[0], nk[1], line)
                            changed = True
                for line, _c, callee, printf, ctx, _f, _r in self._calls[nk]:
                    if printf and "OSError" not in cur and self._survives(
                        "OSError", ctx
                    ):
                        cur["OSError"] = (nk[0], nk[1], line)
                        changed = True
                    if callee is None:
                        continue
                    for n, origin in esc.get(callee, {}).items():
                        if n not in cur and self._survives(n, ctx):
                            cur[n] = origin
                            changed = True
        self._esc = esc

    def may_raise(self, path: str, key: str) -> Dict[str, Tuple[str, str, int]]:
        """{escaping exception name: (origin path, def key, line)} for
        the def ``key`` in the module at ``path``."""
        self._ensure()
        return dict(self._esc.get((os.path.abspath(path), key), {}))

    # -- rule 20: unmapped-edge-exception ------------------------------

    @staticmethod
    def _is_edge_module(path: str) -> bool:
        p = os.path.abspath(path).replace(os.sep, "/")
        return p.endswith("serve/frontend.py") or p.endswith(
            "serve/edge.py"
        )

    def dispatch_entries_for(self, path: str) -> Dict[str, str]:
        """{def key: entry label} — the dispatch entries of an edge
        module: selectors loop callbacks registered anywhere in the
        tree that resolve to defs in this module, plus ``do_GET``/
        ``do_POST``-style handler methods."""
        self._ensure()
        ap = os.path.abspath(path)
        out: Dict[str, str] = {}
        if not self._is_edge_module(ap):
            return out
        for epath, ekey, label in self.graph._loop_entries:
            if epath == ap:
                out.setdefault(ekey, label)
        m = self.graph.by_path.get(ap)
        if m is not None:
            for key in m.defs:
                base = key.rsplit(".", 1)[-1]
                if base in ("do_GET", "do_POST", "do_PUT", "do_DELETE"):
                    out.setdefault(key, f"{m.name}:{key}")
        return out

    def entry_closure_keys(self, path: str) -> Set[str]:
        """Def keys in ``path`` reachable from its dispatch entries —
        what rule 20 actually analyzed (the non-vacuity pin)."""
        self._ensure()
        ap = os.path.abspath(path)
        seeds = {(ap, k) for k in self.dispatch_entries_for(ap)}
        return {nk[1] for nk in self.graph._closure(seeds) if nk[0] == ap}

    def edge_findings_for(self, path: str) -> List[Tuple[int, int, str]]:
        self._ensure()
        ap = os.path.abspath(path)
        out: List[Tuple[int, int, str]] = []
        entries = self.dispatch_entries_for(ap)
        for key in sorted(entries):
            node = self.graph._node_of.get((ap, key))
            if node is None:
                continue
            for exc, origin in sorted(self._esc.get((ap, key), {}).items()):
                if self.ancestors(exc) & _EDGE_EXEMPT_ROOTS:
                    continue
                opath, okey, oline = origin
                where = (
                    f"line {oline}" if opath == ap and okey == key
                    else f"{os.path.basename(opath)}:{oline} in {okey!r}"
                )
                out.append((
                    node.lineno, node.col_offset,
                    f"{exc} (raised at {where}) can escape the edge "
                    f"dispatch entry {key!r} with no status-code "
                    f"mapping in the handler chain — the client gets "
                    f"a wedged connection instead of an error "
                    f"response (the PR 16 _feed_body TypeError "
                    f"shape); catch it where a status can still be "
                    f"sent, or map it explicitly",
                ))
        return out

    # -- rule 21: raise-before-cleanup ---------------------------------

    @staticmethod
    def _is_cleanup_def(key: str) -> bool:
        base = key.rsplit(".", 1)[-1]
        if base in _CLEANUP_EXACT:
            return True
        parts = {p for p in base.lower().split("_") if p}
        return bool(parts & _CLEANUP_TOKENS)

    def cleanup_findings_for(self, path: str) -> List[Tuple[int, int, str]]:
        self._ensure()
        ap = os.path.abspath(path)
        out: List[Tuple[int, int, str]] = []
        for nk in sorted(k for k in self._raises if k[0] == ap):
            key = nk[1]
            releases = self._releases.get(nk, ())
            if not releases:
                continue
            # gate: only defs that ARE a cleanup path by name. A long
            # main() also ends in releases, but a raise mid-setup dying
            # before teardown is process-exit territory — flagging every
            # banner print in every tool main is cry-wolf, and the rule
            # would get turned off (the PR 5 discipline)
            if not self._is_cleanup_def(key):
                continue
            for line, col, callee, printf, ctx, fins, rel in self._calls[nk]:
                if rel:
                    # a release call is the thing being skipped, not
                    # the thing doing the skipping
                    continue
                excs: Dict[str, Tuple[str, str, int]] = {}
                if printf and self._survives("OSError", ctx):
                    excs["OSError"] = (nk[0], key, line)
                if callee is not None:
                    for n, origin in self._esc.get(callee, {}).items():
                        if self._survives(n, ctx):
                            excs.setdefault(n, origin)
                if not excs:
                    continue
                skipped = None
                for rline, recv, attr, in_finals in releases:
                    if rline <= line:
                        continue
                    if any(t in fins for t in in_finals):
                        continue  # shared try/finally: release runs
                    skipped = (rline, recv, attr)
                    break
                if skipped is None:
                    continue
                rline, recv, attr = skipped
                names = ", ".join(sorted(excs))
                out.append((
                    line, col,
                    f"this call may raise {names} before "
                    f"{recv}.{attr}() at line {rline} on the cleanup "
                    f"path {key!r} — the raise skips the release and "
                    f"the resource is never retired (the PR 17 drain "
                    f"BrokenPipeError-before-frontend.stop() shape); "
                    f"move the release into a try/finally or catch "
                    f"{names} around this call",
                ))
        return out
