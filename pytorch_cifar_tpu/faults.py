"""Fault injection: the registry behind the chaos harness (ROBUSTNESS.md).

Production code never *behaves* differently because this module exists —
each injection point is a read of an inert registry that tests and
``tools/chaos_run.py`` arm on purpose. Injection points:

- ``nan_loss`` (value = global step index): the train step poisons the
  loss used for gradients at exactly that step (``train/steps.py``),
  exercising the divergence sentinel's skip/rollback policies.
- ``serve_error`` (optional ``times`` budget): ``InferenceEngine.predict``
  raises before dispatch, exercising the micro-batcher's
  fail-this-batch-only error containment.
- :func:`truncate_file` / :func:`bitflip_file`: deterministic checkpoint
  corruption for the manifest-verified fallback restore path
  (``train/checkpoint.py``).
- ``ckpt_regress`` (value = perturbation scale in PERCENT): the
  checkpoint save path perturbs the snapshot's params before publishing,
  so the committed file is *plausible but wrong* — finite weights, VALID
  manifest, wrong logits. CRC catches torn/bitflipped files; only the
  canary pipeline's output-level vetting (``serve/canary.py``) catches
  this one. :func:`regress_checkpoint` is the offline equivalent for an
  already-published file (``nan=True`` poisons instead of perturbing).
- :func:`slow_loris` / :func:`conn_flood`: live network attackers for
  the edge chaos drill (``tools/chaos_run.py --mode edge``) — a
  one-byte-per-interval request trickle and a hold-open connection
  flood, the two resource-exhaustion shapes the event-loop edge's read
  deadlines exist to bound (SERVING.md "Event-loop edge").

Arming works two ways:

- programmatic (in-process tests): ``faults.inject("nan_loss", 3)``,
  cleaned up with ``faults.clear()``;
- the ``PCT_FAULTS`` environment variable (subprocess chaos runs):
  ``PCT_FAULTS="nan_loss=3"`` or ``PCT_FAULTS="serve_error;nan_loss=7"``
  — parsed once at first use, so a chaos driver can arm a child
  ``train.py``/``serve.py`` without touching its CLI surface.

Stdlib-only on purpose: importable before jax initializes a backend.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

ENV_VAR = "PCT_FAULTS"

_lock = threading.Lock()
_active: Dict[str, Dict[str, Any]] = {}
_env_loaded = False


def _parse_value(raw: str) -> Any:
    try:
        return int(raw)
    except ValueError:
        return raw


def _load_env_locked() -> None:
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    spec = os.environ.get(ENV_VAR, "").strip()
    if not spec:
        return
    for part in spec.replace(",", ";").split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, raw = part.partition("=")
        entry: Dict[str, Any] = {"value": True, "times": None}
        if raw:
            entry["value"] = _parse_value(raw)
        _active.setdefault(name.strip(), entry)


def inject(name: str, value: Any = True, times: Optional[int] = None) -> None:
    """Arm fault ``name``. ``times`` bounds how many triggers fire
    (None = until cleared) — only consumed by :func:`maybe_raise`."""
    with _lock:
        _load_env_locked()
        _active[name] = {"value": value, "times": times}


def clear(name: Optional[str] = None) -> None:
    """Disarm one fault (or all). Also forgets the env arming, so a test
    that calls ``clear()`` fully resets the registry."""
    global _env_loaded
    with _lock:
        _env_loaded = True  # do not resurrect env faults after a clear
        if name is None:
            _active.clear()
        else:
            _active.pop(name, None)


def get(name: str, default: Any = None) -> Any:
    """The armed value of ``name`` (or ``default`` when inert)."""
    with _lock:
        _load_env_locked()
        entry = _active.get(name)
        return default if entry is None else entry["value"]


def is_active(name: str) -> bool:
    return get(name) is not None and get(name) is not False


def nan_loss_step() -> Optional[int]:
    """Global step index at which the train step should poison the loss,
    or None when inert. Read at trace/closure-build time by
    ``make_train_step`` — arm BEFORE constructing the Trainer/step."""
    v = get("nan_loss")
    if v is None or v is False:
        return None
    return int(v) if v is not True else 0


def ckpt_regress_scale() -> Optional[float]:
    """Perturbation scale of the armed ``ckpt_regress`` fault, or None
    when inert. Armed values are PERCENT (``PCT_FAULTS`` carries ints):
    ``ckpt_regress=100`` perturbs each float param leaf by ~1.0 of its
    own std; a bare ``ckpt_regress`` means 100. Read by
    ``save_checkpoint`` right after the device_get snapshot."""
    v = get("ckpt_regress")
    if v is None or v is False:
        return None
    return 1.0 if v is True else float(v) / 100.0


def maybe_raise(name: str, exc: type = RuntimeError) -> None:
    """Raise ``exc`` if fault ``name`` is armed, consuming one unit of its
    ``times`` budget (a budget of 1 gives exactly one failure)."""
    with _lock:
        _load_env_locked()
        entry = _active.get(name)
        if entry is None:
            return
        if entry["times"] is not None:
            if entry["times"] <= 0:
                return
            entry["times"] -= 1
            if entry["times"] == 0:
                _active.pop(name, None)
    raise exc(f"injected fault: {name}")


# -- checkpoint corruption helpers (chaos harness + tests) ---------------


def truncate_file(path: str, keep_fraction: float = 0.5) -> int:
    """Truncate ``path`` to ``keep_fraction`` of its size — the torn-write
    shape a host crash mid-write leaves behind. Returns the new size."""
    size = os.path.getsize(path)
    keep = max(0, int(size * keep_fraction))
    with open(path, "rb+") as f:
        f.truncate(keep)
    return keep


def regress_checkpoint(
    ckpt_dir: str,
    name: str = "ckpt.msgpack",
    scale: float = 1.0,
    seed: int = 0,
    nan: bool = False,
) -> str:
    """Rewrite checkpoint ``name`` in place as a PLAUSIBLE-BUT-WRONG
    publish: every float param leaf perturbed by N(0, scale*std) noise
    (or NaN-poisoned with ``nan=True``), and the sidecar manifest
    RECOMPUTED so integrity verification still passes — the checkpoint
    restores and serves cleanly, its outputs are just wrong. The failure
    shape the canary pipeline exists to catch (ROBUSTNESS.md "canary
    promotion"); :func:`bitflip_file` without the manifest fix covers
    the CRC-visible class instead. Single-payload (v2) checkpoints only.

    Imports flax/numpy lazily — this module stays importable before jax
    initializes a backend; msgpack restore/serialize never touch one."""
    import json

    import numpy as np
    from flax import serialization

    from pytorch_cifar_tpu.train.checkpoint import (
        _atomic_write,
        meta_path,
        payload_manifest,
    )

    path = os.path.join(ckpt_dir, name)
    mpath = meta_path(ckpt_dir, name)
    with open(mpath) as f:
        meta = json.load(f)
    if meta.get("shards"):
        raise ValueError(
            f"{path}: regress_checkpoint supports single-payload (v2) "
            "checkpoints only"
        )
    with open(path, "rb") as f:
        tree = serialization.msgpack_restore(f.read())
    rs = np.random.RandomState(seed)

    def walk(node):
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        arr = np.asarray(node)
        if not np.issubdtype(arr.dtype, np.floating):
            return node
        out = arr.copy()
        if nan:
            out.reshape(-1)[0] = np.nan  # propagates through every layer
            return out
        sd = float(arr.std()) or 1.0
        return (arr + rs.normal(0.0, scale * sd, size=arr.shape)).astype(
            arr.dtype
        )

    tree["params"] = walk(tree["params"])
    payload = serialization.msgpack_serialize(tree)
    _atomic_write(path, payload)
    meta["manifest"] = payload_manifest(payload)
    _atomic_write(mpath, json.dumps(meta).encode())
    return path


def bitflip_file(path: str, offset: Optional[int] = None) -> int:
    """Flip one bit in ``path`` (middle byte by default) — silent media
    corruption that only a checksum can catch (the file stays the same
    size and often still parses). Returns the flipped offset."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot bitflip empty file {path!r}")
    off = size // 2 if offset is None else offset
    with open(path, "rb+") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0x40]))
    return off


def slow_loris(
    host: str,
    port: int,
    *,
    duration_s: float = 5.0,
    interval_s: float = 0.5,
    connect_timeout_s: float = 5.0,
) -> Dict[str, int]:
    """A slow-loris attacker against one HTTP edge: open a connection,
    trickle ONE header byte per ``interval_s``, and never finish the
    request. Against a per-connection-thread frontend this parks a
    handler thread for the socket timeout; against the event-loop edge
    (``serve/edge.py``) the per-connection read deadline must close it
    long before ``duration_s`` elapses. Returns
    ``{"sent": bytes trickled, "closed_by_server": 0/1}`` — the chaos
    drill asserts ``closed_by_server == 1`` and the drill's foreground
    traffic unaffected (ROBUSTNESS.md "edge drill")."""
    import socket
    import time

    head = b"POST /predict HTTP/1.1\r\nContent-Length: 10\r\nX-Slow: "
    sent = 0
    closed = 0
    sock = socket.create_connection((host, port), timeout=connect_timeout_s)
    try:
        sock.settimeout(interval_s)
        deadline = time.monotonic() + duration_s
        while time.monotonic() < deadline:
            try:
                sock.sendall(head[sent % len(head):][:1])
                sent += 1
            except OSError:
                closed = 1  # server reset us mid-trickle: the deadline
                break
            # a server-side close surfaces as EOF on the read side well
            # before the send buffer notices
            try:
                if sock.recv(256) == b"":
                    closed = 1
                    break
            except socket.timeout:
                pass
            except OSError:
                closed = 1
                break
    finally:
        sock.close()
    return {"sent": sent, "closed_by_server": closed}


def conn_flood(
    host: str,
    port: int,
    *,
    connections: int = 256,
    hold_s: float = 1.0,
    connect_timeout_s: float = 5.0,
) -> Dict[str, int]:
    """A connection flood against one HTTP edge: open ``connections``
    sockets as fast as the listener accepts them, send NOTHING, hold
    them ``hold_s``, then close. A thread-per-connection frontend burns
    a thread per socket; the event-loop edge absorbs the whole flood on
    one loop thread (an idle registered socket costs one fd and one
    dict entry — deliberately NOT a loris deadline, since idle
    keep-alive between requests is the legitimate client shape) and
    reaps each on the attacker's close, with foreground traffic
    undisturbed throughout. Returns ``{"opened": n, "refused": n}``."""
    import socket
    import time

    socks = []
    refused = 0
    try:
        for _ in range(connections):
            try:
                socks.append(
                    socket.create_connection(
                        (host, port), timeout=connect_timeout_s
                    )
                )
            except OSError:
                refused += 1
        time.sleep(hold_s)
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
    return {"opened": len(socks), "refused": refused}
