"""On-device batched augmentation under explicit PRNG keys.

The reference augments per-sample on CPU inside DataLoader worker processes
(RandomCrop(32, padding=4) + RandomHorizontalFlip + Normalize,
main.py:30-35). TPU-first redesign: augmentation is a pure, batched jax
function executed on device as the prologue of the jitted train step —
vectorized over the batch, fused by XLA into the step, and requiring no host
worker pool. Host->device traffic is raw uint8 (3 KB/image) instead of
augmented fp32.

All randomness flows through explicit ``jax.random`` keys (per-step,
epoch-seeded), which also fixes the reference's missing
``sampler.set_epoch`` determinism hazard (SURVEY.md §3.2).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

CIFAR10_MEAN = (0.4914, 0.4822, 0.4465)  # main.py:34
CIFAR10_STD = (0.2023, 0.1994, 0.2010)


def normalize(
    x: jax.Array,
    mean: Sequence[float] = CIFAR10_MEAN,
    std: Sequence[float] = CIFAR10_STD,
    dtype=jnp.float32,
) -> jax.Array:
    """uint8 NHWC -> normalized float NHWC (ToTensor + Normalize)."""
    mean = jnp.asarray(mean, jnp.float32) * 255.0
    std = jnp.asarray(std, jnp.float32) * 255.0
    x = (x.astype(jnp.float32) - mean) / std
    return x.astype(dtype)


def random_crop(key: jax.Array, x: jax.Array, padding: int = 4) -> jax.Array:
    """Batched RandomCrop(32, padding=4): zero-pad then per-image offset.

    Implemented as one padded tensor + vmapped dynamic_slice — static shapes
    throughout, so XLA tiles it onto the VPU with no host round-trips.
    """
    n, h, w, c = x.shape
    pad = [(0, 0), (padding, padding), (padding, padding), (0, 0)]
    xp = jnp.pad(x, pad)
    offs = jax.random.randint(key, (n, 2), 0, 2 * padding + 1)

    def crop_one(img, off):
        return jax.lax.dynamic_slice(img, (off[0], off[1], 0), (h, w, c))

    return jax.vmap(crop_one)(xp, offs)


def random_hflip(key: jax.Array, x: jax.Array) -> jax.Array:
    """Batched RandomHorizontalFlip(p=0.5) via a per-image select."""
    n = x.shape[0]
    flip = jax.random.bernoulli(key, 0.5, (n, 1, 1, 1))
    return jnp.where(flip, x[:, :, ::-1, :], x)


def crop_flip_onehot(
    key: jax.Array, x: jax.Array, padding: int = 4, flip: bool = True
) -> jax.Array:
    """Fused RandomCrop+RandomHorizontalFlip as one-hot selection matmuls.

    Per-image dynamic_slice lowers to a gather, which is the single most
    expensive op in the train step on TPU (measured: ~8.5 ms of a 24 ms
    ResNet-18 bs512 step). Reformulated: out = A @ padded @ B^T with A/B
    per-image one-hot (rows select crop rows, cols select crop cols, with
    the flip folded into B by reversing the output index) — two tiny batched
    einsums that ride the MXU. Bit-identical to random_crop+random_hflip
    under the same key (tests/test_data.py), ~8x faster.

    The einsums run in bf16, which is still EXACT: bf16 represents every
    integer 0..256, each selection row is one-hot so every output element is
    a single selected uint8 value (no accumulation), and the MXU accumulates
    in fp32 regardless. Measured 4x faster than the fp32 einsums on v5e
    (fp32 matmul is emulated by multiple bf16 MXU passes).
    """
    n, h, w, c = x.shape
    kc, kf = jax.random.split(key)
    offs = jax.random.randint(kc, (n, 2), 0, 2 * padding + 1)
    xp = jnp.pad(
        x, [(0, 0), (padding, padding), (padding, padding), (0, 0)]
    ).astype(jnp.bfloat16)
    hp, wp = h + 2 * padding, w + 2 * padding

    rows = jax.lax.broadcasted_iota(jnp.int32, (n, h, hp), 1)
    src_r = jax.lax.broadcasted_iota(jnp.int32, (n, h, hp), 2)
    sel_rows = (src_r == rows + offs[:, 0, None, None]).astype(jnp.bfloat16)

    cols = jax.lax.broadcasted_iota(jnp.int32, (n, w, wp), 1)
    if flip:
        do_flip = jax.random.bernoulli(kf, 0.5, (n,))[:, None, None]
        cols = jnp.where(do_flip, w - 1 - cols, cols)
    src_c = jax.lax.broadcasted_iota(jnp.int32, (n, w, wp), 2)
    sel_cols = (src_c == cols + offs[:, 1, None, None]).astype(jnp.bfloat16)

    out = jnp.einsum(
        "nhH,nHWc->nhWc", sel_rows, xp, preferred_element_type=jnp.float32
    ).astype(jnp.bfloat16)
    return jnp.einsum(
        "nwW,nhWc->nhwc", sel_cols, out, preferred_element_type=jnp.float32
    )


def augment_batch(
    key: jax.Array,
    x: jax.Array,
    crop: bool = True,
    flip: bool = True,
    mean: Sequence[float] = CIFAR10_MEAN,
    std: Sequence[float] = CIFAR10_STD,
    dtype=jnp.float32,
) -> jax.Array:
    """Full train-time pipeline: crop -> flip -> normalize (uint8 in)."""
    if crop:
        x = crop_flip_onehot(key, x, flip=flip)
    elif flip:
        _, kf = jax.random.split(key)
        x = random_hflip(kf, x)
    return normalize(x, mean, std, dtype)
