"""On-device batched augmentation under explicit PRNG keys.

The reference augments per-sample on CPU inside DataLoader worker processes
(RandomCrop(32, padding=4) + RandomHorizontalFlip + Normalize,
main.py:30-35). TPU-first redesign: augmentation is a pure, batched jax
function executed on device as the prologue of the jitted train step —
vectorized over the batch, fused by XLA into the step, and requiring no host
worker pool. Host->device traffic is raw uint8 (3 KB/image) instead of
augmented fp32.

All randomness flows through explicit ``jax.random`` keys (per-step,
epoch-seeded), which also fixes the reference's missing
``sampler.set_epoch`` determinism hazard (SURVEY.md §3.2).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

CIFAR10_MEAN = (0.4914, 0.4822, 0.4465)  # main.py:34
CIFAR10_STD = (0.2023, 0.1994, 0.2010)


def normalize(
    x: jax.Array,
    mean: Sequence[float] = CIFAR10_MEAN,
    std: Sequence[float] = CIFAR10_STD,
    dtype=jnp.float32,
) -> jax.Array:
    """uint8 NHWC -> normalized float NHWC (ToTensor + Normalize)."""
    mean = jnp.asarray(mean, jnp.float32) * 255.0
    std = jnp.asarray(std, jnp.float32) * 255.0
    x = (x.astype(jnp.float32) - mean) / std
    return x.astype(dtype)


def random_crop(key: jax.Array, x: jax.Array, padding: int = 4) -> jax.Array:
    """Batched RandomCrop(32, padding=4): zero-pad then per-image offset.

    Implemented as one padded tensor + vmapped dynamic_slice — static shapes
    throughout, so XLA tiles it onto the VPU with no host round-trips.
    """
    n, h, w, c = x.shape
    pad = [(0, 0), (padding, padding), (padding, padding), (0, 0)]
    xp = jnp.pad(x, pad)
    offs = jax.random.randint(key, (n, 2), 0, 2 * padding + 1)

    def crop_one(img, off):
        return jax.lax.dynamic_slice(img, (off[0], off[1], 0), (h, w, c))

    return jax.vmap(crop_one)(xp, offs)


def random_hflip(key: jax.Array, x: jax.Array) -> jax.Array:
    """Batched RandomHorizontalFlip(p=0.5) via a per-image select."""
    n = x.shape[0]
    flip = jax.random.bernoulli(key, 0.5, (n, 1, 1, 1))
    return jnp.where(flip, x[:, :, ::-1, :], x)


def augment_batch(
    key: jax.Array,
    x: jax.Array,
    crop: bool = True,
    flip: bool = True,
    mean: Sequence[float] = CIFAR10_MEAN,
    std: Sequence[float] = CIFAR10_STD,
    dtype=jnp.float32,
) -> jax.Array:
    """Full train-time pipeline: crop -> flip -> normalize (uint8 in)."""
    kc, kf = jax.random.split(key)
    if crop:
        x = random_crop(kc, x)
    if flip:
        x = random_hflip(kf, x)
    return normalize(x, mean, std, dtype)
