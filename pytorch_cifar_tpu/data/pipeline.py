"""Host-side input pipeline: epoch-seeded shuffle + sharded device prefetch.

Replaces the reference's DataLoader worker pool + DistributedSampler
(main.py:44-50, main_dist.py:109-127). Work split:

- host (this module): shuffle an index permutation per epoch, gather uint8
  slices, ``jax.device_put`` onto the batch-sharded mesh axis with one batch
  of lookahead (double buffering);
- device (augment.py): crop/flip/normalize inside the jitted step.

Sharding semantics match the reference's ``global batch / world_size``
(main_dist.py:111-115): the global batch is laid out over the mesh's data
axis by NamedSharding, so each device reads batch/n_devices images. The
per-epoch reshuffle is seeded with (seed, epoch) — the determinism the
reference loses by never calling ``sampler.set_epoch`` (SURVEY.md §3.2).
"""

from __future__ import annotations

import collections
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

from pytorch_cifar_tpu.native import augment_batch_u8, gather_batch


class Dataloader:
    """Iterates (images_uint8, labels_int32) device batches for one epoch."""

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        batch_size: int,
        shuffle: bool = True,
        drop_last: bool = True,
        seed: int = 0,
        sharding: Optional[jax.sharding.Sharding] = None,
        prefetch: int = 2,
        host_augment: bool = False,
        augment_padding: int = 4,
        augment_flip: bool = True,
    ):
        assert images.shape[0] == labels.shape[0]
        # normalize once so the native gather's zero-copy fast path applies
        # to every batch (gather_batch falls back to numpy indexing for
        # non-contiguous or non-canonical dtypes)
        self.images = np.ascontiguousarray(images)
        self.labels = np.ascontiguousarray(
            labels, np.int32 if labels.dtype.kind in "iu" else labels.dtype
        )
        self.batch_size = batch_size
        self.shuffle = shuffle
        # Like the reference's drop_last=False default, a ragged final batch
        # would retrigger XLA compilation per distinct shape; on TPU we drop
        # it for train and pad for eval (see eval_batches).
        self.drop_last = drop_last
        self.seed = seed
        self.sharding = sharding
        self.prefetch = max(1, prefetch)
        # CPU-mode augmentation in the native data plane (crop+flip on the
        # host, native/cifar_native.cpp) — used with a train step built with
        # augment=False; on TPU the on-device path (augment.py) is faster
        self.host_augment = host_augment
        self.augment_padding = augment_padding
        self.augment_flip = augment_flip

    def __len__(self) -> int:
        n = self.images.shape[0]
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def epoch(self, epoch: int) -> Iterator[Tuple[jax.Array, jax.Array]]:
        n = self.images.shape[0]
        if self.shuffle:
            order = np.random.RandomState(
                (self.seed * 100003 + epoch) % (2**31)
            ).permutation(n)
        else:
            order = np.arange(n)
        nb = len(self)

        aug_rng = np.random.RandomState(
            (self.seed * 9973 + epoch * 31 + 7) % (2**31)
        )

        def host_batches():
            for b in range(nb):
                idx = order[b * self.batch_size : (b + 1) * self.batch_size]
                # native parallel gather (OpenMP memcpy, GIL released) with a
                # numpy fancy-indexing fallback — native/cifar_native.cpp
                x, y = gather_batch(self.images, self.labels, idx)
                if self.host_augment:
                    n, pad = x.shape[0], self.augment_padding
                    x = augment_batch_u8(
                        x,
                        aug_rng.randint(0, 2 * pad + 1, n),
                        aug_rng.randint(0, 2 * pad + 1, n),
                        aug_rng.randint(0, 2 if self.augment_flip else 1, n),
                        padding=pad,
                    )
                if not self.drop_last and x.shape[0] < self.batch_size:
                    pad = self.batch_size - x.shape[0]
                    x = np.concatenate([x, np.zeros_like(x[:1]).repeat(pad, 0)])
                    y = np.concatenate([y, np.full((pad,), -1, y.dtype)])
                yield x, y

        # double-buffer: keep `prefetch` batches in flight on device
        queue = collections.deque()
        it = host_batches()
        try:
            while True:
                while len(queue) < self.prefetch:
                    x, y = next(it)
                    queue.append(self._put(x, y))
                yield queue.popleft()
        except StopIteration:
            while queue:
                yield queue.popleft()

    def _put(self, x: np.ndarray, y: np.ndarray):
        if self.sharding is not None:
            x = jax.device_put(x, self.sharding)
            y = jax.device_put(y, self.sharding)
        else:
            x = jax.device_put(x)
            y = jax.device_put(y)
        return x, y


def eval_batches(images: np.ndarray, labels: np.ndarray, batch_size: int):
    """Padded, unshuffled eval batches; labels padded with -1 (masked out).

    The reference evals the full unsharded test set on every rank with no
    metric reduction (main_dist.py:205-252, SURVEY.md §2.5.7); here eval is
    sharded like train and metrics are psum-reduced, with -1 padding labels
    excluded from both loss and accuracy denominators.
    """
    n = images.shape[0]
    nb = -(-n // batch_size)
    for b in range(nb):
        x = images[b * batch_size : (b + 1) * batch_size]
        y = labels[b * batch_size : (b + 1) * batch_size]
        if x.shape[0] < batch_size:
            pad = batch_size - x.shape[0]
            x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
            y = np.concatenate([y, np.full((pad,), -1, y.dtype)])
        yield x, y
