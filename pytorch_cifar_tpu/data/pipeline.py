"""Host-side input pipeline: epoch-seeded shuffle + sharded device prefetch.

Replaces the reference's DataLoader worker pool + DistributedSampler
(main.py:44-50, main_dist.py:109-127). Work split:

- host (this module): shuffle an index permutation per epoch, gather uint8
  slices, ``jax.device_put`` onto the batch-sharded mesh axis with one batch
  of lookahead (double buffering);
- device (augment.py): crop/flip/normalize inside the jitted step.

Sharding semantics match the reference's ``global batch / world_size``
(main_dist.py:111-115): the global batch is laid out over the mesh's data
axis by NamedSharding, so each device reads batch/n_devices images. The
per-epoch reshuffle is seeded with (seed, epoch) — the determinism the
reference loses by never calling ``sampler.set_epoch`` (SURVEY.md §3.2).

Multi-host: every process computes the same epoch permutation (seed is
part of the config, shared by all hosts), gathers only its own contiguous
slice of each global batch (the DistributedSampler role,
main_dist.py:110), and assembles the global array from process-local
shards via ``jax.make_array_from_process_local_data`` — a plain
``device_put`` against a global sharding only works single-process.
"""

from __future__ import annotations

import collections
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

from pytorch_cifar_tpu.native import augment_batch_u8, gather_batch


class Dataloader:
    """Iterates (images_uint8, labels_int32) device batches for one epoch."""

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        batch_size: int,
        shuffle: bool = True,
        drop_last: bool = True,
        seed: int = 0,
        sharding: Optional[jax.sharding.Sharding] = None,
        label_sharding: Optional[jax.sharding.Sharding] = None,
        prefetch: int = 2,
        host_augment: bool = False,
        augment_padding: int = 4,
        augment_flip: bool = True,
    ):
        assert images.shape[0] == labels.shape[0]
        # normalize once so the native gather's zero-copy fast path applies
        # to every batch (gather_batch falls back to numpy indexing for
        # non-contiguous or non-canonical dtypes)
        self.images = np.ascontiguousarray(images)
        self.labels = np.ascontiguousarray(
            labels, np.int32 if labels.dtype.kind in "iu" else labels.dtype
        )
        self.batch_size = batch_size
        # images and labels usually share one batch-axis sharding; spatial
        # partitioning shards images (N,H,...) on two axes while labels (N,)
        # stay batch-only — pass both then
        self.label_sharding = label_sharding if label_sharding is not None else sharding
        self.shuffle = shuffle
        # Like the reference's drop_last=False default, a ragged final batch
        # would retrigger XLA compilation per distinct shape; on TPU we drop
        # it for train and pad for eval (see eval_batches).
        self.drop_last = drop_last
        self.seed = seed
        self.sharding = sharding
        self.prefetch = max(1, prefetch)
        # CPU-mode augmentation in the native data plane (crop+flip on the
        # host, native/cifar_native.cpp) — used with a train step built with
        # augment=False; on TPU the on-device path (augment.py) is faster
        self.host_augment = host_augment
        self.augment_padding = augment_padding
        self.augment_flip = augment_flip

    def __len__(self) -> int:
        n = self.images.shape[0]
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def epoch(self, epoch: int) -> Iterator[Tuple[jax.Array, jax.Array]]:
        n = self.images.shape[0]
        if self.shuffle:
            order = np.random.RandomState(
                (self.seed * 100003 + epoch) % (2**31)
            ).permutation(n)
        else:
            order = np.arange(n)
        nb = len(self)

        aug_rng = np.random.RandomState(
            (self.seed * 9973 + epoch * 31 + 7) % (2**31)
        )

        # multi-host: this process materializes only its slice of each
        # global batch; rows [pid*B/P, (pid+1)*B/P) of the shared permutation
        pid, pcount = jax.process_index(), jax.process_count()
        local_bs = self.batch_size // pcount if pcount > 1 else self.batch_size
        if pcount > 1 and self.batch_size % pcount:
            raise ValueError(
                f"batch_size {self.batch_size} not divisible by "
                f"{pcount} processes"
            )

        def host_batches():
            for b in range(nb):
                lo = b * self.batch_size + pid * local_bs
                idx = order[lo : lo + local_bs]
                # native parallel gather (OpenMP memcpy, GIL released) with a
                # numpy fancy-indexing fallback — native/cifar_native.cpp
                x, y = gather_batch(self.images, self.labels, idx)
                if self.host_augment:
                    pad = self.augment_padding
                    # draw for the FULL global batch and slice this
                    # process's rows: every process consumes the same
                    # stream, so augmentation stays decorrelated across
                    # shards and topology-invariant vs single-process
                    n = x.shape[0]
                    s = slice(pid * local_bs, pid * local_bs + n)
                    dx = aug_rng.randint(0, 2 * pad + 1, self.batch_size)[s]
                    dy = aug_rng.randint(0, 2 * pad + 1, self.batch_size)[s]
                    fl = aug_rng.randint(
                        0, 2 if self.augment_flip else 1, self.batch_size
                    )[s]
                    x = augment_batch_u8(x, dx, dy, fl, padding=pad)
                if not self.drop_last and x.shape[0] < local_bs:
                    # every process pads its slice to exactly local_bs so
                    # shard shapes stay consistent across processes on the
                    # ragged final batch (a process's slice can even be
                    # empty); -1 labels are masked out of the metrics
                    pad = local_bs - x.shape[0]
                    x = np.concatenate(
                        [x, np.zeros((pad,) + x.shape[1:], x.dtype)]
                    )
                    y = np.concatenate([y, np.full((pad,), -1, y.dtype)])
                yield x, y

        # double-buffer: keep `prefetch` batches in flight on device
        queue = collections.deque()
        it = host_batches()
        try:
            while True:
                while len(queue) < self.prefetch:
                    x, y = next(it)
                    queue.append(self._put(x, y))
                yield queue.popleft()
        except StopIteration:
            while queue:
                yield queue.popleft()

    def _put(self, x: np.ndarray, y: np.ndarray):
        if jax.process_count() > 1:
            if self.sharding is None:
                raise ValueError(
                    "multi-process Dataloader requires a batch sharding"
                )
            # assemble the global array from this process's local shard
            x = jax.make_array_from_process_local_data(self.sharding, x)
            y = jax.make_array_from_process_local_data(self.label_sharding, y)
        elif self.sharding is not None:
            x = jax.device_put(x, self.sharding)
            y = jax.device_put(y, self.label_sharding)
        else:
            x = jax.device_put(x)
            y = jax.device_put(y)
        return x, y


def put_global(
    x: np.ndarray,
    y: np.ndarray,
    sharding: Optional[jax.sharding.Sharding],
    label_sharding: Optional[jax.sharding.Sharding] = None,
):
    """Place a host-materialized GLOBAL batch onto the mesh.

    Single-process: a plain sharded device_put. Multi-process: every process
    holds the same global batch (e.g. the full test set, eval_batches);
    each contributes only its contiguous slice and the global array is
    assembled from process-local shards.
    """
    if label_sharding is None:
        label_sharding = sharding
    if jax.process_count() > 1:
        if sharding is None:
            raise ValueError("multi-process put_global requires a sharding")
        pid, pcount = jax.process_index(), jax.process_count()
        if x.shape[0] % pcount:
            raise ValueError(
                f"global batch {x.shape[0]} not divisible by {pcount} processes"
            )
        lb = x.shape[0] // pcount
        xl = x[pid * lb : (pid + 1) * lb]
        yl = y[pid * lb : (pid + 1) * lb]
        return (
            jax.make_array_from_process_local_data(sharding, xl),
            jax.make_array_from_process_local_data(label_sharding, yl),
        )
    if sharding is not None:
        return jax.device_put(x, sharding), jax.device_put(y, label_sharding)
    return jax.device_put(x), jax.device_put(y)


def eval_batches(images: np.ndarray, labels: np.ndarray, batch_size: int):
    """Padded, unshuffled eval batches; labels padded with -1 (masked out).

    The reference evals the full unsharded test set on every rank with no
    metric reduction (main_dist.py:205-252, SURVEY.md §2.5.7); here eval is
    sharded like train and metrics are psum-reduced, with -1 padding labels
    excluded from both loss and accuracy denominators.
    """
    n = images.shape[0]
    nb = -(-n // batch_size)
    for b in range(nb):
        x = images[b * batch_size : (b + 1) * batch_size]
        y = labels[b * batch_size : (b + 1) * batch_size]
        if x.shape[0] < batch_size:
            pad = batch_size - x.shape[0]
            x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
            y = np.concatenate([y, np.full((pad,), -1, y.dtype)])
        yield x, y
