"""Host-side input pipeline: epoch-seeded shuffle + sharded device prefetch.

Replaces the reference's DataLoader worker pool + DistributedSampler
(main.py:44-50, main_dist.py:109-127). Work split:

- host (this module): shuffle an index permutation per epoch, gather uint8
  slices, ``jax.device_put`` onto the batch-sharded mesh axis — by default
  from a background producer thread feeding a bounded queue of ``prefetch``
  batches (``async_input``), so assembly and the H2D transfer overlap step
  dispatch; ``async_input=False`` keeps the inline double-buffer;
- device (augment.py): crop/flip/normalize inside the jitted step.

Sharding semantics match the reference's ``global batch / world_size``
(main_dist.py:111-115): the global batch is laid out over the mesh's data
axis by NamedSharding, so each device reads batch/n_devices images. The
per-epoch reshuffle is seeded with (seed, epoch) — the determinism the
reference loses by never calling ``sampler.set_epoch`` (SURVEY.md §3.2).

Multi-host: every process computes the same epoch permutation (seed is
part of the config, shared by all hosts), gathers only its own contiguous
slice of each global batch (the DistributedSampler role,
main_dist.py:110), and assembles the global array from process-local
shards via ``jax.make_array_from_process_local_data`` — a plain
``device_put`` against a global sharding only works single-process.
"""

from __future__ import annotations

import collections
import queue as queue_lib
import threading
import time
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

from pytorch_cifar_tpu.native import augment_batch_u8, gather_batch
from pytorch_cifar_tpu.obs import trace


def local_slab(
    sharding: jax.sharding.Sharding, global_shape: Tuple[int, ...]
) -> Tuple[Tuple[int, int], Tuple[int, int]]:
    """This process's addressable box of a global array: ((b_lo,b_hi),(h_lo,h_hi)).

    Generalizes the 1-D "rows [pid*B/P, (pid+1)*B/P)" DistributedSampler
    arithmetic to 2-D (batch x spatial) shardings, where a process can own a
    batch range, a height range, or both (parallel/spatial.py). NamedSharding
    lays mesh axes out as a cartesian grid and ``jax.devices()`` orders
    devices by process, so the union of a process's shard indices is always
    an axis-aligned box — asserted, not assumed.
    """
    imap = sharding.addressable_devices_indices_map(global_shape)

    def bounds(dim):
        los, his = set(), set()
        for idx in imap.values():
            sl = idx[dim] if dim < len(idx) else slice(None)
            los.add(0 if sl.start is None else int(sl.start))
            his.add(global_shape[dim] if sl.stop is None else int(sl.stop))
        return min(los), max(his)

    (b_lo, b_hi) = bounds(0)
    (h_lo, h_hi) = bounds(1) if len(global_shape) > 1 else (0, 0)
    # box check: total addressable elements == box volume (no gaps/overlap
    # beyond replication). Replicated shards repeat the same index; dedupe.
    boxes = {
        tuple(
            (
                0 if s.start is None else int(s.start),
                global_shape[d] if s.stop is None else int(s.stop),
            )
            for d, s in enumerate(idx)
        )
        for idx in imap.values()
    }
    vol = sum(
        int(np.prod([hi - lo for lo, hi in box])) for box in boxes
    )
    box_dims = [b_hi - b_lo, h_hi - h_lo] + [
        global_shape[d] for d in range(2, len(global_shape))
    ]
    expect = int(np.prod(box_dims[: len(global_shape)]))
    if vol != expect:
        raise ValueError(
            f"process-local shards of {sharding} do not form a contiguous "
            f"box over {global_shape} — unsupported device order"
        )
    return (b_lo, b_hi), (h_lo, h_hi)


class Dataloader:
    """Iterates (images_uint8, labels_int32) device batches for one epoch."""

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        batch_size: int,
        shuffle: bool = True,
        drop_last: bool = True,
        seed: int = 0,
        sharding: Optional[jax.sharding.Sharding] = None,
        label_sharding: Optional[jax.sharding.Sharding] = None,
        prefetch: int = 2,
        async_input: bool = True,
        host_augment: bool = False,
        augment_padding: int = 4,
        augment_flip: bool = True,
        registry=None,
    ):
        assert images.shape[0] == labels.shape[0]
        # normalize once so the native gather's zero-copy fast path applies
        # to every batch (gather_batch falls back to numpy indexing for
        # non-contiguous or non-canonical dtypes)
        self.images = np.ascontiguousarray(images)
        self.labels = np.ascontiguousarray(
            labels, np.int32 if labels.dtype.kind in "iu" else labels.dtype
        )
        self.batch_size = batch_size
        # images and labels usually share one batch-axis sharding; spatial
        # partitioning shards images (N,H,...) on two axes while labels (N,)
        # stay batch-only — pass both then
        self.label_sharding = label_sharding if label_sharding is not None else sharding
        self.shuffle = shuffle
        # drop_last=False matches the reference DataLoader default
        # (main.py:44-45: every image trains every epoch). A ragged final
        # batch would retrigger XLA compilation per distinct shape, so the
        # tail batch is padded to full size with wrap-around images from the
        # start of the epoch's permutation: real pixels keep BatchNorm batch
        # statistics clean (zero-fill would inject constant images into the
        # moments), while their -1 labels mask them out of the loss,
        # gradients, and metrics (steps.py masks label < 0 everywhere).
        self.drop_last = drop_last
        self.seed = seed
        self.sharding = sharding
        self.prefetch = max(1, prefetch)
        # async_input=True (the production default, --async_input) moves
        # batch assembly AND the host->device put onto a dedicated worker
        # thread feeding a bounded queue of depth `prefetch`, so input
        # production overlaps step dispatch instead of executing inline
        # between dispatches. False keeps the inline double-buffer path —
        # the debugging escape hatch and the reference the equivalence
        # test compares against (both yield bit-identical batches in
        # identical order: same generator, one producer, FIFO queue).
        self.async_input = async_input
        # CPU-mode augmentation in the native data plane (crop+flip on the
        # host, native/cifar_native.cpp) — used with a train step built with
        # augment=False; on TPU the on-device path (augment.py) is faster
        self.host_augment = host_augment
        self.augment_padding = augment_padding
        self.augment_flip = augment_flip
        # observability (obs/, OBSERVABILITY.md): per-batch host production
        # cost (gather + augment + put dispatch). Input-bound detection is
        # the ratio of this against device step time — the Trainer records
        # its own wait-side histogram (train.input_wait_ms) and bench folds
        # both into the obs block. None = zero-cost (one is-None check).
        self._obs_hist = (
            registry.histogram("data.host_batch_ms")
            if registry is not None
            else None
        )
        # async-pipeline instruments: queue depth AFTER each consumer take
        # (sustained 0 = producer-bound input, sustained ~prefetch = the
        # healthy state where the chip is the bottleneck) and the producer
        # thread's full per-batch cost (gather + augment + put dispatch —
        # the work the async path moves OFF the training thread)
        self._obs_depth = (
            registry.gauge("data.prefetch_depth")
            if registry is not None
            else None
        )
        self._obs_producer = (
            registry.histogram("data.producer_batch_ms")
            if registry is not None
            else None
        )

    def __len__(self) -> int:
        n = self.images.shape[0]
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def epoch(self, epoch: int) -> Iterator[Tuple[jax.Array, jax.Array]]:
        n = self.images.shape[0]
        if self.shuffle:
            order = np.random.RandomState(
                (self.seed * 100003 + epoch) % (2**31)
            ).permutation(n)
        else:
            order = np.arange(n)
        nb = len(self)

        aug_rng = np.random.RandomState(
            (self.seed * 9973 + epoch * 31 + 7) % (2**31)
        )

        # multi-host: this process materializes only its slab of each global
        # batch. For batch-only sharding that is the classic DistributedSampler
        # rows [pid*B/P, (pid+1)*B/P) (main_dist.py:110); for 2-D
        # batch x spatial shardings the slab can also be a height range
        # (multi-host spatial partitioning) — local_slab derives both from
        # the sharding itself.
        img_shape = self.images.shape[1:]
        if jax.process_count() > 1:
            if self.sharding is None:
                raise ValueError(
                    "multi-process Dataloader requires a batch sharding"
                )
            (r0, r1), (h0, h1) = local_slab(
                self.sharding, (self.batch_size,) + tuple(img_shape)
            )
        else:
            (r0, r1), (h0, h1) = (0, self.batch_size), (0, img_shape[0])
        local_bs = r1 - r0

        def host_batches():
            for b in range(nb):
                t0 = time.perf_counter()
                lo = b * self.batch_size + r0
                hi = lo + local_bs
                if hi <= n and lo < n:
                    idx, valid = order[lo:hi], None
                else:
                    # ragged final batch (drop_last=False): wrap-pad with
                    # images from the start of this epoch's permutation so
                    # shard shapes stay full across processes; the wrapped
                    # rows carry -1 labels and are masked downstream
                    j = np.arange(lo, hi)
                    idx, valid = order[j % n], j < n
                # native parallel gather (OpenMP memcpy, GIL released) with a
                # numpy fancy-indexing fallback — native/cifar_native.cpp
                x, y = gather_batch(self.images, self.labels, idx)
                if valid is not None:
                    y = np.where(valid, y, np.int32(-1)).astype(y.dtype)
                if self.host_augment:
                    pad = self.augment_padding
                    # draw for the FULL global batch and slice this
                    # process's rows: every process consumes the same
                    # stream, so augmentation stays decorrelated across
                    # shards and topology-invariant vs single-process
                    nx = x.shape[0]
                    s = slice(r0, r0 + nx)
                    dx = aug_rng.randint(0, 2 * pad + 1, self.batch_size)[s]
                    dy = aug_rng.randint(0, 2 * pad + 1, self.batch_size)[s]
                    fl = aug_rng.randint(
                        0, 2 if self.augment_flip else 1, self.batch_size
                    )[s]
                    x = augment_batch_u8(x, dx, dy, fl, padding=pad)
                if (h0, h1) != (0, img_shape[0]):
                    # 2-D slab: this process holds a height range; slice
                    # AFTER augmentation (crops move pixels across shard
                    # boundaries, so the full image must exist first)
                    x = np.ascontiguousarray(x[:, h0:h1])
                if self._obs_hist is not None:
                    self._obs_hist.observe(
                        (time.perf_counter() - t0) * 1e3
                    )
                yield x, y

        it = host_batches()
        if self.async_input:
            # background prefetcher: assembly + H2D on a worker thread
            yield from self._async_epoch(it)
            return
        # inline double-buffer (--async_input off): keep `prefetch`
        # batches in flight on device, refilled on the training thread
        # between step dispatches — the synchronous reference path
        queue = collections.deque()
        try:
            while True:
                while len(queue) < self.prefetch:
                    x, y = next(it)
                    queue.append(self._put(x, y))
                yield queue.popleft()
        except StopIteration:
            while queue:
                yield queue.popleft()

    def _async_epoch(self, it) -> Iterator[Tuple[jax.Array, jax.Array]]:
        """Drain ``it`` (one epoch's host batches) through a background
        producer thread.

        The worker runs the SAME generator the inline path consumes —
        native gather, host augmentation (one sequential rng stream),
        multi-process ``make_array_from_process_local_data`` slab
        assembly, and the ``_put`` H2D transfer — and feeds finished
        device batches into a bounded FIFO queue of depth ``prefetch``,
        so production overlaps the training thread's step dispatches.
        One producer + FIFO ordering makes the yielded stream
        bit-identical, in identical order, to ``async_input=False``
        (pinned by tests/test_data.py).

        Shutdown contract: a consumer that stops early — sentinel
        rollback breaking the epoch loop, ``Trainer.request_stop``, an
        exception in the step — closes this generator; the ``finally``
        block stops the producer, unblocks a full-queue put by draining,
        and joins the thread, so no thread outlives the epoch. Producer
        exceptions are re-queued and re-raised HERE, on the consumer
        thread, with their original tracebacks — never swallowed.

        Concurrency shape (graftcheck unlocked-shared-mutation): all
        cross-thread state is local to this call and internally
        synchronized (queue.Queue, threading.Event); the worker mutates
        no shared attributes.
        """
        q: queue_lib.Queue = queue_lib.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def produce():
            try:
                while not stop.is_set():
                    t0 = time.perf_counter()
                    try:
                        x, y = next(it)
                    except StopIteration:
                        q.put(("end", None))
                        return
                    batch = self._put(x, y)
                    if self._obs_producer is not None:
                        self._obs_producer.observe(
                            (time.perf_counter() - t0) * 1e3
                        )
                    # blocking put = backpressure at `prefetch` batches;
                    # a shutdown mid-put is unblocked by the consumer's
                    # drain below, and the loop re-checks `stop` before
                    # producing more
                    q.put(("ok", batch))
            except BaseException as e:  # re-raised on the consumer thread
                q.put(("err", e))

        worker = threading.Thread(
            target=produce, name="input-prefetch", daemon=True
        )
        worker.start()
        try:
            while True:
                kind, payload = q.get()
                if self._obs_depth is not None:
                    self._obs_depth.set(q.qsize())
                if kind == "end":
                    return
                if kind == "err":
                    raise payload
                yield payload
        finally:
            stop.set()
            # unblock a producer parked on a full queue (maxsize >= 1, so
            # after one drain its pending put always succeeds and the
            # loop exits on `stop`)
            while True:
                try:
                    q.get_nowait()
                except queue_lib.Empty:
                    break
            worker.join(timeout=30.0)

    def _put(self, x: np.ndarray, y: np.ndarray):
        if jax.process_count() > 1:
            if self.sharding is None:
                raise ValueError(
                    "multi-process Dataloader requires a batch sharding"
                )
            # assemble the global array from this process's local slab;
            # explicit global_shape so 2-D (batch x height) slabs resolve
            # unambiguously (a dim matching the global size is read whole,
            # a smaller one is mapped from the process's addressable slices)
            gx = (self.batch_size,) + tuple(self.images.shape[1:])
            x = jax.make_array_from_process_local_data(self.sharding, x, gx)
            y = jax.make_array_from_process_local_data(
                self.label_sharding, y, (self.batch_size,)
            )
        elif self.sharding is not None:
            x = jax.device_put(x, self.sharding)
            y = jax.device_put(y, self.label_sharding)
        else:
            x = jax.device_put(x)
            y = jax.device_put(y)
        return x, y


class DeviceDataset:
    """Device-resident data plane: the whole dataset lives in HBM.

    The host Dataloader re-transfers every batch — 153 MB per CIFAR-10
    train epoch. Measured through the axon remote-TPU transport, H2D
    sustains only ~7.5 MB/s, so per-batch transfer costs ~20 s/epoch
    against ~1.4 s of device compute: the link, not the chip, becomes the
    training bottleneck. CIFAR-10 is 184 MB total — a rounding error in
    16 GB of HBM — so the TPU-native layout is to stage the uint8 arrays
    on device ONCE (replicated over the mesh) and run each epoch entirely
    on device: a jitted dynamic-slice + gather materializes every
    (batch, labels) pair from a per-epoch permutation; only the ~200 KB
    permutation crosses the link each epoch. Augmentation already runs
    inside the train step, so the batches this yields are bit-identical
    to the host Dataloader's (same seed, same permutation arithmetic,
    same wrap-padding) — pinned by tests/test_data.py.

    Also the eval path: with shuffle=False the identity "permutation" is
    baked in (no per-epoch transfer at all) and ragged tails get -1
    labels exactly like eval_batches.
    """

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        batch_size: int,
        shuffle: bool = True,
        drop_last: bool = False,
        seed: int = 0,
        sharding: Optional[jax.sharding.Sharding] = None,
        label_sharding: Optional[jax.sharding.Sharding] = None,
        device_perm: bool = False,
    ):
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        assert images.shape[0] == labels.shape[0]
        self.n = images.shape[0]
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        label_sharding = (
            label_sharding if label_sharding is not None else sharding
        )
        if sharding is not None:
            mesh = sharding.mesh
            self._replicated = NamedSharding(mesh, PartitionSpec())
        else:
            self._replicated = None
        self.images = self._put_replicated(np.ascontiguousarray(images))
        self.labels = self._put_replicated(
            np.ascontiguousarray(labels, np.int32)
        )

        n, B = self.n, batch_size
        nb = len(self)

        def materialize(images, labels, perm, start):
            idx = jax.lax.dynamic_slice(perm, (start,), (B,))
            x = jnp.take(images, idx, axis=0)
            y = jnp.take(labels, idx, axis=0)
            # wrap-padded rows (position >= n in the extended permutation)
            # are masked with label -1, same contract as the host loader
            pos = start + jnp.arange(B, dtype=jnp.int32)
            y = jnp.where(pos < n, y, -1)
            return x, y

        out_sh = (
            (sharding, label_sharding) if sharding is not None else None
        )
        self._materialize = jax.jit(
            materialize,
            **({"out_shardings": out_sh} if out_sh is not None else {}),
        )
        # device_perm: generate the epoch permutation ON DEVICE from
        # (seed, epoch) — a Fisher-Yates-equivalent jax.random.permutation
        # inside one tiny jitted dispatch — instead of uploading a
        # host-numpy permutation. Removes the last per-epoch H2D transfer
        # of the device data plane (only the 4-byte epoch scalar rides the
        # dispatch). The permutation DIFFERS from the host RandomState one
        # (different generator), so the host/device bit-exactness pin
        # (tests/test_data.py) uses device_perm=False; the device stream is
        # pinned at the distribution level instead (valid permutation,
        # (seed, epoch)-deterministic, epoch-distinct, topology-invariant).
        self.device_perm = device_perm and shuffle
        if self.device_perm:
            base_key = jax.random.PRNGKey(seed)
            total = len(self) * batch_size
            n_data = self.n

            def device_epoch_perm(epoch):
                key = jax.random.fold_in(base_key, epoch)
                order = jax.random.permutation(key, n_data)
                if total <= n_data:
                    ext = order[:total]
                else:
                    j = jnp.arange(total, dtype=jnp.int32)
                    ext = order[j % n_data]
                return ext.astype(jnp.int32)

            rep = self._replicated
            self._device_perm_fn = jax.jit(
                device_epoch_perm,
                **({"out_shardings": rep} if rep is not None else {}),
            )
        if not shuffle:
            self._perm_static = self._put_perm(self._epoch_perm(order=None))

    def _put_replicated(self, a):
        if jax.process_count() > 1:
            if self._replicated is None:
                raise ValueError(
                    "multi-process DeviceDataset requires a sharding"
                )
            # identical on every host -> replicated global array
            return jax.make_array_from_process_local_data(
                self._replicated, a, a.shape
            )
        if self._replicated is not None:
            return jax.device_put(a, self._replicated)
        return jax.device_put(a)

    def __len__(self) -> int:
        return (
            self.n // self.batch_size
            if self.drop_last
            else -(-self.n // self.batch_size)
        )

    def _epoch_perm(self, order):
        """Extended permutation of length nb*B: epoch order followed by
        wrap-around indices for the ragged tail (same wrap rule as the
        host loader, so batches match bit-for-bit)."""
        n, B, nb = self.n, self.batch_size, len(self)
        if order is None:
            order = np.arange(n, dtype=np.int32)
        total = nb * B
        if total <= n:
            return order[:total].astype(np.int32)
        j = np.arange(total)
        return order[j % n].astype(np.int32)

    def _put_perm(self, perm):
        return self._put_replicated(perm)

    def staged_perm(self, epoch: int) -> jax.Array:
        """The epoch's extended permutation, staged on device (replicated).

        ``device_perm=True`` (the production default via config.device_perm)
        computes it on device — zero per-epoch H2D; otherwise the host
        permutation is uploaded (~200 KB — the only per-epoch transfer of
        the device data plane). shuffle=False reuses one staged identity
        permutation forever — only valid for consumers that do NOT donate
        the perm (the eval epoch fn); the train epoch fn donates its perm
        argument (parallel/dp.py), which is safe precisely because
        shuffle=True stages a fresh array every epoch."""
        if not self.shuffle:
            return self._perm_static
        with trace.span("data/staged_perm", epoch=epoch):
            if self.device_perm:
                return self._device_perm_fn(np.int32(epoch))
            order = np.random.RandomState(
                (self.seed * 100003 + epoch) % (2**31)
            ).permutation(self.n)
            return self._put_perm(self._epoch_perm(order))

    def epoch(self, epoch: int) -> Iterator[Tuple[jax.Array, jax.Array]]:
        perm = self.staged_perm(epoch)
        B = self.batch_size
        for b in range(len(self)):
            # dispatches a device-side slice+gather; nothing crosses the
            # host link, and dispatch is async so steps pipeline naturally
            yield self._materialize(
                self.images, self.labels, perm, np.int32(b * B)
            )


class StagingPool:
    """Shape-keyed pool of reusable host staging buffers.

    The serve hot path assembled every dispatched batch into a FRESH
    allocation (the micro-batcher's ``np.concatenate``, the engine's
    per-request pad buffer) feeding the same H2D put both
    ``put_sharded_array`` callers make. Shape-bucketed serving means the
    set of batch shapes is tiny and fixed, so those allocations are pure
    allocator churn: this pool hands the SAME buffers back out,
    round-robin per shape, and the batch-assembly copy writes into warm,
    page-resident memory (the host-side analogue of a pinned staging
    buffer — on runtimes with real pinned host allocation this is where
    it would live).

    Lifetime contract (the reason the pool is explicit acquire/release
    and not hidden inside ``put_sharded_array``): a buffer may be
    released only once NOTHING will read it again — for the serving
    engine that is after the bucket call's D2H fetch completes, which
    also covers any zero-copy ``device_put`` aliasing the host buffer.
    The train/eval ``put_global`` caller deliberately stays un-pooled:
    its batch outlives the put into a step whose completion the loader
    never observes, so there is no safe release point there.

    Thread-safe; at most ``max_per_shape`` buffers are retained per
    shape (excess releases are dropped to the allocator), bounding the
    arena even if a caller leaks acquisitions.
    """

    def __init__(self, max_per_shape: int = 4, registry=None):
        self.max_per_shape = int(max_per_shape)
        self._lock = threading.Lock()
        self._free: dict = {}  # (shape, dtype-str) -> [ndarray, ...]
        self._c_reuse = (
            registry.counter("serve.staging_reuse")
            if registry is not None
            else None
        )

    def acquire(self, shape: Tuple[int, ...], dtype) -> np.ndarray:
        """A writable buffer of exactly (shape, dtype) — reused when one
        is free, freshly allocated otherwise. Contents are UNDEFINED:
        the caller overwrites every byte it cares about (batch rows) and
        zeroes the pad tail itself."""
        key = (tuple(int(s) for s in shape), np.dtype(dtype).str)
        with self._lock:
            bufs = self._free.get(key)
            if bufs:
                buf = bufs.pop()
                reused = True
            else:
                buf = None
                reused = False
        if reused:
            if self._c_reuse is not None:
                self._c_reuse.inc()
            return buf
        return np.empty(key[0], dtype=np.dtype(dtype))

    def release(self, buf: np.ndarray) -> None:
        """Return a buffer for reuse. Only call once no consumer (device
        transfer included) will read it again."""
        key = (tuple(buf.shape), buf.dtype.str)
        with self._lock:
            bufs = self._free.setdefault(key, [])
            if len(bufs) < self.max_per_shape:
                bufs.append(buf)


def put_sharded_array(
    x: np.ndarray, sharding: jax.sharding.Sharding
) -> jax.Array:
    """Place ONE host-materialized global array onto the mesh.

    Single-process: a plain sharded device_put. Multi-process: every
    process holds the same global array; each contributes only its
    contiguous slab and the global array is assembled from process-local
    shards. Shared by the eval/serve paths (``put_global`` and the
    serving engine's sharded batch put — including the multi-process
    mesh replica's batch ingestion, where every rank holds the full
    broadcast batch and contributes its slab; serve/mesh_replica.py) so
    the multi-process assembly arithmetic lives in exactly one place.
    """
    if jax.process_count() > 1:
        (r0, r1), (h0, h1) = local_slab(sharding, x.shape)
        xl = x[r0:r1]
        if x.ndim > 1 and (h0, h1) != (0, x.shape[1]):
            xl = np.ascontiguousarray(xl[:, h0:h1])
        return jax.make_array_from_process_local_data(
            sharding, xl, x.shape
        )
    return jax.device_put(x, sharding)


def put_global(
    x: np.ndarray,
    y: np.ndarray,
    sharding: Optional[jax.sharding.Sharding],
    label_sharding: Optional[jax.sharding.Sharding] = None,
):
    """Place a host-materialized GLOBAL batch onto the mesh.

    Single-process: a plain sharded device_put. Multi-process: every process
    holds the same global batch (e.g. the full test set, eval_batches);
    each contributes only its contiguous slice and the global array is
    assembled from process-local shards.
    """
    if label_sharding is None:
        label_sharding = sharding
    if jax.process_count() > 1 and sharding is None:
        raise ValueError("multi-process put_global requires a sharding")
    if sharding is not None:
        return (
            put_sharded_array(x, sharding),
            put_sharded_array(y, label_sharding),
        )
    return jax.device_put(x), jax.device_put(y)


def eval_batches(images: np.ndarray, labels: np.ndarray, batch_size: int):
    """Padded, unshuffled eval batches; labels padded with -1 (masked out).

    The reference evals the full unsharded test set on every rank with no
    metric reduction (main_dist.py:205-252, SURVEY.md §2.5.7); here eval is
    sharded like train and metrics are psum-reduced, with -1 padding labels
    excluded from both loss and accuracy denominators.
    """
    n = images.shape[0]
    nb = -(-n // batch_size)
    for b in range(nb):
        x = images[b * batch_size : (b + 1) * batch_size]
        y = labels[b * batch_size : (b + 1) * batch_size]
        if x.shape[0] < batch_size:
            pad = batch_size - x.shape[0]
            x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
            y = np.concatenate([y, np.full((pad,), -1, y.dtype)])
        yield x, y
