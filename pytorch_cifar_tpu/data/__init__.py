from pytorch_cifar_tpu.data.cifar10 import load_cifar10  # noqa: F401
from pytorch_cifar_tpu.data.augment import augment_batch, normalize  # noqa: F401
from pytorch_cifar_tpu.data.pipeline import Dataloader  # noqa: F401
