"""CIFAR-10 dataset loading without the torchvision dependency.

The reference uses torchvision's ``CIFAR10(download=True)`` (main.py:42-48),
which fetches the python-pickle archive and unpacks
``data_batch_1..5`` + ``test_batch``. We parse the same on-disk layout
directly with numpy, search a few conventional locations, optionally
download, and fall back to a deterministic synthetic set so the framework
runs in zero-egress environments (tests, benchmarks).

Arrays are returned in NHWC uint8 (TPU-preferred layout) + int32 labels.
"""

from __future__ import annotations

import os
import pickle
import tarfile
from typing import Tuple

import numpy as np

CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
_DIRNAME = "cifar-10-batches-py"
_BIN_DIRNAME = "cifar-10-batches-bin"

Arrays = Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]


def _parse_batch(path: str) -> Tuple[np.ndarray, np.ndarray]:
    with open(path, "rb") as f:
        d = pickle.load(f, encoding="bytes")
    # stored as (N, 3072) uint8, channel-major rows -> NHWC
    x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    y = np.asarray(d[b"labels"], dtype=np.int32)
    return np.ascontiguousarray(x), y


def _load_from_dir(batches_dir: str) -> Arrays:
    xs, ys = [], []
    for i in range(1, 6):
        x, y = _parse_batch(os.path.join(batches_dir, f"data_batch_{i}"))
        xs.append(x)
        ys.append(y)
    train_x = np.concatenate(xs)
    train_y = np.concatenate(ys)
    test_x, test_y = _parse_batch(os.path.join(batches_dir, "test_batch"))
    return train_x, train_y, test_x, test_y


def _load_from_bin_dir(bin_dir: str) -> Arrays:
    """The cifar-10-binary.tar.gz layout (3073-byte records), decoded by the
    native data plane (planar CHW -> NHWC in C++/OpenMP, with a numpy
    fallback — native/cifar_native.cpp)."""
    from pytorch_cifar_tpu.native import decode_cifar_records

    def read_records(path):
        with open(path, "rb") as f:
            buf = f.read()
        if not buf or len(buf) % 3073:
            # a partially-extracted file must not silently yield a
            # truncated dataset (same hazard _find_dataset guards for the
            # pickle layout)
            raise ValueError(
                f"{path}: size {len(buf)} is not a whole number of "
                "3073-byte CIFAR records — archive truncated?"
            )
        return decode_cifar_records(buf)

    xs, ys = [], []
    for i in range(1, 6):
        x, y = read_records(os.path.join(bin_dir, f"data_batch_{i}.bin"))
        xs.append(x)
        ys.append(y)
    test_x, test_y = read_records(os.path.join(bin_dir, "test_batch.bin"))
    return np.concatenate(xs), np.concatenate(ys), test_x, test_y


def _find_dataset(data_dir: str):
    """Returns (path, kind) for the first complete archive found; kind is
    'py' (pickle batches) or 'bin' (binary records). Each candidate root is
    probed for both layouts, including $CIFAR10_PATH."""
    roots = [data_dir, os.path.join(data_dir, "cifar10"),
             os.path.expanduser("~/data"), "/root/data"]
    required = [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]
    candidates = []
    env = os.environ.get("CIFAR10_PATH")
    if env:
        # the env var may point at the batch dir itself, either layout
        candidates += [(env, "py"), (env, "bin")]
    for r in roots:
        candidates.append((os.path.join(r, _DIRNAME), "py"))
        candidates.append((os.path.join(r, _BIN_DIRNAME), "bin"))
    for c, kind in candidates:
        suffix = ".bin" if kind == "bin" else ""
        # all six batch files must exist — a partially-extracted directory
        # (e.g. ENOSPC mid-extraction) must not be mistaken for the dataset
        if all(
            os.path.isfile(os.path.join(c, f + suffix)) for f in required
        ):
            return c, kind
    return None


def _try_download(data_dir: str):
    """Best-effort download (the reference's download=True, main.py:42)."""
    import urllib.request

    os.makedirs(data_dir, exist_ok=True)
    archive = os.path.join(data_dir, "cifar-10-python.tar.gz")
    try:
        if not os.path.exists(archive):
            urllib.request.urlretrieve(CIFAR10_URL, archive)
        with tarfile.open(archive, "r:gz") as tf:
            if hasattr(tarfile, "data_filter"):
                tf.extractall(data_dir, filter="data")
            else:  # pragma: no cover - pre-3.12
                tf.extractall(data_dir)
        return os.path.join(data_dir, _DIRNAME)
    except (tarfile.ReadError, EOFError):
        # A truncated archive from an interrupted download would otherwise
        # block every future attempt (exists -> skip re-download -> fail).
        # Only corrupt-archive errors trigger removal; transient failures
        # (disk full, permissions) must not destroy a valid archive.
        if os.path.exists(archive):
            try:
                os.remove(archive)
            except OSError:
                pass
        return None
    except Exception:
        return None


def get_mean_and_std(images: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-channel mean/std of a uint8 NHWC dataset, in [0,1] units.

    The reference ships a broken, never-called version (utils.py:16-28
    references torch.utils.data without importing torch — SURVEY.md §2.5.2)
    that also averages per-image stds rather than computing the dataset std.
    This is the working equivalent: exact dataset statistics, the same
    quantities as the hardcoded normalize constants (main.py:34).
    """
    # reduce in float64 without materializing a float64 copy of the dataset
    mean = images.mean(axis=(0, 1, 2), dtype=np.float64) / 255.0
    std = images.std(axis=(0, 1, 2), dtype=np.float64) / 255.0
    return mean.astype(np.float32), std.astype(np.float32)


def synthetic_cifar10(
    n_train: int = 2048, n_test: int = 512, seed: int = 0
) -> Arrays:
    """Deterministic class-separable stand-in with the real shapes/dtypes.

    Each class gets a fixed random 32x32x3 template; samples are the template
    plus noise, so short training runs show a decreasing loss — enough signal
    for integration tests and throughput benchmarks.
    """
    rng = np.random.RandomState(seed)
    templates = rng.randint(0, 256, size=(10, 32, 32, 3)).astype(np.float32)

    def make(n, seed_off):
        r = np.random.RandomState(seed + seed_off)
        y = r.randint(0, 10, size=n).astype(np.int32)
        noise = r.normal(0.0, 48.0, size=(n, 32, 32, 3))
        x = np.clip(templates[y] + noise, 0, 255).astype(np.uint8)
        return x, y

    train_x, train_y = make(n_train, 1)
    test_x, test_y = make(n_test, 2)
    return train_x, train_y, test_x, test_y


def load_cifar10(data_dir: str = "./data", synthetic_ok: bool = False) -> Arrays:
    """Load real CIFAR-10, or raise with remediation advice.

    ``synthetic_ok=True`` (explicit opt-in only — a silent fallback would
    make accuracy numbers meaningless) substitutes the deterministic
    synthetic set with a loud warning.
    """
    found = _find_dataset(data_dir)
    if found is None:
        path = _try_download(data_dir)
        found = (path, "py") if path is not None else None
    if found is not None:
        path, kind = found
        return _load_from_dir(path) if kind == "py" else _load_from_bin_dir(path)
    if synthetic_ok:
        import logging

        logging.getLogger(__name__).warning(
            "CIFAR-10 not found under %r and download failed; using SYNTHETIC "
            "data — accuracies will not be comparable to real CIFAR-10",
            data_dir,
        )
        return synthetic_cifar10()
    raise FileNotFoundError(
        f"CIFAR-10 not found under {data_dir!r} and download failed "
        f"(offline?). Provide the dataset: extract cifar-10-python.tar.gz "
        f"(-> cifar-10-batches-py/) or cifar-10-binary.tar.gz "
        f"(-> cifar-10-batches-bin/) under {data_dir!r}, or point "
        "CIFAR10_PATH at the batch directory. For a no-dataset smoke run "
        "pass --synthetic_data (accuracies then mean nothing)."
    )
