"""Event-loop edge: the non-blocking frontend + router I/O layer.

The threaded edge (``serve/frontend.py``'s ``ThreadingHTTPServer``, the
router's thread-per-exchange ``Replica``) spends one OS thread per
connection. That is fine for drills and collapses at production
connection counts: 10k keep-alive clients would mean 10k stacks, 10k
scheduler entries, and a context switch per byte. This module is the
same edge rebuilt on readiness notification (stdlib ``selectors`` — the
zero-dependency stance holds): single-digit threads, any number of
sockets.

Two halves, one event-loop core:

- :class:`EdgeFrontend` — a drop-in replacement for
  :class:`~pytorch_cifar_tpu.serve.frontend.ServingFrontend` (same
  constructor surface, same ``start()/stop()/url``, same routes, same
  error contract, same ``serve.http_*`` metrics) whose listener, HTTP
  parsing, and response writes all run on ONE non-blocking loop thread.
  Each connection is a small state machine (READ_HEAD -> READ_BODY ->
  DISPATCH -> WRITE): bytes arrive via ``recv_into`` a reused
  per-connection buffer, bodies accumulate into one exactly-sized
  ``bytearray`` (the PCTW frame's payload is then decoded as a zero-copy
  view over it), and responses leave through a memoryview write queue
  that survives partial ``send``s. The blocking work — request decode,
  ``backend.predict`` (micro-batcher or router), response encode — runs
  on a small off-loop worker pool; completions re-arm the loop through a
  wakeup pipe. Answers are bit-identical to the threaded frontend across
  both wire encodings (same decode/encode functions, same bytes).
- :class:`EdgePool` — the router's event transport: instead of
  one-thread-one-exchange through ``http.client``, every replica gets a
  non-blocking connection pool multiplexed on one shared loop. In-flight
  exchanges are request-id-tagged in the pool's pending table; caller
  threads block on a per-exchange event (the router's hedging, eviction,
  and status classification code is unchanged — it only ever sees
  ``(status, payload)`` or :class:`ReplicaError`-shaped failures).

**Edge protections** — enforced BEFORE a request costs allocation or a
worker (SERVING.md "Event-loop edge"):

- per-client token-bucket rate limiting (``rate_limit_rps``/
  ``rate_burst``, keyed by client IP): an over-budget request head is
  answered 429 and never decoded;
- slow-loris read deadlines (``read_deadline_s``): a connection that
  STARTS a request and then trickles it is closed at the deadline —
  idle keep-alive connections are unaffected;
- oversized-frame rejection from the header alone: a binary
  Content-Length beyond :func:`wire.max_request_bytes` (or any body
  beyond the JSON cap) is 400'd before the body is read, and a PCTW
  frame's ``n`` is checked the moment its 24 header bytes arrive —
  mid-body, before the payload accumulates;
- load-shed tiers wired to the priority lanes (``shed_pending`` /
  ``shed_pending_bulk``): when the dispatch backlog passes the bulk
  threshold, bulk-priority requests are shed with 429 while interactive
  traffic still flows; past the interactive threshold everything sheds.
  Priority is read from the frame flags (binary) or a cheap body scan
  (JSON) — no full decode on the shed path.

**Observability** (``serve.edge.*``, OBSERVABILITY.md): connections
gauge, accepts/closes/rate_limited/loris_closed/shed counters, and
read/write-ms histograms (first byte -> request complete; response
queued -> flushed), alongside the ``serve.http_*`` family the threaded
frontend emits — the ``serve.py --http_port`` report keeps its keys
whichever edge serves.

**Event-loop discipline** (graftcheck rule 18 ``blocking-in-event-loop``
polices this statically): no function reachable from a selectors
callback may block without a bound. Cross-thread traffic is a deque +
the wakeup pipe; the only lock the loop ever holds is a micro
critical-section around deque/dict ops (every holder is a handful of
bytecode ops, so the wait is bounded — nothing like an unbounded
``acquire()``); the loop never joins, never sleeps, and every socket is
``setblocking(False)``. Worker threads may block (that is their job) —
they are reachable only as ``Thread(target=...)`` entries, never called
from the loop.
"""

from __future__ import annotations

import collections
import errno
import json
import logging
import os
import queue
import selectors
import socket
import threading
import time
from typing import Optional, Tuple

import numpy as np

from pytorch_cifar_tpu.obs import MetricsRegistry
from pytorch_cifar_tpu.obs.export import prometheus_text
from pytorch_cifar_tpu.serve import wire
from pytorch_cifar_tpu.serve.batcher import (
    BatcherClosed,
    DeadlineExceeded,
    QueueFull,
)
from pytorch_cifar_tpu.serve.frontend import (
    MAX_IMAGES_PER_REQUEST,
    decode_predict_request,
    encode_predict_response,
)
from pytorch_cifar_tpu.serve.tenancy import UnknownModel

log = logging.getLogger(__name__)

# connection read-buffer chunk: one recv_into per readiness event reads
# at most this much; a 64 KiB chunk keeps a 12 MiB binary frame under
# ~200 events without holding 64 KiB per IDLE connection (the chunk is
# loop-owned and shared — only one recv runs at a time on one loop)
_RECV_CHUNK = 64 * 1024

# JSON request bound: nested-list uint8 images cost up to 4 chars per
# byte; base64 4/3 — this cap covers the largest legal request in either
# JSON form with headroom, so an oversized Content-Length is rejected
# before the body is read whatever the encoding
_MAX_JSON_BODY = 64 * 1024 * 1024

_CRLF2 = b"\r\n\r\n"


class TokenBucket:
    """Per-client token bucket: ``rate`` tokens/s refill, ``burst``
    capacity. ``allow(key, now)`` spends one token or answers False.
    Loop-thread-only (no locking); stale clients are pruned so 10k
    one-shot clients do not grow the table forever."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self._state: dict = {}  # key -> [tokens, last_ts]

    def allow(self, key, now: float) -> bool:
        if self.rate <= 0:
            return True
        st = self._state.get(key)
        if st is None:
            st = self._state[key] = [self.burst, now]
        tokens = min(self.burst, st[0] + (now - st[1]) * self.rate)
        st[1] = now
        if tokens < 1.0:
            st[0] = tokens
            return False
        st[0] = tokens - 1.0
        if len(self._state) > 4096:
            self._prune(now)
        return True

    def _prune(self, now: float) -> None:
        full_by = self.burst / max(self.rate, 1e-9)
        dead = [
            k for k, st in self._state.items() if now - st[1] > full_by
        ]
        for k in dead:
            del self._state[k]


# connection states
_READ_HEAD = 0
_READ_BODY = 1
_BUSY = 2  # dispatched to a worker; response not yet queued
_CLOSED = 3


class _Conn:
    """One client connection's state machine (module docstring). Owned
    by the loop thread; workers only ever see the immutable request
    tuple and the connection's id."""

    __slots__ = (
        "sock", "cid", "addr", "state", "head", "body", "body_filled",
        "binary", "content_length", "keep_alive", "out", "close_after",
        "deadline", "t_first_byte", "t_write_start", "wire_checked",
        "priority_hint", "path", "method",
    )

    def __init__(self, sock, cid: int, addr):
        self.sock = sock
        self.cid = cid
        self.addr = addr
        self.state = _READ_HEAD
        self.head = bytearray()
        self.body: Optional[memoryview] = None  # over an exact bytearray
        self.body_filled = 0
        self.binary = False
        self.content_length = 0
        self.keep_alive = True
        self.out: collections.deque = collections.deque()  # memoryviews
        self.close_after = False
        self.deadline: Optional[float] = None  # slow-loris bound
        self.t_first_byte = 0.0
        self.t_write_start = 0.0
        self.wire_checked = False
        self.priority_hint = "interactive"
        self.path = ""
        self.method = ""


def _parse_head(head: bytes):
    """Minimal HTTP/1.1 request-head parse: (method, path, headers
    dict lower-cased) or raises ValueError."""
    try:
        text = head.decode("iso-8859-1")
    except UnicodeDecodeError as e:  # pragma: no cover - latin1 total
        raise ValueError(f"undecodable request head: {e}") from None
    lines = text.split("\r\n")
    parts = lines[0].split()
    if len(parts) != 3 or not parts[2].startswith("HTTP/1."):
        raise ValueError(f"malformed request line {lines[0]!r}")
    headers = {}
    for ln in lines[1:]:
        if not ln:
            continue
        name, sep, value = ln.partition(":")
        if not sep:
            raise ValueError(f"malformed header line {ln!r}")
        headers[name.strip().lower()] = value.strip()
    return parts[0], parts[1], headers


def _http_response(
    code: int, body: bytes, ctype: str, keep_alive: bool
) -> bytes:
    reason = {
        200: "OK", 400: "Bad Request", 404: "Not Found",
        405: "Method Not Allowed", 429: "Too Many Requests",
        500: "Internal Server Error", 503: "Service Unavailable",
        504: "Gateway Timeout",
    }.get(code, "Error")
    head = (
        f"HTTP/1.1 {code} {reason}\r\n"
        f"Server: pct-serve-edge\r\n"
        f"Content-Type: {ctype}\r\n"
        f"Content-Length: {len(body)}\r\n"
        f"Connection: {'keep-alive' if keep_alive else 'close'}\r\n"
        "\r\n"
    ).encode("ascii")
    return head + body


class EdgeFrontend:
    """The event-loop HTTP frontend (module docstring). Same surface as
    :class:`~pytorch_cifar_tpu.serve.frontend.ServingFrontend`; the
    extra knobs are the edge protections."""

    def __init__(
        self,
        backend,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        registry: Optional[MetricsRegistry] = None,
        image_shape: Tuple[int, int, int] = (32, 32, 3),
        workers: int = 4,
        rate_limit_rps: float = 0.0,
        rate_burst: float = 0.0,
        read_deadline_s: float = 10.0,
        shed_pending: int = 256,
        shed_pending_bulk: int = 64,
    ):
        self.backend = backend
        self.registry = registry if registry is not None else MetricsRegistry()
        self.image_shape = tuple(
            getattr(getattr(backend, "engine", None), "image_shape", None)
            or image_shape
        )
        self.read_deadline_s = float(read_deadline_s)
        self.shed_pending = int(shed_pending)
        self.shed_pending_bulk = int(shed_pending_bulk)
        self._bucket = TokenBucket(
            rate_limit_rps, rate_burst or max(rate_limit_rps, 1.0)
        )
        # the serve.http_* family the threaded frontend emits — report
        # assembly (serve.py) and dashboards see one edge, not two
        self.c_http_requests = self.registry.counter("serve.http_requests")
        self.c_http_images = self.registry.counter("serve.http_images")
        self.c_http_errors = self.registry.counter("serve.http_errors")
        self.h_http_ms = self.registry.histogram("serve.http_ms")
        self.c_wire_requests = self.registry.counter("serve.wire_requests")
        self.h_wire_decode = self.registry.histogram("serve.wire_decode_ms")
        # the serve.edge.* family (OBSERVABILITY.md "event-loop edge")
        self.g_connections = self.registry.gauge("serve.edge.connections")
        self.c_accepts = self.registry.counter("serve.edge.accepts")
        self.c_closes = self.registry.counter("serve.edge.closes")
        self.c_rate_limited = self.registry.counter("serve.edge.rate_limited")
        self.c_loris_closed = self.registry.counter("serve.edge.loris_closed")
        self.c_shed = self.registry.counter("serve.edge.shed")
        self.h_read_ms = self.registry.histogram("serve.edge.read_ms")
        self.h_write_ms = self.registry.histogram("serve.edge.write_ms")
        # model routing — identical resolution to ServingFrontend
        self.backend_routes_models = bool(
            getattr(backend, "supports_model_routing", False)
        )
        self.served_model = None
        b = backend
        for _ in range(4):
            eng = getattr(b, "engine", None)
            if eng is not None and hasattr(eng, "model_name"):
                self.served_model = eng.model_name
                break
            b = getattr(b, "backend", None)
            if b is None:
                break

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(1024)
        self._listener.setblocking(False)
        self.host, self.port = self._listener.getsockname()[:2]

        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        os.set_blocking(self._wake_w, False)
        self._recv_buf = bytearray(_RECV_CHUNK)  # loop-owned, reused
        self._recv_view = memoryview(self._recv_buf)
        self._conns: dict = {}  # cid -> _Conn
        self._by_sock: dict = {}  # id(sock) -> _Conn (selector key map)
        self._next_cid = 0
        self._pending = 0  # dispatched-to-worker, not yet answered
        # cross-thread channels: deque append/popleft are GIL-atomic, so
        # loop callbacks touch them lock-free (rule 18)
        self._done: collections.deque = collections.deque()
        self._work_q: queue.Queue = queue.Queue()
        self._draining = False
        self._drain_deadline = 0.0
        self._n_workers = max(1, int(workers))
        # thread handles: mutated only by start()/stop() under _lock
        # (graftcheck unlocked-shared-mutation; the loop never takes it)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._workers: list = []

    # -- lifecycle -----------------------------------------------------

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def backend_version(self) -> int:
        return int(getattr(self.backend, "engine_version", 0))

    def start(self) -> "EdgeFrontend":
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._sel.register(
                    self._listener, selectors.EVENT_READ, self._on_accept
                )
                self._sel.register(
                    self._wake_r, selectors.EVENT_READ, self._on_wakeup
                )
                self._workers = [
                    threading.Thread(
                        target=self._worker,
                        name=f"edge-worker-{i}:{self.port}",
                        daemon=False,
                    )
                    for i in range(self._n_workers)
                ]
                for t in self._workers:
                    t.start()
                self._thread = threading.Thread(
                    target=self._loop,
                    name=f"edge-loop:{self.port}",
                    daemon=False,
                )
                self._thread.start()
        return self

    def stop(self, drain_timeout_s: float = 30.0) -> None:
        """Graceful drain: stop accepting, let in-flight requests finish
        and their responses flush, close every connection, join the loop
        and the workers. Idempotent; after return no edge thread or fd
        survives (pinned by tests/test_edge.py)."""
        with self._lock:
            t = self._thread
            workers = self._workers
            self._thread = None
            self._workers = []
        if t is None:
            return
        with self._lock:
            self._done.append(("drain", float(drain_timeout_s)))
        self._wake()
        t.join()
        for _ in workers:
            self._work_q.put(None)
        for w in workers:
            w.join()
        self._sel.close()
        for fd in (self._wake_r, self._wake_w):
            try:
                os.close(fd)
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _wake(self) -> None:
        try:
            os.write(self._wake_w, b"\x00")
        except (BlockingIOError, OSError):
            pass  # pipe full: the loop is already waking up

    # -- the loop ------------------------------------------------------

    def _loop(self) -> None:
        while True:
            timeout = self._next_timeout()
            try:
                events = self._sel.select(timeout)
            except OSError:  # pragma: no cover - selector torn down
                break
            for key, mask in events:
                callback = key.data
                try:
                    callback(key, mask)
                except Exception:
                    log.exception("edge loop callback failed")
            now = time.monotonic()
            self._expire_loris(now)
            if self._draining and self._drain_done(now):
                break
        self._teardown()

    def _next_timeout(self) -> float:
        timeout = 0.5
        now = time.monotonic()
        for conn in self._conns.values():
            if conn.deadline is not None:
                timeout = min(timeout, max(0.0, conn.deadline - now))
        if self._draining:
            timeout = min(timeout, 0.02)
        return timeout

    def _expire_loris(self, now: float) -> None:
        expired = [
            c for c in self._conns.values()
            if c.deadline is not None and now >= c.deadline
        ]
        for conn in expired:
            # a started-but-trickling request: the slow-loris shape —
            # close before it pins buffer + table space any longer
            self.c_loris_closed.inc()
            self._close_conn(conn)

    def _drain_done(self, now: float) -> bool:
        if now >= self._drain_deadline:
            return True
        busy = any(
            c.state == _BUSY or c.out for c in self._conns.values()
        )
        return not busy and self._pending == 0

    def _teardown(self) -> None:
        for conn in list(self._conns.values()):
            self._close_conn(conn)
        try:
            self._sel.unregister(self._listener)
        except (KeyError, ValueError, OSError):
            pass
        try:
            self._sel.unregister(self._wake_r)
        except (KeyError, ValueError, OSError):
            pass

    # -- loop callbacks (registered as selector data; rule 18 scope) ---

    def _on_accept(self, key, mask) -> None:
        # accept until the backlog is dry: one readiness event can cover
        # many queued connects under a flood
        while True:
            try:
                sock, addr = self._listener.accept()
            except BlockingIOError:
                return
            except OSError:
                return
            if self._draining:
                sock.close()
                continue
            sock.setblocking(False)
            try:
                sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            except OSError:
                pass
            self._next_cid += 1
            conn = _Conn(sock, self._next_cid, addr)
            with self._lock:
                self._conns[conn.cid] = conn
                self._by_sock[id(sock)] = conn
            self._sel.register(
                sock, selectors.EVENT_READ, self._on_conn_event
            )
            self.c_accepts.inc()
            self.g_connections.set(len(self._conns))

    def _on_wakeup(self, key, mask) -> None:
        try:
            os.read(self._wake_r, 4096)
        except (BlockingIOError, OSError):
            pass
        while self._done:
            with self._lock:
                item = self._done.popleft()
            if item[0] == "drain":
                self._draining = True
                self._drain_deadline = time.monotonic() + item[1]
                try:
                    self._sel.unregister(self._listener)
                except (KeyError, ValueError, OSError):
                    pass
                self._listener.close()
                # idle keep-alive connections will never send again in
                # time we care about: close them now, keep busy ones
                for conn in list(self._conns.values()):
                    if conn.state == _READ_HEAD and not conn.out:
                        if not conn.head:
                            self._close_conn(conn)
                continue
            _tag, cid, payload = item
            self._pending -= 1
            conn = self._conns.get(cid)
            if conn is None:
                continue  # client hung up while the worker computed
            self._queue_response(conn, payload)

    def _on_conn_event(self, key, mask) -> None:
        conn = self._by_sock.get(id(key.fileobj))
        if conn is None:
            try:
                self._sel.unregister(key.fileobj)
            except (KeyError, ValueError, OSError):
                pass
            return
        if mask & selectors.EVENT_WRITE:
            self._on_writable(conn)
        if conn.state != _CLOSED and mask & selectors.EVENT_READ:
            self._on_readable(conn)

    # -- connection I/O (loop thread) ----------------------------------

    def _on_readable(self, conn: _Conn) -> None:
        try:
            n = conn.sock.recv_into(self._recv_view)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(conn)
            return
        if n == 0:
            self._close_conn(conn)
            return
        if conn.state == _BUSY:
            # pipelined bytes ahead of the in-flight response: buffer
            # them in head; the parser resumes after the response flush
            conn.head += self._recv_view[:n]
            return
        self._feed(conn, self._recv_view[:n])

    def _feed(self, conn: _Conn, data) -> None:
        """Advance the state machine with freshly received bytes."""
        if conn.state == _CLOSED or conn.close_after:
            return  # the connection is on its way out; drop the bytes
        now = time.monotonic()
        if conn.state == _READ_HEAD:
            if not conn.head:
                conn.t_first_byte = now
                conn.deadline = now + self.read_deadline_s
            conn.head += data
            idx = conn.head.find(_CRLF2)
            if idx < 0:
                if len(conn.head) > 64 * 1024:
                    self._send_error(
                        conn, 400, "request head exceeds 64 KiB",
                        close=True,
                    )
                return
            head = bytes(conn.head[:idx])
            rest = conn.head[idx + 4:]
            conn.head = bytearray()
            if not self._begin_request(conn, head, now):
                return
            if conn.state == _READ_BODY and rest:
                self._feed_body(conn, rest)
            elif conn.state == _READ_HEAD and rest:
                self._feed(conn, rest)
            elif rest:
                conn.head += rest  # pipelined past a dispatched request
        elif conn.state == _READ_BODY:
            self._feed_body(conn, data)

    def _begin_request(self, conn: _Conn, head: bytes, now: float) -> bool:
        """Parse a complete request head; route GETs, arm a body read
        for POST /predict. Returns False when the connection died."""
        try:
            method, path, headers = _parse_head(head)
        except ValueError as e:
            self._send_error(conn, 400, str(e), close=True)
            return False
        conn.method, conn.path = method, path
        conn.keep_alive = (
            headers.get("connection", "keep-alive").lower() != "close"
        )
        self.c_http_requests.inc()
        if method == "GET":
            conn.deadline = None
            self._handle_get(conn, path)
            return conn.state != _CLOSED
        if method != "POST":
            self._send_error(conn, 405, f"unsupported method {method!r}")
            return conn.state != _CLOSED
        if path != "/predict":
            self._send_error(conn, 404, f"unknown path {path!r}")
            return conn.state != _CLOSED
        if self._draining:
            self._send_error(conn, 503, "frontend is draining")
            return conn.state != _CLOSED
        # protection 1: per-client rate limit — answered from the head,
        # before the body is read or a byte of it is allocated
        if not self._bucket.allow(conn.addr[0], now):
            self.c_rate_limited.inc()
            self._send_error(
                conn, 429,
                "rate limit exceeded for this client; back off and retry",
                drop_body=True,
            )
            return conn.state != _CLOSED
        try:
            length = int(headers.get("content-length", "0"))
        except ValueError:
            self._send_error(conn, 400, "bad Content-Length", close=True)
            return False
        if length <= 0:
            self._send_error(conn, 400, "missing request body")
            return conn.state != _CLOSED
        conn.binary = wire.is_binary_content_type(
            headers.get("content-type")
        )
        # protection 2: oversized rejection BEFORE the body is read —
        # the binary bound is exact (wire.max_request_bytes); the JSON
        # bound covers the largest legal request with headroom
        cap = (
            wire.max_request_bytes(self.image_shape, MAX_IMAGES_PER_REQUEST)
            if conn.binary
            else _MAX_JSON_BODY
        )
        if length > cap:
            self._send_error(
                conn, 400,
                (
                    f"binary frame of {length} bytes exceeds the "
                    f"{MAX_IMAGES_PER_REQUEST}-image request cap"
                    if conn.binary
                    else f"request body of {length} bytes exceeds the "
                    f"{cap}-byte cap"
                ),
                close=True,
            )
            return False
        conn.content_length = length
        conn.body = memoryview(bytearray(length))
        conn.body_filled = 0
        conn.wire_checked = False
        conn.state = _READ_BODY
        conn.deadline = now + self.read_deadline_s
        return True

    def _feed_body(self, conn: _Conn, data) -> None:
        take = min(len(data), conn.content_length - conn.body_filled)
        conn.body[conn.body_filled:conn.body_filled + take] = data[:take]
        conn.body_filled += take
        if (
            conn.binary
            and not conn.wire_checked
            and conn.body_filled >= wire.HEADER_SIZE
        ):
            # protection 2b: the PCTW header is in hand — reject a bad
            # n/shape NOW, mid-body, before the payload accumulates
            conn.wire_checked = True
            if not self._check_wire_header(conn):
                return
        if conn.body_filled < conn.content_length:
            return
        leftovers = bytes(data[take:]) if take < len(data) else b""
        self._complete_request(conn, leftovers)

    def _check_wire_header(self, conn: _Conn) -> bool:
        hdr = bytes(conn.body[:wire.HEADER_SIZE])
        try:
            magic, version, frame, dtype, flags, n, h, w, c = (
                wire._HEADER.unpack(hdr)
            )
        except Exception:
            self._send_error(conn, 400, "undecodable frame header",
                             close=True)
            return False
        if magic != wire.MAGIC:
            self._send_error(
                conn, 400,
                f"bad magic {magic!r} (expected {wire.MAGIC!r})",
                close=True,
            )
            return False
        if n > MAX_IMAGES_PER_REQUEST:
            self._send_error(
                conn, 400,
                f"frame carries {n} images; a single request is capped "
                f"at {MAX_IMAGES_PER_REQUEST}",
                close=True,
            )
            return False
        conn.priority_hint = (
            "bulk" if flags & wire.FLAG_BULK else "interactive"
        )
        return True

    def _complete_request(self, conn: _Conn, leftovers: bytes) -> None:
        self.h_read_ms.observe(
            (time.monotonic() - conn.t_first_byte) * 1e3
        )
        conn.deadline = None
        body = conn.body.obj if conn.body is not None else b""
        conn.body = None
        if not conn.binary:
            # cheap priority hint for the shed decision — a real decode
            # happens off-loop only if the request is admitted
            conn.priority_hint = (
                "bulk"
                if b'"priority"' in body and b'"bulk"' in body
                else "interactive"
            )
        if leftovers:
            conn.head += leftovers  # before any synchronous flush/resume
        # protection 3: load-shed tiers — bulk sheds first, interactive
        # holds on until the higher bound; both BEFORE a worker is spent
        backlog = self._pending
        if backlog >= self.shed_pending or (
            conn.priority_hint == "bulk"
            and backlog >= self.shed_pending_bulk
        ):
            self.c_shed.inc()
            # the body is fully consumed: rearm the parser BEFORE the
            # 429 is queued, or the next keep-alive request would land
            # in _feed_body against a None body
            conn.state = _READ_HEAD
            conn.content_length = 0
            conn.body_filled = 0
            self._send_error(
                conn, 429,
                f"edge shedding load ({backlog} requests pending)",
            )
        else:
            conn.state = _BUSY
            self._pending += 1
            t0 = time.monotonic()
            self._work_q.put_nowait(
                (conn.cid, bytes(body), conn.binary, conn.keep_alive, t0)
            )

    def _handle_get(self, conn: _Conn, path: str) -> None:
        # GET routes answer from worker threads too (health may call a
        # blocking backend), except /metrics which is a pure snapshot
        if path == "/metrics":
            body = prometheus_text(self.registry.snapshot()).encode()
            self._queue_response(
                conn,
                _http_response(
                    200, body, "text/plain; version=0.0.4",
                    conn.keep_alive,
                ),
            )
            return
        if path == "/healthz":
            conn.state = _BUSY
            self._pending += 1
            self._work_q.put_nowait(
                (conn.cid, None, False, conn.keep_alive, time.monotonic())
            )
            return
        if path == "/predict":
            self._send_error(conn, 405, "POST /predict (GET not supported)")
            return
        self._send_error(conn, 404, f"unknown path {path!r}")

    # -- responses (loop thread) ---------------------------------------

    def _send_error(
        self, conn: _Conn, code: int, message: str,
        close: bool = False, drop_body: bool = False,
    ) -> None:
        self.c_http_errors.inc()
        self.registry.counter(f"serve.http_{code}").inc()
        body = json.dumps({"error": message, "status": code}).encode()
        keep = conn.keep_alive and not close
        if drop_body:
            # rate-limited POST: the body is on the wire but unread; a
            # keep-alive parse would see it as the next request head, so
            # the connection closes after the 429 flushes
            keep = False
        conn.close_after = conn.close_after or not keep
        self._queue_response(
            conn, _http_response(code, body, "application/json", keep)
        )
        if close:
            conn.close_after = True

    def _queue_response(self, conn: _Conn, payload: bytes) -> None:
        if conn.state == _CLOSED:
            return
        # a response to a Connection: close request advertises close in
        # its header; the flush path must actually close the socket
        conn.close_after = conn.close_after or not conn.keep_alive
        if not conn.out:
            conn.t_write_start = time.monotonic()
        conn.out.append(memoryview(payload))
        if conn.state == _BUSY:
            conn.state = _READ_HEAD
        self._arm(conn)
        self._on_writable(conn)  # opportunistic: most flushes are one send

    def _arm(self, conn: _Conn) -> None:
        mask = selectors.EVENT_READ
        if conn.out:
            mask |= selectors.EVENT_WRITE
        try:
            self._sel.modify(conn.sock, mask, self._on_conn_event)
        except (KeyError, ValueError, OSError):
            pass

    def _on_writable(self, conn: _Conn) -> None:
        while conn.out:
            mv = conn.out[0]
            try:
                sent = conn.sock.send(mv)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._close_conn(conn)
                return
            if sent < len(mv):
                conn.out[0] = mv[sent:]  # partial write: resume later
                break
            conn.out.popleft()
        if not conn.out:
            self.h_write_ms.observe(
                (time.monotonic() - conn.t_write_start) * 1e3
            )
            if conn.close_after or (self._draining and conn.state != _BUSY):
                self._close_conn(conn)
                return
            self._arm(conn)
            # response flushed: resume the parser over pipelined bytes
            if conn.state == _READ_HEAD and conn.head:
                buffered = bytes(conn.head)
                conn.head = bytearray()
                self._feed(conn, buffered)
        else:
            self._arm(conn)

    def _close_conn(self, conn: _Conn) -> None:
        if conn.state == _CLOSED:
            return
        conn.state = _CLOSED
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        with self._lock:
            self._conns.pop(conn.cid, None)
            self._by_sock.pop(id(conn.sock), None)
        conn.out.clear()
        self.c_closes.inc()
        self.g_connections.set(len(self._conns))

    # -- worker threads (may block; never loop-reachable) --------------

    def _worker(self) -> None:
        while True:
            item = self._work_q.get()
            if item is None:
                return
            cid, body, binary, keep_alive, t0 = item
            try:
                if body is None:
                    payload = self._do_health(keep_alive)
                else:
                    payload = self._do_predict(body, binary, keep_alive, t0)
            except Exception as e:  # a broken handler must not kill a worker
                log.exception("edge worker failed")
                payload = self._error_payload(
                    500, f"{type(e).__name__}: {e}", keep_alive
                )
            with self._lock:
                self._done.append(("done", cid, payload))
            self._wake()

    def _do_health(self, keep_alive: bool) -> bytes:
        try:
            health = self.backend.health()
        except Exception as e:
            health = {"status": "error", "error": str(e)}
        if self._draining:
            health = {**health, "status": "draining"}
        code = 200 if health.get("status") == "ok" else 503
        return _http_response(
            code, json.dumps(health).encode(), "application/json",
            keep_alive,
        )

    def _error_payload(
        self, code: int, message: str, keep_alive: bool
    ) -> bytes:
        self.c_http_errors.inc()
        self.registry.counter(f"serve.http_{code}").inc()
        body = json.dumps({"error": message, "status": code}).encode()
        return _http_response(code, body, "application/json", keep_alive)

    def _do_predict(
        self, body: bytes, binary: bool, keep_alive: bool, t0: float
    ) -> bytes:
        t_dec = time.perf_counter()
        try:
            if binary:
                x, deadline_ms, priority, json_resp, model = (
                    wire.decode_request(
                        body, self.image_shape, MAX_IMAGES_PER_REQUEST
                    )
                )
                encoding = "json" if json_resp else "binary"
                self.c_wire_requests.inc()
            else:
                x, deadline_ms, priority, encoding, model = (
                    decode_predict_request(body, self.image_shape)
                )
        except (wire.WireError, ValueError) as e:
            return self._error_payload(400, str(e), keep_alive)
        self.h_wire_decode.observe((time.perf_counter() - t_dec) * 1e3)
        if model is not None and not self.backend_routes_models:
            if model != self.served_model:
                return self._error_payload(
                    404,
                    f"model {model!r} is not served here "
                    f"(this replica serves {self.served_model!r})",
                    keep_alive,
                )
            model = None
        try:
            if model is not None:
                logits = self.backend.predict(
                    x, deadline_ms=deadline_ms, priority=priority,
                    model=model,
                )
            else:
                logits = self.backend.predict(
                    x, deadline_ms=deadline_ms, priority=priority
                )
        except UnknownModel as e:
            return self._error_payload(404, str(e), keep_alive)
        except QueueFull as e:
            return self._error_payload(429, str(e), keep_alive)
        except DeadlineExceeded as e:
            return self._error_payload(504, str(e), keep_alive)
        except BatcherClosed as e:
            return self._error_payload(503, str(e), keep_alive)
        except ValueError as e:
            return self._error_payload(400, str(e), keep_alive)
        except Exception as e:
            log.exception("backend failure")
            return self._error_payload(
                500, f"{type(e).__name__}: {e}", keep_alive
            )
        self.c_http_images.inc(int(x.shape[0]))
        self.h_http_ms.observe((time.monotonic() - t0) * 1e3)
        if encoding == "binary":
            return _http_response(
                200,
                wire.encode_response(logits, self.backend_version()),
                wire.CONTENT_TYPE,
                keep_alive,
            )
        return _http_response(
            200,
            json.dumps(
                encode_predict_response(
                    logits, encoding, self.backend_version()
                )
            ).encode(),
            "application/json",
            keep_alive,
        )


# ---------------------------------------------------------------------
# EdgePool: the router's event transport
# ---------------------------------------------------------------------


class _Exchange:
    """One in-flight request-id-tagged HTTP exchange: the caller thread
    blocks on ``event``; the loop fills ``status``/``payload`` or
    ``error`` and sets it."""

    __slots__ = (
        "xid", "host", "port", "request", "deadline", "event",
        "status", "payload", "error", "retried",
    )

    def __init__(self, xid, host, port, request: bytes, deadline: float):
        self.xid = xid
        self.host = host
        self.port = port
        self.request = request
        self.deadline = deadline
        self.event = threading.Event()
        self.status: Optional[int] = None
        self.payload: bytes = b""
        self.error: Optional[str] = None
        self.retried = False


_PC_CONNECTING = 0
_PC_WRITING = 1
_PC_READ_HEAD = 2
_PC_READ_BODY = 3
_PC_IDLE = 4


class _PoolConn:
    """One pooled replica connection: carries at most one exchange at a
    time (HTTP/1.1); the POOL multiplexes many of these per replica on
    one loop."""

    __slots__ = (
        "sock", "host", "port", "state", "ex", "out", "rbuf",
        "body", "body_filled", "content_length", "status", "reused",
    )

    def __init__(self, sock, host, port):
        self.sock = sock
        self.host = host
        self.port = port
        self.state = _PC_CONNECTING
        self.ex: Optional[_Exchange] = None
        self.out: collections.deque = collections.deque()
        self.rbuf = bytearray()
        self.body: Optional[memoryview] = None
        self.body_filled = 0
        self.content_length = 0
        self.status = 0
        self.reused = False


class EdgePool:
    """Non-blocking per-replica connection pools on one shared event
    loop (module docstring). ``exchange()`` is the blocking caller-side
    API — the frontend's worker threads and the router's probe thread
    call it exactly like ``Replica.request`` uses ``http.client`` — and
    everything socket-shaped happens on the loop thread."""

    def __init__(
        self,
        *,
        timeout_s: float = 60.0,
        max_conns_per_host: int = 64,
    ):
        self.timeout_s = float(timeout_s)
        self.max_conns_per_host = int(max_conns_per_host)
        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        os.set_blocking(self._wake_w, False)
        self._sel.register(
            self._wake_r, selectors.EVENT_READ, self._on_wakeup
        )
        self._submitted: collections.deque = collections.deque()
        self._pending: dict = {}  # xid -> _Exchange (the tag table)
        self._idle: dict = {}  # (host, port) -> [conns]
        self._conns: dict = {}  # id(sock) -> _PoolConn
        self._waiting: dict = {}  # (host, port) -> deque of exchanges
        self._next_xid = 0
        self._xid_lock = threading.Lock()
        self._stopping = False
        self._recv_buf = bytearray(_RECV_CHUNK)
        self._recv_view = memoryview(self._recv_buf)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "EdgePool":
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="edge-pool", daemon=False
                )
                self._thread.start()
        return self

    def close(self) -> None:
        with self._lock:
            t = self._thread
            self._thread = None
        if t is None:
            return
        self._submitted.append(None)  # stop sentinel
        self._wake()
        t.join()
        self._sel.close()
        for fd in (self._wake_r, self._wake_w):
            try:
                os.close(fd)
            except OSError:
                pass

    def _wake(self) -> None:
        try:
            os.write(self._wake_w, b"\x00")
        except (BlockingIOError, OSError):
            pass

    # -- caller-side API (any thread; blocks on the exchange event) ----

    def exchange(
        self,
        host: str,
        port: int,
        method: str,
        path: str,
        body: Optional[bytes] = None,
        content_type: str = "application/json",
        timeout_s: Optional[float] = None,
    ) -> Tuple[int, bytes]:
        """One HTTP exchange through the pool; returns ``(status,
        payload)`` or raises ``OSError`` on connection failure/timeout
        (the Replica wrapper maps that to :class:`ReplicaError`)."""
        bound = self.timeout_s if timeout_s is None else float(timeout_s)
        blines = [
            f"{method} {path} HTTP/1.1",
            f"Host: {host}:{port}",
            "Connection: keep-alive",
        ]
        payload = body or b""
        if payload:
            blines.append(f"Content-Type: {content_type}")
        blines.append(f"Content-Length: {len(payload)}")
        request = "\r\n".join(blines).encode("ascii") + b"\r\n\r\n" + payload
        with self._xid_lock:
            self._next_xid += 1
            xid = self._next_xid
        ex = _Exchange(
            xid, host, int(port), request, time.monotonic() + bound
        )
        with self._lock:
            started = self._thread is not None
        if not started:
            raise OSError("edge pool is not running")
        self._submitted.append(ex)
        self._wake()
        if not ex.event.wait(bound + 5.0):
            ex.error = ex.error or f"exchange timeout after {bound}s"
        if ex.error is not None:
            raise OSError(ex.error)
        assert ex.status is not None
        return ex.status, ex.payload

    # -- loop ----------------------------------------------------------

    def _loop(self) -> None:
        while True:
            timeout = self._pool_timeout()
            try:
                events = self._sel.select(timeout)
            except OSError:  # pragma: no cover
                break
            for key, mask in events:
                callback = key.data
                try:
                    callback(key, mask)
                except Exception:
                    log.exception("edge pool callback failed")
            self._expire(time.monotonic())
            if self._stopping:
                break
        self._teardown()

    def _pool_timeout(self) -> float:
        timeout = 0.5
        now = time.monotonic()
        for ex in self._pending.values():
            timeout = min(timeout, max(0.0, ex.deadline - now))
        return timeout

    def _expire(self, now: float) -> None:
        expired = [
            ex for ex in self._pending.values() if now >= ex.deadline
        ]
        for ex in expired:
            conn = next(
                (c for c in self._conns.values() if c.ex is ex), None
            )
            if conn is not None:
                self._fail_conn(
                    conn, f"{ex.host}:{ex.port}: exchange timed out"
                )
            else:
                self._resolve(
                    ex, error=f"{ex.host}:{ex.port}: exchange timed out"
                )

    def _teardown(self) -> None:
        for conn in list(self._conns.values()):
            if conn.ex is not None:
                self._resolve(conn.ex, error="edge pool closed")
            self._drop_conn(conn)
        for dq in self._waiting.values():
            while dq:
                self._resolve(dq.popleft(), error="edge pool closed")
        for ex in list(self._pending.values()):
            self._resolve(ex, error="edge pool closed")

    def _resolve(
        self, ex: _Exchange, *, error: Optional[str] = None
    ) -> None:
        with self._lock:
            self._pending.pop(ex.xid, None)
        if error is not None and ex.error is None:
            ex.error = error
        ex.event.set()

    # -- loop callbacks ------------------------------------------------

    def _on_wakeup(self, key, mask) -> None:
        try:
            os.read(self._wake_r, 4096)
        except (BlockingIOError, OSError):
            pass
        while self._submitted:
            ex = self._submitted.popleft()
            if ex is None:
                self._stopping = True
                continue
            with self._lock:
                self._pending[ex.xid] = ex
            self._assign(ex)

    def _assign(self, ex: _Exchange) -> None:
        hp = (ex.host, ex.port)
        idle = self._idle.get(hp)
        while idle:
            conn = idle.pop()
            if id(conn.sock) in self._conns:
                self._start_exchange(conn, ex)
                return
        n_here = sum(
            1 for c in self._conns.values()
            if (c.host, c.port) == hp
        )
        if n_here >= self.max_conns_per_host:
            with self._lock:
                self._waiting.setdefault(hp, collections.deque()).append(ex)
            return
        try:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setblocking(False)
            try:
                sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            except OSError:
                pass
            rc = sock.connect_ex((ex.host, ex.port))
        except OSError as e:
            self._resolve(ex, error=f"{ex.host}:{ex.port}: {e}")
            return
        if rc not in (0, errno.EINPROGRESS, errno.EWOULDBLOCK):
            sock.close()
            self._resolve(
                ex,
                error=f"{ex.host}:{ex.port}: connect failed "
                f"({errno.errorcode.get(rc, rc)})",
            )
            return
        conn = _PoolConn(sock, ex.host, ex.port)
        conn.ex = ex
        conn.out.append(memoryview(ex.request))
        with self._lock:
            self._conns[id(sock)] = conn
        self._sel.register(
            sock,
            selectors.EVENT_READ | selectors.EVENT_WRITE,
            self._on_conn_event,
        )

    def _start_exchange(self, conn: _PoolConn, ex: _Exchange) -> None:
        conn.ex = ex
        conn.state = _PC_WRITING
        conn.reused = True
        conn.rbuf = bytearray()
        conn.status = 0
        conn.body = None
        conn.body_filled = 0
        conn.out.append(memoryview(ex.request))
        self._arm(conn)
        self._on_conn_writable(conn)

    def _arm(self, conn: _PoolConn) -> None:
        mask = selectors.EVENT_READ
        if conn.out:
            mask |= selectors.EVENT_WRITE
        try:
            self._sel.modify(conn.sock, mask, self._on_conn_event)
        except (KeyError, ValueError, OSError):
            pass

    def _on_conn_event(self, key, mask) -> None:
        conn = self._conns.get(id(key.fileobj))
        if conn is None:
            try:
                self._sel.unregister(key.fileobj)
            except (KeyError, ValueError, OSError):
                pass
            return
        if mask & selectors.EVENT_WRITE:
            if conn.state == _PC_CONNECTING:
                err = conn.sock.getsockopt(
                    socket.SOL_SOCKET, socket.SO_ERROR
                )
                if err != 0:
                    self._fail_conn(
                        conn,
                        f"{conn.host}:{conn.port}: connect failed "
                        f"({errno.errorcode.get(err, err)})",
                    )
                    return
                conn.state = _PC_WRITING
            self._on_conn_writable(conn)
        if id(conn.sock) in self._conns and mask & selectors.EVENT_READ:
            self._on_conn_readable(conn)

    def _on_conn_writable(self, conn: _PoolConn) -> None:
        while conn.out:
            mv = conn.out[0]
            try:
                sent = conn.sock.send(mv)
            except (BlockingIOError, InterruptedError):
                break
            except OSError as e:
                self._fail_conn(conn, f"{conn.host}:{conn.port}: {e}")
                return
            if sent < len(mv):
                conn.out[0] = mv[sent:]
                break
            conn.out.popleft()
        if not conn.out and conn.state == _PC_WRITING:
            conn.state = _PC_READ_HEAD
        self._arm(conn)

    def _on_conn_readable(self, conn: _PoolConn) -> None:
        try:
            n = conn.sock.recv_into(self._recv_view)
        except (BlockingIOError, InterruptedError):
            return
        except OSError as e:
            self._fail_conn(conn, f"{conn.host}:{conn.port}: {e}")
            return
        if n == 0:
            # server closed: a stale keep-alive conn that died before
            # any response byte gets ONE transparent retry on a fresh
            # connection (same contract as Replica's reconnect)
            self._fail_conn(
                conn, f"{conn.host}:{conn.port}: connection closed"
            )
            return
        data = self._recv_view[:n]
        if conn.state == _PC_READ_HEAD:
            conn.rbuf += data
            idx = conn.rbuf.find(_CRLF2)
            if idx < 0:
                return
            head = bytes(conn.rbuf[:idx])
            rest = conn.rbuf[idx + 4:]
            conn.rbuf = bytearray()
            try:
                status, length = self._parse_response_head(head)
            except ValueError as e:
                self._fail_conn(conn, f"{conn.host}:{conn.port}: {e}")
                return
            conn.status = status
            conn.content_length = length
            conn.body = memoryview(bytearray(length))
            conn.body_filled = 0
            conn.state = _PC_READ_BODY
            if rest:
                self._pool_feed_body(conn, rest)
            elif length == 0:
                self._finish_exchange(conn)
        elif conn.state == _PC_READ_BODY:
            self._pool_feed_body(conn, data)

    @staticmethod
    def _parse_response_head(head: bytes) -> Tuple[int, int]:
        lines = head.decode("iso-8859-1").split("\r\n")
        parts = lines[0].split(None, 2)
        if len(parts) < 2 or not parts[0].startswith("HTTP/1."):
            raise ValueError(f"malformed status line {lines[0]!r}")
        status = int(parts[1])
        length = 0
        for ln in lines[1:]:
            name, _, value = ln.partition(":")
            if name.strip().lower() == "content-length":
                length = int(value.strip())
        return status, length

    def _pool_feed_body(self, conn: _PoolConn, data) -> None:
        take = min(len(data), conn.content_length - conn.body_filled)
        conn.body[conn.body_filled:conn.body_filled + take] = data[:take]
        conn.body_filled += take
        if conn.body_filled >= conn.content_length:
            self._finish_exchange(conn)

    def _finish_exchange(self, conn: _PoolConn) -> None:
        ex = conn.ex
        conn.ex = None
        conn.state = _PC_IDLE
        conn.reused = True
        if ex is not None and ex.xid in self._pending:
            ex.status = conn.status
            ex.payload = bytes(conn.body.obj) if conn.body else b""
            self._resolve(ex)
        conn.body = None
        hp = (conn.host, conn.port)
        nxt = self._next_waiting(hp)
        if nxt is not None:
            self._start_exchange(conn, nxt)
        else:
            self._idle.setdefault(hp, []).append(conn)
            self._arm(conn)

    def _next_waiting(self, hp) -> Optional[_Exchange]:
        waiting = self._waiting.get(hp)
        while waiting:
            ex = waiting.popleft()
            if ex.xid in self._pending:  # skip already-timed-out waiters
                return ex
        return None

    def _fail_conn(self, conn: _PoolConn, why: str) -> None:
        ex = conn.ex
        conn.ex = None
        self._drop_conn(conn)
        if ex is None or ex.xid not in self._pending:
            return
        no_response_bytes = (
            conn.status == 0 and not conn.rbuf and conn.body_filled == 0
        )
        if (
            conn.reused and no_response_bytes and not ex.retried
            and time.monotonic() < ex.deadline
        ):
            # stale keep-alive: retry ONCE on a fresh connection with
            # the complete buffered request (never a half-consumed one)
            # — but only while the caller is still waiting; a retry of
            # an expired exchange just burns replica capacity
            ex.retried = True
            self._assign(ex)
            return
        self._resolve(ex, error=why)

    def _drop_conn(self, conn: _PoolConn) -> None:
        with self._lock:
            self._conns.pop(id(conn.sock), None)
        hp = (conn.host, conn.port)
        idle = self._idle.get(hp)
        if idle and conn in idle:
            idle.remove(conn)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        # capacity freed: a waiting exchange may now open a fresh conn
        nxt = self._next_waiting(hp)
        if nxt is not None:
            self._assign(nxt)
