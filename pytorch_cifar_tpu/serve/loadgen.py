"""Synthetic closed-loop load generator + latency statistics.

Closed-loop: each simulated client submits one request, BLOCKS on its
result, then immediately submits the next — so offered load adapts to
service capacity (``clients`` bounds the in-flight requests) and the
latency distribution is the one a real synchronous client would see.
``QueueFull`` rejections are counted and retried after a short backoff,
exercising the admission-control path rather than hiding it.

Deadline hedging (ROBUSTNESS.md "serving retry/hedging"): a request that
fails with ``DeadlineExceeded`` (its queue-time bound passed during an
engine stall or a deep backlog) is resubmitted ONCE — the fresh submit
re-enters the queue at the tail with a fresh deadline, which is exactly
what a real frontend would do before surfacing the error to the client.
Hedges are counted (``hedged``, and the ``serve.hedged`` obs counter);
a request whose hedge also fails is counted in ``failed`` instead of
crashing the client loop. The retry wait is part of the client-observed
latency, like the QueueFull backoff.

Shared by ``serve.py`` and ``bench.py --serve`` so the reported p50/p95/p99
and img/s always mean the same protocol.

**HTTP client mode**: ``run_load`` drives anything with the batcher's
``submit`` surface — :class:`HttpTarget` wraps a frontend/router URL in
exactly that surface (one persistent HTTP/1.1 connection per client
thread; 429/504/503 map back to ``QueueFull``/``DeadlineExceeded``/
``BatcherClosed``), so ``bench.py --serve-http`` and the router chaos
drill report the SAME closed-loop stats and hedge counters through the
full network path that the in-process numbers mean. ``wire=`` picks the
request encoding per target — JSON, the zero-copy binary frame, or a
mixed fleet of both (SERVING.md "Binary wire format").

**Mixed-priority load**: ``bulk_fraction`` tags that share of requests
``priority="bulk"`` (per-client deterministic rng), exercising the
batcher's lanes and the router's priority-aware admission under one
closed loop.

**Heavy-tailed multi-model load** (SERVING.md "Multi-tenant zoo
serving"): ``model_mix={name: weight, ...}`` makes each request name a
model drawn from that distribution (per-client deterministic rng) —
:func:`zipf_mix` builds the production-shaped heavy tail from the zoo's
model list, optionally ordered by the zoo sweep's throughput priors.
The id rides the JSON ``model`` field or the wire-v2 frame field
(``HttpTarget``) or the zoo server's ``submit(model=)`` surface; the
report grows a ``per_model`` request-count block.
"""

from __future__ import annotations

import base64
import http.client
import json
import socket
import threading
import time
from typing import Optional
from urllib.parse import urlsplit

import numpy as np

from pytorch_cifar_tpu.serve.batcher import (
    BatcherClosed,
    DeadlineExceeded,
    QueueFull,
)


class _Resolved:
    """Future-compatible wrapper over an already-computed result: the
    HTTP exchange is synchronous, so by the time ``submit`` returns the
    answer exists — ``result()`` just hands it over. Keeps ``run_load``'s
    ``submit(...).result()`` protocol identical for both transports."""

    __slots__ = ("_value",)

    def __init__(self, value):
        self._value = value

    def result(self, timeout=None):
        return self._value


class HttpTarget:
    """A frontend/router URL exposed through the batcher's ``submit``
    surface (module docstring). Thread-safe: each loadgen client thread
    gets its own persistent HTTP/1.1 connection (``threading.local``),
    reconnecting transparently when the server idles one out.

    ``wire`` picks the request encoding: ``"json"`` (the base64-packed
    JSON protocol every earlier round reported), ``"binary"`` (the
    zero-copy frame of ``serve/wire.py`` — raw bytes both ways), or
    ``"mixed"`` (each client thread alternates encodings per request —
    the chaos drills' fleet-realism mode: one fleet, heterogeneous
    clients).

    Error mapping is the frontend contract in reverse: 429 raises
    :class:`QueueFull` (the client backs off and retries), 504 raises
    :class:`DeadlineExceeded` (the client hedges once), 503 and
    connection failures raise :class:`BatcherClosed` (counted failed).
    """

    def __init__(
        self,
        url: str,
        *,
        deadline_ms: Optional[float] = None,
        timeout_s: float = 60.0,
        wire: str = "json",
    ):
        parts = urlsplit(url if "//" in url else f"http://{url}")
        if parts.scheme != "http" or not parts.hostname:
            raise ValueError(f"target url must be http://host:port: {url!r}")
        if wire not in ("json", "binary", "mixed"):
            raise ValueError(
                f"wire must be 'json', 'binary', or 'mixed': {wire!r}"
            )
        self.host = parts.hostname
        self.tcp_port = int(parts.port or 80)
        self.url = f"http://{self.host}:{self.tcp_port}"
        self.deadline_ms = deadline_ms
        self.timeout_s = float(timeout_s)
        self.wire = wire
        self._local = threading.local()
        self.obs = None  # loadgen's optional registry hook (run_load)

    def _conn(self, fresh: bool = False) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        # a conn whose sock is gone (closed after a failure, or a
        # connect() that raised before the cache slot was replaced) must
        # be rebuilt, not reused — reusing it crashes on .sock access
        if conn is None or fresh or conn.sock is None:
            if conn is not None:
                conn.close()
            self._local.conn = None  # a failing connect leaves no stale cache
            conn = http.client.HTTPConnection(
                self.host, self.tcp_port, timeout=self.timeout_s
            )
            # TCP_NODELAY both ways (see frontend._Handler): without it
            # Nagle + delayed ACK adds a flat ~40 ms per exchange
            conn.connect()
            conn.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
            self._local.conn = conn
        return conn

    def submit(
        self,
        images: np.ndarray,
        deadline_ms: Optional[float] = None,
        priority: str = "interactive",
        model: Optional[str] = None,
    ) -> _Resolved:
        """One synchronous ``POST /predict``; returns a resolved future
        of the fp32 logits (b64-packed JSON or a raw binary frame on the
        wire, per ``wire``: bit-identical to the server's array either
        way). ``model`` names a zoo tenant (JSON ``model`` field /
        wire-v2 frame field); an unhosted model's 404 raises
        :class:`~pytorch_cifar_tpu.serve.tenancy.UnknownModel`."""
        from pytorch_cifar_tpu.serve import wire as wire_mod
        from pytorch_cifar_tpu.serve.frontend import decode_logits

        x = np.ascontiguousarray(np.asarray(images, dtype=np.uint8))
        if deadline_ms is None:
            deadline_ms = self.deadline_ms
        binary = self.wire == "binary"
        if self.wire == "mixed":
            # per-thread alternation: deterministic, no coordination
            seq = getattr(self._local, "seq", 0)
            self._local.seq = seq + 1
            binary = seq % 2 == 0
        if binary:
            body = wire_mod.encode_request(
                x,
                deadline_ms=float(deadline_ms) if deadline_ms else None,
                priority=priority,
                model=model,
            )
            ctype = wire_mod.CONTENT_TYPE
        else:
            req = {
                "images": base64.b64encode(x.tobytes()).decode("ascii"),
                "shape": [int(v) for v in x.shape],
                "priority": priority,
                "encoding": "b64",
            }
            if deadline_ms:
                req["deadline_ms"] = float(deadline_ms)
            if model is not None:
                req["model"] = str(model)
            body = json.dumps(req).encode("utf-8")
            ctype = "application/json"
        for attempt in (0, 1):
            try:
                conn = self._conn(fresh=attempt > 0)
                conn.request(
                    "POST", "/predict", body=body,
                    headers={"Content-Type": ctype},
                )
                resp = conn.getresponse()
                payload = resp.read()
                status = resp.status
            except (
                http.client.HTTPException,
                ConnectionError,
                TimeoutError,
                OSError,
            ) as e:
                if attempt == 0:
                    continue  # stale keep-alive: reconnect once
                raise BatcherClosed(
                    f"{self.url}: {type(e).__name__}: {e}"
                ) from None
            break
        if status == 200:
            if binary:
                logits, _version = wire_mod.decode_response(payload)
                return _Resolved(logits)
            return _Resolved(decode_logits(json.loads(payload)))
        try:
            err = json.loads(payload).get("error", "")
        except ValueError:
            err = payload[:200].decode("utf-8", "replace")
        if status == 404:
            from pytorch_cifar_tpu.serve.tenancy import UnknownModel

            raise UnknownModel(f"{self.url}: {err}")
        if status == 429:
            raise QueueFull(f"{self.url}: {err}")
        if status == 504:
            raise DeadlineExceeded(f"{self.url}: {err}")
        raise BatcherClosed(f"{self.url}: http {status}: {err}")

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None


def zipf_mix(models, s: float = 1.2, priors=None) -> dict:
    """Heavy-tailed per-model traffic weights: weight(rank) = 1/rank^s,
    the classic production shape (a few hot models, a long cold tail).
    With ``priors`` ({model: img/s} — the zoo sweep's cost priors), rank
    order is cheapest-first so the HOT models are the cheap ones (the
    realistic case: the expensive tail still forces placement churn);
    without priors the given order is the rank order."""
    models = list(models)
    if priors:
        models.sort(key=lambda m: -float(priors.get(m, 0.0)))
    weights = {
        m: 1.0 / float(rank + 1) ** s for rank, m in enumerate(models)
    }
    total = sum(weights.values())
    return {m: w / total for m, w in weights.items()}


def percentile_ms(latencies_ms, pct: float) -> float:
    """Nearest-rank percentile of a latency sample (ms)."""
    if not latencies_ms:
        return 0.0
    xs = sorted(latencies_ms)
    idx = min(len(xs) - 1, max(0, int(round(pct / 100.0 * len(xs))) - 1))
    return xs[idx]


def run_load(
    batcher,
    *,
    clients: int = 8,
    requests_per_client: int = 16,
    images_min: int = 1,
    images_max: int = 8,
    image_shape=(32, 32, 3),
    seed: int = 0,
    retry_backoff_s: float = 0.002,
    duration_s: Optional[float] = None,
    hedge: bool = True,
    bulk_fraction: float = 0.0,
    model_mix: Optional[dict] = None,
) -> dict:
    """Drive ``batcher`` with ``clients`` synchronous synthetic clients.

    Each request carries a uniform-random 1..images_max image batch (the
    realistic serving mix: mostly small requests, padded by the engine).
    Stops after ``requests_per_client`` requests per client, or after
    ``duration_s`` wall seconds when given (whichever comes first).
    ``hedge``: resubmit a ``DeadlineExceeded`` request once before
    counting it failed (module docstring; ``--no-hedge`` disables).
    ``bulk_fraction``: that share of requests carries
    ``priority="bulk"`` (deterministic per-client rng; 0.0 keeps the
    all-interactive protocol every earlier round reported).
    ``model_mix``: {model: weight} — each request names a model drawn
    from this distribution (:func:`zipf_mix` builds the heavy tail);
    the target must take a ``model`` kwarg on ``submit`` (an
    :class:`HttpTarget` or a
    :class:`~pytorch_cifar_tpu.serve.tenancy.ModelZooServer`), and the
    report grows a ``per_model`` request-count block.
    ``batcher`` is anything with the submit surface — a
    :class:`~pytorch_cifar_tpu.serve.batcher.MicroBatcher`, an
    :class:`HttpTarget` (the full network path), or a zoo server.

    Returns the latency/throughput report the CLIs publish:
    ``img_per_sec``, ``request_per_sec``, ``p50_ms``/``p95_ms``/``p99_ms``,
    ``mean_ms``, ``requests``, ``images``, ``rejected``, ``hedged``,
    ``failed``, ``elapsed_s``.
    """
    images_max = max(images_min, images_max)
    latencies_ms: list = []
    counts = {
        "images": 0, "rejected": 0, "hedged": 0, "failed": 0, "bulk": 0,
    }
    per_model: dict = {}
    lock = threading.Lock()
    stop_at = None
    # the per-model draw table (cumulative weights, deterministic rng)
    mix_names = mix_cum = None
    if model_mix:
        mix_names = list(model_mix)
        w = np.asarray([float(model_mix[m]) for m in mix_names])
        mix_cum = np.cumsum(w / w.sum())
    # hedges ride the serving registry (when the batcher carries one) so
    # the Prometheus dump / exporter see retry pressure, not just the CLI
    obs = getattr(batcher, "obs", None)
    c_hedged = obs.counter("serve.hedged") if obs is not None else None

    def submit_with_backoff(x, priority, model):
        kw = {} if model is None else {"model": model}
        while True:
            try:
                return batcher.submit(x, priority=priority, **kw)
            except QueueFull:
                # admission control said back off; the retry delay is
                # part of the client-observed latency (t0 stays)
                with lock:
                    counts["rejected"] += 1
                time.sleep(retry_backoff_s)

    def client(cid: int) -> None:
        rs = np.random.RandomState(seed * 1000 + cid)
        for _ in range(requests_per_client):
            if stop_at is not None and time.monotonic() >= stop_at:
                return
            n = int(rs.randint(images_min, images_max + 1))
            x = rs.randint(0, 256, size=(n, *image_shape)).astype(np.uint8)
            priority = (
                "bulk"
                if bulk_fraction and rs.uniform() < bulk_fraction
                else "interactive"
            )
            if priority == "bulk":
                with lock:
                    counts["bulk"] += 1
            model = None
            if mix_names is not None:
                model = mix_names[
                    int(np.searchsorted(mix_cum, rs.uniform()))
                ]
            t0 = time.perf_counter()
            try:
                submit_with_backoff(x, priority, model).result()
            except DeadlineExceeded:
                if not hedge:
                    with lock:
                        counts["failed"] += 1
                    continue
                # retry-once hedge: re-enter the queue with a fresh
                # deadline; a second expiry (or a shutdown race) fails
                # the request for good — never a third attempt
                with lock:
                    counts["hedged"] += 1
                if c_hedged is not None:
                    c_hedged.inc()
                try:
                    submit_with_backoff(x, priority, model).result()
                except (DeadlineExceeded, BatcherClosed):
                    with lock:
                        counts["failed"] += 1
                    continue
            except BatcherClosed:
                with lock:
                    counts["failed"] += 1
                continue
            dt_ms = (time.perf_counter() - t0) * 1e3
            with lock:
                latencies_ms.append(dt_ms)
                counts["images"] += n
                if model is not None:
                    per_model[model] = per_model.get(model, 0) + 1

    threads = [
        threading.Thread(target=client, args=(i,), name=f"loadgen-{i}")
        for i in range(clients)
    ]
    t_start = time.perf_counter()
    if duration_s is not None:
        stop_at = time.monotonic() + duration_s
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t_start

    out_per_model = (
        {"per_model": {m: per_model.get(m, 0) for m in mix_names}}
        if mix_names is not None
        else {}
    )
    return {
        "clients": clients,
        "requests": len(latencies_ms),
        "images": counts["images"],
        "rejected": counts["rejected"],
        "hedged": counts["hedged"],
        "failed": counts["failed"],
        "bulk_requests": counts["bulk"],
        **out_per_model,
        "elapsed_s": round(elapsed, 4),
        "img_per_sec": counts["images"] / max(elapsed, 1e-9),
        "request_per_sec": len(latencies_ms) / max(elapsed, 1e-9),
        "mean_ms": (
            sum(latencies_ms) / len(latencies_ms) if latencies_ms else 0.0
        ),
        "p50_ms": percentile_ms(latencies_ms, 50),
        "p95_ms": percentile_ms(latencies_ms, 95),
        "p99_ms": percentile_ms(latencies_ms, 99),
    }
