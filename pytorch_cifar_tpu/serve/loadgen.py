"""Synthetic closed-loop load generator + latency statistics.

Closed-loop: each simulated client submits one request, BLOCKS on its
result, then immediately submits the next — so offered load adapts to
service capacity (``clients`` bounds the in-flight requests) and the
latency distribution is the one a real synchronous client would see.
``QueueFull`` rejections are counted and retried after a short backoff,
exercising the admission-control path rather than hiding it.

Deadline hedging (ROBUSTNESS.md "serving retry/hedging"): a request that
fails with ``DeadlineExceeded`` (its queue-time bound passed during an
engine stall or a deep backlog) is resubmitted ONCE — the fresh submit
re-enters the queue at the tail with a fresh deadline, which is exactly
what a real frontend would do before surfacing the error to the client.
Hedges are counted (``hedged``, and the ``serve.hedged`` obs counter);
a request whose hedge also fails is counted in ``failed`` instead of
crashing the client loop. The retry wait is part of the client-observed
latency, like the QueueFull backoff.

Shared by ``serve.py`` and ``bench.py --serve`` so the reported p50/p95/p99
and img/s always mean the same protocol.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from pytorch_cifar_tpu.serve.batcher import (
    BatcherClosed,
    DeadlineExceeded,
    QueueFull,
)


def percentile_ms(latencies_ms, pct: float) -> float:
    """Nearest-rank percentile of a latency sample (ms)."""
    if not latencies_ms:
        return 0.0
    xs = sorted(latencies_ms)
    idx = min(len(xs) - 1, max(0, int(round(pct / 100.0 * len(xs))) - 1))
    return xs[idx]


def run_load(
    batcher,
    *,
    clients: int = 8,
    requests_per_client: int = 16,
    images_min: int = 1,
    images_max: int = 8,
    image_shape=(32, 32, 3),
    seed: int = 0,
    retry_backoff_s: float = 0.002,
    duration_s: Optional[float] = None,
    hedge: bool = True,
) -> dict:
    """Drive ``batcher`` with ``clients`` synchronous synthetic clients.

    Each request carries a uniform-random 1..images_max image batch (the
    realistic serving mix: mostly small requests, padded by the engine).
    Stops after ``requests_per_client`` requests per client, or after
    ``duration_s`` wall seconds when given (whichever comes first).
    ``hedge``: resubmit a ``DeadlineExceeded`` request once before
    counting it failed (module docstring; ``--no-hedge`` disables).

    Returns the latency/throughput report the CLIs publish:
    ``img_per_sec``, ``request_per_sec``, ``p50_ms``/``p95_ms``/``p99_ms``,
    ``mean_ms``, ``requests``, ``images``, ``rejected``, ``hedged``,
    ``failed``, ``elapsed_s``.
    """
    images_max = max(images_min, images_max)
    latencies_ms: list = []
    counts = {"images": 0, "rejected": 0, "hedged": 0, "failed": 0}
    lock = threading.Lock()
    stop_at = None
    # hedges ride the serving registry (when the batcher carries one) so
    # the Prometheus dump / exporter see retry pressure, not just the CLI
    obs = getattr(batcher, "obs", None)
    c_hedged = obs.counter("serve.hedged") if obs is not None else None

    def submit_with_backoff(x):
        while True:
            try:
                return batcher.submit(x)
            except QueueFull:
                # admission control said back off; the retry delay is
                # part of the client-observed latency (t0 stays)
                with lock:
                    counts["rejected"] += 1
                time.sleep(retry_backoff_s)

    def client(cid: int) -> None:
        rs = np.random.RandomState(seed * 1000 + cid)
        for _ in range(requests_per_client):
            if stop_at is not None and time.monotonic() >= stop_at:
                return
            n = int(rs.randint(images_min, images_max + 1))
            x = rs.randint(0, 256, size=(n, *image_shape)).astype(np.uint8)
            t0 = time.perf_counter()
            try:
                submit_with_backoff(x).result()
            except DeadlineExceeded:
                if not hedge:
                    with lock:
                        counts["failed"] += 1
                    continue
                # retry-once hedge: re-enter the queue with a fresh
                # deadline; a second expiry (or a shutdown race) fails
                # the request for good — never a third attempt
                with lock:
                    counts["hedged"] += 1
                if c_hedged is not None:
                    c_hedged.inc()
                try:
                    submit_with_backoff(x).result()
                except (DeadlineExceeded, BatcherClosed):
                    with lock:
                        counts["failed"] += 1
                    continue
            except BatcherClosed:
                with lock:
                    counts["failed"] += 1
                continue
            dt_ms = (time.perf_counter() - t0) * 1e3
            with lock:
                latencies_ms.append(dt_ms)
                counts["images"] += n

    threads = [
        threading.Thread(target=client, args=(i,), name=f"loadgen-{i}")
        for i in range(clients)
    ]
    t_start = time.perf_counter()
    if duration_s is not None:
        stop_at = time.monotonic() + duration_s
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t_start

    return {
        "clients": clients,
        "requests": len(latencies_ms),
        "images": counts["images"],
        "rejected": counts["rejected"],
        "hedged": counts["hedged"],
        "failed": counts["failed"],
        "elapsed_s": round(elapsed, 4),
        "img_per_sec": counts["images"] / max(elapsed, 1e-9),
        "request_per_sec": len(latencies_ms) / max(elapsed, 1e-9),
        "mean_ms": (
            sum(latencies_ms) / len(latencies_ms) if latencies_ms else 0.0
        ),
        "p50_ms": percentile_ms(latencies_ms, 50),
        "p95_ms": percentile_ms(latencies_ms, 95),
        "p99_ms": percentile_ms(latencies_ms, 99),
    }
