"""Synthetic closed-loop load generator + latency statistics.

Closed-loop: each simulated client submits one request, BLOCKS on its
result, then immediately submits the next — so offered load adapts to
service capacity (``clients`` bounds the in-flight requests) and the
latency distribution is the one a real synchronous client would see.
``QueueFull`` rejections are counted and retried after a short backoff,
exercising the admission-control path rather than hiding it.

Deadline hedging (ROBUSTNESS.md "serving retry/hedging"): a request that
fails with ``DeadlineExceeded`` (its queue-time bound passed during an
engine stall or a deep backlog) is resubmitted ONCE — the fresh submit
re-enters the queue at the tail with a fresh deadline, which is exactly
what a real frontend would do before surfacing the error to the client.
Hedges are counted (``hedged``, and the ``serve.hedged`` obs counter);
a request whose hedge also fails is counted in ``failed`` instead of
crashing the client loop. The retry wait is part of the client-observed
latency, like the QueueFull backoff.

Shared by ``serve.py`` and ``bench.py --serve`` so the reported p50/p95/p99
and img/s always mean the same protocol.

**HTTP client mode**: ``run_load`` drives anything with the batcher's
``submit`` surface — :class:`HttpTarget` wraps a frontend/router URL in
exactly that surface (one persistent HTTP/1.1 connection per client
thread; 429/504/503 map back to ``QueueFull``/``DeadlineExceeded``/
``BatcherClosed``), so ``bench.py --serve-http`` and the router chaos
drill report the SAME closed-loop stats and hedge counters through the
full network path that the in-process numbers mean. ``wire=`` picks the
request encoding per target — JSON, the zero-copy binary frame, or a
mixed fleet of both (SERVING.md "Binary wire format").

**Mixed-priority load**: ``bulk_fraction`` tags that share of requests
``priority="bulk"`` (per-client deterministic rng), exercising the
batcher's lanes and the router's priority-aware admission under one
closed loop.

**Heavy-tailed multi-model load** (SERVING.md "Multi-tenant zoo
serving"): ``model_mix={name: weight, ...}`` makes each request name a
model drawn from that distribution (per-client deterministic rng) —
:func:`zipf_mix` builds the production-shaped heavy tail from the zoo's
model list, optionally ordered by the zoo sweep's throughput priors.
The id rides the JSON ``model`` field or the wire-v2 frame field
(``HttpTarget``) or the zoo server's ``submit(model=)`` surface; the
report grows a ``per_model`` request-count block.
"""

from __future__ import annotations

import base64
import http.client
import json
import socket
import threading
import time
from typing import Optional
from urllib.parse import urlsplit

import numpy as np

from pytorch_cifar_tpu.serve.batcher import (
    BatcherClosed,
    DeadlineExceeded,
    QueueFull,
)


class _Resolved:
    """Future-compatible wrapper over an already-computed result: the
    HTTP exchange is synchronous, so by the time ``submit`` returns the
    answer exists — ``result()`` just hands it over. Keeps ``run_load``'s
    ``submit(...).result()`` protocol identical for both transports."""

    __slots__ = ("_value",)

    def __init__(self, value):
        self._value = value

    def result(self, timeout=None):
        return self._value


class HttpTarget:
    """A frontend/router URL exposed through the batcher's ``submit``
    surface (module docstring). Thread-safe: each loadgen client thread
    gets its own persistent HTTP/1.1 connection (``threading.local``),
    reconnecting transparently when the server idles one out.

    ``wire`` picks the request encoding: ``"json"`` (the base64-packed
    JSON protocol every earlier round reported), ``"binary"`` (the
    zero-copy frame of ``serve/wire.py`` — raw bytes both ways), or
    ``"mixed"`` (each client thread alternates encodings per request —
    the chaos drills' fleet-realism mode: one fleet, heterogeneous
    clients).

    Error mapping is the frontend contract in reverse: 429 raises
    :class:`QueueFull` (the client backs off and retries), 504 raises
    :class:`DeadlineExceeded` (the client hedges once), 503 and
    connection failures raise :class:`BatcherClosed` (counted failed).
    """

    def __init__(
        self,
        url: str,
        *,
        deadline_ms: Optional[float] = None,
        timeout_s: float = 60.0,
        wire: str = "json",
    ):
        parts = urlsplit(url if "//" in url else f"http://{url}")
        if parts.scheme != "http" or not parts.hostname:
            raise ValueError(f"target url must be http://host:port: {url!r}")
        if wire not in ("json", "binary", "mixed"):
            raise ValueError(
                f"wire must be 'json', 'binary', or 'mixed': {wire!r}"
            )
        self.host = parts.hostname
        self.tcp_port = int(parts.port or 80)
        self.url = f"http://{self.host}:{self.tcp_port}"
        self.deadline_ms = deadline_ms
        self.timeout_s = float(timeout_s)
        self.wire = wire
        self._local = threading.local()
        self.obs = None  # loadgen's optional registry hook (run_load)

    def _conn(self, fresh: bool = False) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        # a conn whose sock is gone (closed after a failure, or a
        # connect() that raised before the cache slot was replaced) must
        # be rebuilt, not reused — reusing it crashes on .sock access
        if conn is None or fresh or conn.sock is None:
            if conn is not None:
                conn.close()
            self._local.conn = None  # a failing connect leaves no stale cache
            conn = http.client.HTTPConnection(
                self.host, self.tcp_port, timeout=self.timeout_s
            )
            # TCP_NODELAY both ways (see frontend._Handler): without it
            # Nagle + delayed ACK adds a flat ~40 ms per exchange
            conn.connect()
            conn.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
            self._local.conn = conn
        return conn

    def submit(
        self,
        images: np.ndarray,
        deadline_ms: Optional[float] = None,
        priority: str = "interactive",
        model: Optional[str] = None,
    ) -> _Resolved:
        """One synchronous ``POST /predict``; returns a resolved future
        of the fp32 logits (b64-packed JSON or a raw binary frame on the
        wire, per ``wire``: bit-identical to the server's array either
        way). ``model`` names a zoo tenant (JSON ``model`` field /
        wire-v2 frame field); an unhosted model's 404 raises
        :class:`~pytorch_cifar_tpu.serve.tenancy.UnknownModel`."""
        from pytorch_cifar_tpu.serve import wire as wire_mod
        from pytorch_cifar_tpu.serve.frontend import decode_logits

        x = np.ascontiguousarray(np.asarray(images, dtype=np.uint8))
        if deadline_ms is None:
            deadline_ms = self.deadline_ms
        binary = self.wire == "binary"
        if self.wire == "mixed":
            # per-thread alternation: deterministic, no coordination
            seq = getattr(self._local, "seq", 0)
            self._local.seq = seq + 1
            binary = seq % 2 == 0
        if binary:
            body = wire_mod.encode_request(
                x,
                deadline_ms=float(deadline_ms) if deadline_ms else None,
                priority=priority,
                model=model,
            )
            ctype = wire_mod.CONTENT_TYPE
        else:
            req = {
                "images": base64.b64encode(x.tobytes()).decode("ascii"),
                "shape": [int(v) for v in x.shape],
                "priority": priority,
                "encoding": "b64",
            }
            if deadline_ms:
                req["deadline_ms"] = float(deadline_ms)
            if model is not None:
                req["model"] = str(model)
            body = json.dumps(req).encode("utf-8")
            ctype = "application/json"
        for attempt in (0, 1):
            try:
                conn = self._conn(fresh=attempt > 0)
                conn.request(
                    "POST", "/predict", body=body,
                    headers={"Content-Type": ctype},
                )
                resp = conn.getresponse()
                payload = resp.read()
                status = resp.status
            except (
                http.client.HTTPException,
                ConnectionError,
                TimeoutError,
                OSError,
            ) as e:
                if attempt == 0:
                    continue  # stale keep-alive: reconnect once
                raise BatcherClosed(
                    f"{self.url}: {type(e).__name__}: {e}"
                ) from None
            break
        if status == 200:
            if binary:
                logits, _version = wire_mod.decode_response(payload)
                return _Resolved(logits)
            return _Resolved(decode_logits(json.loads(payload)))
        try:
            err = json.loads(payload).get("error", "")
        except ValueError:
            err = payload[:200].decode("utf-8", "replace")
        if status == 404:
            from pytorch_cifar_tpu.serve.tenancy import UnknownModel

            raise UnknownModel(f"{self.url}: {err}")
        if status == 429:
            raise QueueFull(f"{self.url}: {err}")
        if status == 504:
            raise DeadlineExceeded(f"{self.url}: {err}")
        raise BatcherClosed(f"{self.url}: http {status}: {err}")

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None


def zipf_mix(models, s: float = 1.2, priors=None) -> dict:
    """Heavy-tailed per-model traffic weights: weight(rank) = 1/rank^s,
    the classic production shape (a few hot models, a long cold tail).
    With ``priors`` ({model: img/s} — the zoo sweep's cost priors), rank
    order is cheapest-first so the HOT models are the cheap ones (the
    realistic case: the expensive tail still forces placement churn);
    without priors the given order is the rank order."""
    models = list(models)
    if priors:
        models.sort(key=lambda m: -float(priors.get(m, 0.0)))
    weights = {
        m: 1.0 / float(rank + 1) ** s for rank, m in enumerate(models)
    }
    total = sum(weights.values())
    return {m: w / total for m, w in weights.items()}


def percentile_ms(latencies_ms, pct: float) -> float:
    """Nearest-rank percentile of a latency sample (ms)."""
    if not latencies_ms:
        return 0.0
    xs = sorted(latencies_ms)
    idx = min(len(xs) - 1, max(0, int(round(pct / 100.0 * len(xs))) - 1))
    return xs[idx]


def run_load(
    batcher,
    *,
    clients: int = 8,
    requests_per_client: int = 16,
    images_min: int = 1,
    images_max: int = 8,
    image_shape=(32, 32, 3),
    seed: int = 0,
    retry_backoff_s: float = 0.002,
    duration_s: Optional[float] = None,
    hedge: bool = True,
    bulk_fraction: float = 0.0,
    model_mix: Optional[dict] = None,
) -> dict:
    """Drive ``batcher`` with ``clients`` synchronous synthetic clients.

    Each request carries a uniform-random 1..images_max image batch (the
    realistic serving mix: mostly small requests, padded by the engine).
    Stops after ``requests_per_client`` requests per client, or after
    ``duration_s`` wall seconds when given (whichever comes first).
    ``hedge``: resubmit a ``DeadlineExceeded`` request once before
    counting it failed (module docstring; ``--no-hedge`` disables).
    ``bulk_fraction``: that share of requests carries
    ``priority="bulk"`` (deterministic per-client rng; 0.0 keeps the
    all-interactive protocol every earlier round reported).
    ``model_mix``: {model: weight} — each request names a model drawn
    from this distribution (:func:`zipf_mix` builds the heavy tail);
    the target must take a ``model`` kwarg on ``submit`` (an
    :class:`HttpTarget` or a
    :class:`~pytorch_cifar_tpu.serve.tenancy.ModelZooServer`), and the
    report grows a ``per_model`` request-count block.
    ``batcher`` is anything with the submit surface — a
    :class:`~pytorch_cifar_tpu.serve.batcher.MicroBatcher`, an
    :class:`HttpTarget` (the full network path), or a zoo server.

    Returns the latency/throughput report the CLIs publish:
    ``img_per_sec``, ``request_per_sec``, ``p50_ms``/``p95_ms``/``p99_ms``,
    ``mean_ms``, ``requests``, ``images``, ``rejected``, ``hedged``,
    ``failed``, ``elapsed_s``.
    """
    images_max = max(images_min, images_max)
    latencies_ms: list = []
    counts = {
        "images": 0, "rejected": 0, "hedged": 0, "failed": 0, "bulk": 0,
    }
    per_model: dict = {}
    lock = threading.Lock()
    stop_at = None
    # the per-model draw table (cumulative weights, deterministic rng)
    mix_names = mix_cum = None
    if model_mix:
        mix_names = list(model_mix)
        w = np.asarray([float(model_mix[m]) for m in mix_names])
        mix_cum = np.cumsum(w / w.sum())
    # hedges ride the serving registry (when the batcher carries one) so
    # the Prometheus dump / exporter see retry pressure, not just the CLI
    obs = getattr(batcher, "obs", None)
    c_hedged = obs.counter("serve.hedged") if obs is not None else None

    def submit_with_backoff(x, priority, model):
        kw = {} if model is None else {"model": model}
        while True:
            try:
                return batcher.submit(x, priority=priority, **kw)
            except QueueFull:
                # admission control said back off; the retry delay is
                # part of the client-observed latency (t0 stays)
                with lock:
                    counts["rejected"] += 1
                time.sleep(retry_backoff_s)

    def client(cid: int) -> None:
        rs = np.random.RandomState(seed * 1000 + cid)
        for _ in range(requests_per_client):
            if stop_at is not None and time.monotonic() >= stop_at:
                return
            n = int(rs.randint(images_min, images_max + 1))
            x = rs.randint(0, 256, size=(n, *image_shape)).astype(np.uint8)
            priority = (
                "bulk"
                if bulk_fraction and rs.uniform() < bulk_fraction
                else "interactive"
            )
            if priority == "bulk":
                with lock:
                    counts["bulk"] += 1
            model = None
            if mix_names is not None:
                model = mix_names[
                    int(np.searchsorted(mix_cum, rs.uniform()))
                ]
            t0 = time.perf_counter()
            try:
                submit_with_backoff(x, priority, model).result()
            except DeadlineExceeded:
                if not hedge:
                    with lock:
                        counts["failed"] += 1
                    continue
                # retry-once hedge: re-enter the queue with a fresh
                # deadline; a second expiry (or a shutdown race) fails
                # the request for good — never a third attempt
                with lock:
                    counts["hedged"] += 1
                if c_hedged is not None:
                    c_hedged.inc()
                try:
                    submit_with_backoff(x, priority, model).result()
                except (DeadlineExceeded, BatcherClosed):
                    with lock:
                        counts["failed"] += 1
                    continue
            except BatcherClosed:
                with lock:
                    counts["failed"] += 1
                continue
            dt_ms = (time.perf_counter() - t0) * 1e3
            with lock:
                latencies_ms.append(dt_ms)
                counts["images"] += n
                if model is not None:
                    per_model[model] = per_model.get(model, 0) + 1

    threads = [
        threading.Thread(target=client, args=(i,), name=f"loadgen-{i}")
        for i in range(clients)
    ]
    t_start = time.perf_counter()
    if duration_s is not None:
        stop_at = time.monotonic() + duration_s
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t_start

    out_per_model = (
        {"per_model": {m: per_model.get(m, 0) for m in mix_names}}
        if mix_names is not None
        else {}
    )
    return {
        "clients": clients,
        "requests": len(latencies_ms),
        "images": counts["images"],
        "rejected": counts["rejected"],
        "hedged": counts["hedged"],
        "failed": counts["failed"],
        "bulk_requests": counts["bulk"],
        **out_per_model,
        "elapsed_s": round(elapsed, 4),
        "img_per_sec": counts["images"] / max(elapsed, 1e-9),
        "request_per_sec": len(latencies_ms) / max(elapsed, 1e-9),
        "mean_ms": (
            sum(latencies_ms) / len(latencies_ms) if latencies_ms else 0.0
        ),
        "p50_ms": percentile_ms(latencies_ms, 50),
        "p95_ms": percentile_ms(latencies_ms, 95),
        "p99_ms": percentile_ms(latencies_ms, 99),
    }


# ---------------------------------------------------------------------
# Async client driver (SERVING.md "Event-loop edge")
# ---------------------------------------------------------------------

# The thread-per-client driver above cannot GENERATE production
# connection counts: 1k clients would be 1k stacks on the loadgen side.
# run_async_load is the same closed-loop protocol — each logical client
# has exactly one request in flight, QueueFull backs off and retries,
# DeadlineExceeded hedges once, latency includes both waits — driven by
# ONE thread over non-blocking sockets, so `--clients 2048` costs 2048
# sockets, not 2048 threads. It exists to exercise the event-loop edge
# at the connection counts it was built for (bench.py --serve-edge,
# chaos_run --mode edge).


def _encode_predict_body(x, deadline_ms, priority, model, binary):
    """(body, content_type) for one POST /predict — the exact encodings
    HttpTarget.submit puts on the wire, factored for the async driver."""
    from pytorch_cifar_tpu.serve import wire as wire_mod

    if binary:
        return (
            wire_mod.encode_request(
                x,
                deadline_ms=float(deadline_ms) if deadline_ms else None,
                priority=priority,
                model=model,
            ),
            wire_mod.CONTENT_TYPE,
        )
    req = {
        "images": base64.b64encode(x.tobytes()).decode("ascii"),
        "shape": [int(v) for v in x.shape],
        "priority": priority,
        "encoding": "b64",
    }
    if deadline_ms:
        req["deadline_ms"] = float(deadline_ms)
    if model is not None:
        req["model"] = str(model)
    return json.dumps(req).encode("utf-8"), "application/json"


class _AsyncClient:
    """One logical closed-loop client: request generator + HTTP/1.1
    response parser over a non-blocking keep-alive socket. All state is
    driven by the single run_async_load loop thread."""

    __slots__ = (
        "cid", "rs", "seq", "done_requests", "sock", "connected",
        "out", "rbuf", "body", "body_filled", "content_length", "status",
        "request", "t0", "hedged_once", "retry_at", "reconnects",
        "deadline_at", "finished", "n_images", "model",
    )

    def __init__(self, cid, seed):
        self.cid = cid
        self.rs = np.random.RandomState(seed * 1000 + cid)
        self.seq = 0
        self.done_requests = 0
        self.sock = None
        self.connected = False
        self.out = None  # memoryview of unsent request bytes
        self.rbuf = bytearray()
        self.body = None
        self.body_filled = 0
        self.content_length = 0
        self.status = 0
        self.request = b""
        self.t0 = 0.0
        self.hedged_once = False
        self.retry_at = 0.0  # 429 backoff wakeup
        self.reconnects = 0
        self.deadline_at = 0.0
        self.finished = False
        self.n_images = 0
        self.model = None


def run_async_load(
    url: str,
    *,
    clients: int = 64,
    requests_per_client: int = 16,
    images_min: int = 1,
    images_max: int = 8,
    image_shape=(32, 32, 3),
    seed: int = 0,
    retry_backoff_s: float = 0.002,
    duration_s: Optional[float] = None,
    hedge: bool = True,
    bulk_fraction: float = 0.0,
    model_mix: Optional[dict] = None,
    wire: str = "json",
    deadline_ms: Optional[float] = None,
    timeout_s: float = 60.0,
) -> dict:
    """Closed-loop load from ``clients`` LOGICAL clients multiplexed on
    one thread of non-blocking sockets (module comment above). Protocol
    and report keys are identical to :func:`run_load` — 429 backs off
    ``retry_backoff_s`` and retries (counted ``rejected``, latency keeps
    running), 504 hedges once (counted ``hedged``), other errors and
    dead connections count ``failed`` — so A/B numbers against the
    threaded driver compare like for like. ``wire`` is ``"json"``,
    ``"binary"``, or ``"mixed"`` (per-client alternation)."""
    import selectors

    parts = urlsplit(url if "//" in url else f"http://{url}")
    if parts.scheme != "http" or not parts.hostname:
        raise ValueError(f"target url must be http://host:port: {url!r}")
    if wire not in ("json", "binary", "mixed"):
        raise ValueError(f"wire must be 'json', 'binary', or 'mixed': {wire!r}")
    host, port = parts.hostname, int(parts.port or 80)
    images_max = max(images_min, images_max)

    latencies_ms: list = []
    counts = {
        "images": 0, "rejected": 0, "hedged": 0, "failed": 0, "bulk": 0,
    }
    per_model: dict = {}
    mix_names = mix_cum = None
    if model_mix:
        mix_names = list(model_mix)
        w = np.asarray([float(model_mix[m]) for m in mix_names])
        mix_cum = np.cumsum(w / w.sum())

    sel = selectors.DefaultSelector()
    by_fd: dict = {}
    stop_at = (
        time.monotonic() + duration_s if duration_s is not None else None
    )
    live = 0

    def next_request(c: _AsyncClient):
        """Generate the next request (the run_load generator, verbatim
        protocol) or mark the client finished."""
        if c.done_requests >= requests_per_client or (
            stop_at is not None and time.monotonic() >= stop_at
        ):
            finish(c)
            return
        n = int(c.rs.randint(images_min, images_max + 1))
        x = c.rs.randint(0, 256, size=(n, *image_shape)).astype(np.uint8)
        priority = (
            "bulk"
            if bulk_fraction and c.rs.uniform() < bulk_fraction
            else "interactive"
        )
        if priority == "bulk":
            counts["bulk"] += 1
        c.model = None
        if mix_names is not None:
            c.model = mix_names[
                int(np.searchsorted(mix_cum, c.rs.uniform()))
            ]
        binary = wire == "binary" or (wire == "mixed" and c.seq % 2 == 0)
        c.seq += 1
        body, ctype = _encode_predict_body(
            x, deadline_ms, priority, c.model, binary
        )
        c.request = (
            f"POST /predict HTTP/1.1\r\n"
            f"Host: {host}:{port}\r\n"
            f"Connection: keep-alive\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode("ascii") + body
        c.n_images = n
        c.t0 = time.perf_counter()
        c.hedged_once = False
        c.reconnects = 0
        send_current(c)

    def send_current(c: _AsyncClient):
        """(Re)send the buffered current request — fresh attempt, fresh
        exchange deadline; reuses the live connection when there is one."""
        c.rbuf = bytearray()
        c.body = None
        c.body_filled = 0
        c.status = 0
        c.deadline_at = time.monotonic() + timeout_s
        c.out = memoryview(c.request)
        if c.sock is None:
            open_conn(c)
        else:
            arm(c)
            on_writable(c)

    def open_conn(c: _AsyncClient):
        import errno as _errno

        close_sock(c)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setblocking(False)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        rc = s.connect_ex((host, port))
        if rc not in (0, _errno.EINPROGRESS, _errno.EWOULDBLOCK):
            s.close()
            fail_request(c)
            return
        c.sock = s
        c.connected = False
        by_fd[s.fileno()] = c
        sel.register(
            s, selectors.EVENT_READ | selectors.EVENT_WRITE, c
        )

    def close_sock(c: _AsyncClient):
        if c.sock is None:
            return
        by_fd.pop(c.sock.fileno(), None)
        try:
            sel.unregister(c.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            c.sock.close()
        except OSError:
            pass
        c.sock = None
        c.connected = False

    def finish(c: _AsyncClient):
        nonlocal live
        if not c.finished:
            c.finished = True
            live -= 1
        close_sock(c)

    def fail_request(c: _AsyncClient):
        counts["failed"] += 1
        close_sock(c)
        c.done_requests += 1
        next_request(c)

    def conn_lost(c: _AsyncClient):
        """Transport died mid-exchange. A stale keep-alive (zero
        response bytes on a reused conn) gets one fresh-connection
        resend — the HttpTarget reconnect contract; anything else is a
        failed request."""
        stale = (
            c.status == 0 and not c.rbuf and c.body_filled == 0
            and c.reconnects == 0
        )
        close_sock(c)
        if stale:
            c.reconnects += 1
            send_current(c)
        else:
            fail_request(c)

    def arm(c: _AsyncClient):
        mask = selectors.EVENT_READ
        if c.out is not None and len(c.out):
            mask |= selectors.EVENT_WRITE
        try:
            sel.modify(c.sock, mask, c)
        except (KeyError, ValueError, OSError):
            pass

    def on_writable(c: _AsyncClient):
        if not c.connected:
            err = c.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
            if err != 0:
                conn_lost(c)
                return
            c.connected = True
        while c.out is not None and len(c.out):
            try:
                sent = c.sock.send(c.out)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                conn_lost(c)
                return
            c.out = c.out[sent:]
        if c.out is not None and not len(c.out):
            c.out = None
        arm(c)

    def on_readable(c: _AsyncClient):
        try:
            data = c.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            conn_lost(c)
            return
        if not data:
            conn_lost(c)
            return
        if c.body is None:
            c.rbuf += data
            idx = c.rbuf.find(b"\r\n\r\n")
            if idx < 0:
                return
            head = bytes(c.rbuf[:idx])
            rest = bytes(c.rbuf[idx + 4:])
            c.rbuf = bytearray()
            try:
                lines = head.decode("iso-8859-1").split("\r\n")
                c.status = int(lines[0].split(None, 2)[1])
                length = 0
                for ln in lines[1:]:
                    name, _, value = ln.partition(":")
                    if name.strip().lower() == "content-length":
                        length = int(value.strip())
            except (ValueError, IndexError):
                conn_lost(c)
                return
            c.content_length = length
            c.body = memoryview(bytearray(length))
            c.body_filled = 0
            if rest:
                feed_body(c, rest)
            elif length == 0:
                complete(c)
        else:
            feed_body(c, data)

    def feed_body(c: _AsyncClient, data):
        take = min(len(data), c.content_length - c.body_filled)
        c.body[c.body_filled:c.body_filled + take] = data[:take]
        c.body_filled += take
        if c.body_filled >= c.content_length:
            complete(c)

    def complete(c: _AsyncClient):
        payload = bytes(c.body.obj)
        status = c.status
        c.body = None
        c.status = 0
        if status == 200:
            dt_ms = (time.perf_counter() - c.t0) * 1e3
            latencies_ms.append(dt_ms)
            counts["images"] += c.n_images
            if c.model is not None:
                per_model[c.model] = per_model.get(c.model, 0) + 1
            c.done_requests += 1
            next_request(c)
            return
        if status == 429:
            # admission control said back off; latency keeps running
            counts["rejected"] += 1
            c.retry_at = time.monotonic() + retry_backoff_s
            return
        if status == 504 and hedge and not c.hedged_once:
            c.hedged_once = True
            counts["hedged"] += 1
            send_current(c)
            return
        counts["failed"] += 1
        c.done_requests += 1
        next_request(c)

    pool = [_AsyncClient(i, seed) for i in range(clients)]
    live = clients
    t_start = time.perf_counter()
    for c in pool:
        next_request(c)

    while live > 0:
        now = time.monotonic()
        timeout = 0.25
        for c in pool:
            if c.finished:
                continue
            if c.retry_at and now >= c.retry_at:
                c.retry_at = 0.0
                send_current(c)
            elif c.retry_at:
                timeout = min(timeout, c.retry_at - now)
            if c.sock is not None and now >= c.deadline_at:
                fail_request(c)
        if live <= 0:
            break
        for key, mask in sel.select(max(timeout, 0.0)):
            c = key.data
            if c.sock is None or c.finished:
                continue
            if mask & selectors.EVENT_WRITE:
                on_writable(c)
            if c.sock is not None and mask & selectors.EVENT_READ:
                on_readable(c)
        if stop_at is not None and time.monotonic() >= stop_at:
            for c in pool:
                if not c.finished and c.sock is None and not c.retry_at:
                    finish(c)
            if all(
                c.finished or c.sock is None for c in pool
            ) and time.monotonic() >= stop_at + timeout_s:
                break  # hung tail past the grace window: report what we have
    sel.close()
    elapsed = time.perf_counter() - t_start

    out_per_model = (
        {"per_model": {m: per_model.get(m, 0) for m in mix_names}}
        if mix_names is not None
        else {}
    )
    return {
        "clients": clients,
        "requests": len(latencies_ms),
        "images": counts["images"],
        "rejected": counts["rejected"],
        "hedged": counts["hedged"],
        "failed": counts["failed"],
        "bulk_requests": counts["bulk"],
        **out_per_model,
        "elapsed_s": round(elapsed, 4),
        "img_per_sec": counts["images"] / max(elapsed, 1e-9),
        "request_per_sec": len(latencies_ms) / max(elapsed, 1e-9),
        "mean_ms": (
            sum(latencies_ms) / len(latencies_ms) if latencies_ms else 0.0
        ),
        "p50_ms": percentile_ms(latencies_ms, 50),
        "p95_ms": percentile_ms(latencies_ms, 95),
        "p99_ms": percentile_ms(latencies_ms, 99),
    }


def main(argv=None) -> int:
    """CLI: drive a frontend/router URL with the async client driver and
    print the one-line JSON report — ``python -m
    pytorch_cifar_tpu.serve.loadgen --url http://... --clients 512``."""
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--url", required=True, help="frontend/router URL")
    p.add_argument(
        "--clients", type=int, default=64,
        help="LOGICAL clients (sockets, not threads — thousands are fine)",
    )
    p.add_argument("--requests", type=int, default=16)
    p.add_argument("--images_min", type=int, default=1)
    p.add_argument("--images_max", type=int, default=8)
    p.add_argument("--duration_s", type=float, default=0.0)
    p.add_argument("--wire", choices=("json", "binary", "mixed"),
                   default="json")
    p.add_argument("--deadline_ms", type=float, default=0.0)
    p.add_argument("--bulk_fraction", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeout_s", type=float, default=60.0)
    args = p.parse_args(argv)

    report = run_async_load(
        args.url,
        clients=args.clients,
        requests_per_client=args.requests,
        images_min=args.images_min,
        images_max=args.images_max,
        seed=args.seed,
        duration_s=args.duration_s or None,
        bulk_fraction=args.bulk_fraction,
        wire=args.wire,
        deadline_ms=args.deadline_ms or None,
        timeout_s=args.timeout_s,
    )
    print(json.dumps({"harness": "loadgen_async", **report}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
