"""Zero-copy binary wire format for the serve path.

The JSON `/predict` encodings (nested lists, base64) pay per-image host
work on the hot path: a UTF-8 parse, a base64 decode, and — on the
response side — a float->text conversion per logit. The profile that
motivated this module (`bench.py --serve-http`'s ``http_vs_inproc`` A/B)
shows serve latency living on the wire and the host, not the device, so
the binary frame removes every per-pixel conversion:

- the request payload is the image batch's raw C-order bytes; the server
  decodes it with ONE 24-byte header parse and a ``np.frombuffer`` view
  (zero copy — the first copy the bytes ever see is batch staging);
- the response payload is the raw float32 logit bytes, bit-identical to
  the in-process ``engine.predict`` array by construction (no text
  round-trip to reason about).

Frame layout (SERVING.md "Binary wire format" is the client-facing spec;
this module is the single implementation both sides share):

    offset  size  field
    0       4     magic ``b"PCTW"``
    4       1     version (1 or 2; see below)
    5       1     frame type: 1 = predict request, 2 = logits response
    6       1     dtype code: 1 = uint8 (requests), 2 = float32 (responses)
    7       1     flags (requests: bit0 deadline field present, bit1 bulk
                  priority, bit2 respond in JSON, bit3 model-id field
                  present [version 2 only]; responses: none)
    8       16    4 x uint32 LE dims — requests: [n, h, w, c];
                  responses: [n, num_classes, engine_version, 0]
    24      8     float64 LE ``deadline_ms`` — present ONLY when flag
                  bit0 is set (requests only)
    ...     1+L   model id — present ONLY when flag bit3 is set (version
                  2 requests only): one uint8 length L, then L bytes of
                  UTF-8 model name (a ``models.MODEL_REGISTRY`` key)
    ...           payload: raw C-order bytes, exactly prod(dims) elements

Version/compat policy: the version byte covers the whole layout — any
change to the header or payload encoding bumps it, and a server rejects
frames from a version it does not speak with a 400 (clients fall back to
JSON, which every server version accepts). Reserved flag bits MUST be
zero; a frame with unknown bits set is rejected rather than half-read,
so a future flag can change the layout behind it safely.

Version 2 (multi-tenant zoo serving, SERVING.md "Multi-tenant zoo
serving") adds exactly one thing: the optional model-id field selecting
a tenant of a :class:`~pytorch_cifar_tpu.serve.tenancy.ModelZooServer`.
Compat, per the policy above:

- **v1 frames keep decoding forever** and route to the server's DEFAULT
  model — a pre-zoo client against a zoo fleet keeps working unchanged;
  :func:`encode_request` still emits v1 when no model is named, so the
  v1 path stays continuously exercised.
- flag bit3 is RESERVED in v1 (a v1 frame with it set is a 400, as it
  always was); only v2 frames may carry the field.
- a well-formed frame naming a model the server does not host is **404**
  (JSON error body), not 400 — the frame was valid, the tenant is
  absent; malformed frames (truncated model field, zero-length name,
  undecodable UTF-8) stay 400s.
- response frames are unchanged by v2 and are still emitted at v1;
  decoders accept either version byte.

Every malformed-input class raises :class:`WireError` with a message
naming exactly what was wrong — the frontend maps it to a 400 with a
parseable JSON error body (errors are ALWAYS JSON, whatever the request
encoding: a client that cannot decode a frame can still read why).
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

import numpy as np

MAGIC = b"PCTW"
VERSION_V1 = 1
VERSION = 2  # current: v1 + the optional model-id field (module docstring)
FRAME_PREDICT = 1
FRAME_LOGITS = 2
DTYPE_UINT8 = 1
DTYPE_FLOAT32 = 2
FLAG_DEADLINE = 0x01
FLAG_BULK = 0x02
FLAG_JSON_RESPONSE = 0x04
FLAG_MODEL = 0x08  # version 2 only; reserved (-> 400) in version 1
_KNOWN_FLAGS = {
    VERSION_V1: FLAG_DEADLINE | FLAG_BULK | FLAG_JSON_RESPONSE,
    VERSION: FLAG_DEADLINE | FLAG_BULK | FLAG_JSON_RESPONSE | FLAG_MODEL,
}
MAX_MODEL_NAME_BYTES = 255  # one uint8 length prefix

# magic, version, frame type, dtype code, flags, 4 x uint32 dims
_HEADER = struct.Struct("<4sBBBB4I")
_DEADLINE = struct.Struct("<d")
HEADER_SIZE = _HEADER.size  # 24 bytes

# the Content-Type that selects this format on POST /predict
CONTENT_TYPE = "application/octet-stream"


class WireError(ValueError):
    """A malformed binary frame — maps to HTTP 400 at the frontend."""


def max_request_bytes(image_shape: Tuple[int, int, int], max_images: int) -> int:
    """Upper bound on a legal request frame's size — the frontend
    rejects a larger Content-Length BEFORE reading the body, so an
    oversized ``n`` cannot even cost the read."""
    return (
        HEADER_SIZE
        + _DEADLINE.size
        + 1 + MAX_MODEL_NAME_BYTES  # the v2 model-id field at its largest
        + int(max_images) * int(np.prod(image_shape))
    )


def encode_request(
    images: np.ndarray,
    deadline_ms: Optional[float] = None,
    priority: str = "interactive",
    json_response: bool = False,
    model: Optional[str] = None,
) -> bytes:
    """One predict-request frame for a uint8 NHWC batch. With no
    ``model`` the frame is emitted at VERSION 1 (byte-identical to the
    pre-zoo encoder — maximum compat, and the v1 decode path stays
    continuously exercised); naming a model emits a version-2 frame
    carrying the model-id field."""
    x = np.ascontiguousarray(np.asarray(images, dtype=np.uint8))
    if x.ndim != 4:
        raise ValueError(f"images must be (n, h, w, c), got {x.shape}")
    flags = 0
    if deadline_ms is not None:
        flags |= FLAG_DEADLINE
    if priority == "bulk":
        flags |= FLAG_BULK
    if json_response:
        flags |= FLAG_JSON_RESPONSE
    model_bytes = b""
    version = VERSION_V1
    if model is not None:
        model_bytes = str(model).encode("utf-8")
        if not 0 < len(model_bytes) <= MAX_MODEL_NAME_BYTES:
            raise ValueError(
                f"model name must be 1..{MAX_MODEL_NAME_BYTES} UTF-8 "
                f"bytes, got {len(model_bytes)}"
            )
        flags |= FLAG_MODEL
        version = VERSION
    header = _HEADER.pack(
        MAGIC, version, FRAME_PREDICT, DTYPE_UINT8, flags, *x.shape
    )
    parts = [header]
    if deadline_ms is not None:
        parts.append(_DEADLINE.pack(float(deadline_ms)))
    if model is not None:
        parts.append(bytes([len(model_bytes)]) + model_bytes)
    parts.append(x.data if x.flags.c_contiguous else x.tobytes())
    return b"".join(parts)


def _header(body: bytes, want_frame: int, want_dtype: int):
    if len(body) < HEADER_SIZE:
        raise WireError(
            f"truncated frame: {len(body)} bytes is shorter than the "
            f"{HEADER_SIZE}-byte header"
        )
    magic, version, frame, dtype, flags, d0, d1, d2, d3 = _HEADER.unpack_from(
        body
    )
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r} (expected {MAGIC!r})")
    if version not in _KNOWN_FLAGS:
        raise WireError(
            f"unsupported wire version {version} (this side speaks "
            f"{sorted(_KNOWN_FLAGS)}; fall back to the JSON encoding)"
        )
    if frame != want_frame:
        raise WireError(f"unexpected frame type {frame} (expected {want_frame})")
    if dtype != want_dtype:
        raise WireError(
            f"unsupported dtype code {dtype} (expected {want_dtype})"
        )
    return version, flags, (d0, d1, d2, d3)


def decode_request(
    body: bytes,
    image_shape: Tuple[int, int, int],
    max_images: int,
) -> Tuple[np.ndarray, Optional[float], str, bool, Optional[str]]:
    """Parse one request frame into ``(images, deadline_ms, priority,
    json_response, model)``. ``images`` is a read-only zero-copy view
    over the body's payload bytes; ``model`` is None for version-1
    frames and v2 frames without the model field — the server routes
    those to its default model (compat policy, module docstring)."""
    version, flags, (n, h, w, c) = _header(body, FRAME_PREDICT, DTYPE_UINT8)
    known = _KNOWN_FLAGS[version]
    if flags & ~known:
        raise WireError(
            f"unknown flag bits 0x{flags & ~known:02x} set "
            f"(reserved bits must be zero in version {version})"
        )
    if n < 1:
        raise WireError(f"frame carries n={n} images (need n >= 1)")
    if (h, w, c) != tuple(image_shape):
        raise WireError(
            f"frame image shape ({h}, {w}, {c}) does not match the "
            f"served shape {tuple(image_shape)}"
        )
    if n > max_images:
        raise WireError(
            f"frame carries {n} images; a single request is capped at "
            f"{max_images}"
        )
    off = HEADER_SIZE
    deadline_ms: Optional[float] = None
    if flags & FLAG_DEADLINE:
        if len(body) < off + _DEADLINE.size:
            raise WireError(
                "truncated frame: deadline flag set but the deadline "
                "field is missing"
            )
        (deadline_ms,) = _DEADLINE.unpack_from(body, off)
        if not np.isfinite(deadline_ms) or deadline_ms < 0:
            raise WireError(
                f"deadline_ms must be a finite non-negative number, got "
                f"{deadline_ms}"
            )
        off += _DEADLINE.size
    model: Optional[str] = None
    if flags & FLAG_MODEL:  # reachable only at version >= 2 (flag check)
        if len(body) < off + 1:
            raise WireError(
                "truncated frame: model flag set but the model-id "
                "length byte is missing"
            )
        mlen = body[off]
        off += 1
        if mlen < 1:
            raise WireError("model-id field has zero length")
        if len(body) < off + mlen:
            raise WireError(
                f"truncated frame: model-id field promises {mlen} bytes, "
                f"{len(body) - off} remain"
            )
        try:
            model = bytes(body[off : off + mlen]).decode("utf-8")
        except UnicodeDecodeError as e:
            raise WireError(f"model-id field is not valid UTF-8: {e}")
        off += mlen
    expect = n * h * w * c
    if len(body) - off != expect:
        raise WireError(
            f"frame payload is {len(body) - off} bytes; the header's "
            f"[{n}, {h}, {w}, {c}] shape needs exactly {expect}"
        )
    x = np.frombuffer(body, dtype=np.uint8, count=expect, offset=off)
    return (
        x.reshape(n, h, w, c),
        deadline_ms,
        "bulk" if flags & FLAG_BULK else "interactive",
        bool(flags & FLAG_JSON_RESPONSE),
        model,
    )


def encode_response(logits: np.ndarray, engine_version: int) -> bytes:
    """One logits-response frame: raw float32 bytes, bit-transparent.
    Response layout is unchanged by wire v2, so responses are still
    emitted at version 1 (module docstring compat policy: the version
    byte covers the layout, and this layout did not change)."""
    out = np.ascontiguousarray(np.asarray(logits, dtype=np.float32))
    if out.ndim != 2:
        raise ValueError(f"logits must be (n, classes), got {out.shape}")
    header = _HEADER.pack(
        MAGIC, VERSION_V1, FRAME_LOGITS, DTYPE_FLOAT32, 0,
        out.shape[0], out.shape[1], int(engine_version), 0,
    )
    return header + out.tobytes()


def decode_response(body: bytes) -> Tuple[np.ndarray, int]:
    """Parse one response frame into ``(logits, engine_version)``."""
    _version, flags, (n, classes, engine_version, _) = _header(
        body, FRAME_LOGITS, DTYPE_FLOAT32
    )
    if flags:
        raise WireError(f"unknown response flag bits 0x{flags:02x}")
    expect = n * classes * 4
    if len(body) - HEADER_SIZE != expect:
        raise WireError(
            f"response payload is {len(body) - HEADER_SIZE} bytes; the "
            f"header's [{n}, {classes}] float32 shape needs {expect}"
        )
    logits = np.frombuffer(
        body, dtype=np.float32, count=n * classes, offset=HEADER_SIZE
    )
    return logits.reshape(n, classes), int(engine_version)


def is_binary_content_type(content_type: Optional[str]) -> bool:
    """True when the request's Content-Type selects the binary frame."""
    if not content_type:
        return False
    return content_type.split(";", 1)[0].strip().lower() == CONTENT_TYPE
