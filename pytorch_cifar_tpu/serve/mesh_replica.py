"""Cross-host serving: one logical replica spanning N processes.

A ``router_run`` fleet used to be single-host replicas only, so the
largest servable model (and one replica's batch-throughput ceiling) was
capped by one host. This module presents an :class:`InferenceEngine`
whose mesh spans ``jax.process_count()`` processes to the router as ONE
logical replica (SERVING.md "Multi-process mesh replica"):

- **Process 0 (the leader)** owns the HTTP frontend and the
  micro-batcher. Every formed batch is broadcast to the followers —
  first a fixed-size command frame (op, row count), then the batch
  bytes — over :func:`~pytorch_cifar_tpu.parallel.mesh.broadcast_pytree`
  (the gloo-safe uniform-chunk path), and all processes then enter the
  SAME sharded bucket program, ingesting the batch through the train
  pipeline's ``put_sharded_array``. The logits come back through the
  engine's host allgather, and the leader answers the wire.
- **Followers (ranks > 0)** run :meth:`MeshReplica.follower_loop` on
  their MAIN thread: a lock-step responder that blocks on the next
  command broadcast and mirrors whatever the leader dispatched. A
  follower makes no timing decision of its own — the whole protocol has
  exactly ONE collective initiator, the leader's dispatch thread.
- **Single initiator, total order.** All collectives (batches, weight
  swaps, heartbeats, shutdown) are issued by one leader thread,
  ``_dispatch_loop``; callers (batcher worker, hot-reload watcher)
  enqueue work and wait on a Future. This is what makes a collective on
  a background thread safe here — and it is declared to graftcheck via
  ``GRAFTCHECK_SANCTIONED_COLLECTIVE_ENTRIES`` below rather than
  suppressed (STATIC_ANALYSIS.md "thread-collective").
- **Bootstrap + distributed warmup barrier.** Construction broadcasts
  the leader's weights to every process (bit-identical serving state by
  construction, whatever each process loaded from disk), then runs a
  collective rendezvous per bucket: every process executes the
  canonical probe batch through its compiled program and must match the
  leader's logits bit-for-bit. No process can serve (or report healthy)
  ahead of a straggler still compiling — the probe call blocks until
  every peer arrives.
- **Hot reload / swap.** ``swap_weights`` validates avals on the
  caller's thread, then the dispatch loop broadcasts the trees and every
  process swaps the same generation atomically (``engine.version``
  advances in lock-step; a wrong-model checkpoint is rejected before
  anything is broadcast).
- **Bounded dead-peer detection, never a hang — and never a zombie.**
  A dead peer surfaces in one of two ways, and both are terminal:
  (a) the collective HANGS — gloo waits for a peer that will never
  arrive; it cannot be interrupted from Python, so each side runs a
  watchdog (the leader arms a deadline around every collective, a
  follower re-arms on every received command while the idle leader
  broadcasts heartbeats) that exits :data:`PEER_TIMEOUT_RC` within
  ``timeout_s``; or (b) the collective RAISES — gloo's TCP transport
  noticed the reset — which is just as fatal: the ranks are now
  desynced mid-protocol, so continuing to serve would make this leader
  a zombie that accepts work it cannot answer while flapping in and
  out of the router's health view (observed in the chaos drill before
  this rule existed). Either way the process exits
  :data:`PEER_TIMEOUT_RC`, the router sees the leader's probe fail,
  evicts the LOGICAL replica, and hedges the in-flight requests
  (drilled by ``tools/chaos_run.py --mode mesh``).

Degenerate single-process mode (``jax.process_count() == 1``) keeps the
exact engine semantics — every broadcast is the identity and the
watchdog never starts — which is what the tier-1 pins in
tests/test_serve.py exercise on the forced-8-device host.
"""

from __future__ import annotations

import logging
import os
import queue as queue_lib
import threading
import time
from concurrent.futures import Future
from typing import Optional

import numpy as np

from pytorch_cifar_tpu import faults
from pytorch_cifar_tpu.obs import trace
from pytorch_cifar_tpu.parallel.mesh import broadcast_pytree

log = logging.getLogger(__name__)

# command frame (int64[4]): [op, n_rows, sequence, reserved]. Fixed size
# so a follower can always post the placeholder without knowing what is
# coming — the op then tells it the shape of any payload broadcast.
_CMD_LEN = 4
OP_HEARTBEAT = 0
OP_BATCH = 1
OP_SWAP = 2
OP_SHUTDOWN = 3

# exit code of a process that detected a dead/wedged collective peer:
# the launcher (router_run) and the chaos drill key on "non-zero within
# timeout_s", and 70 (EX_SOFTWARE) never collides with a signal death
PEER_TIMEOUT_RC = 70

# graftcheck thread-collective sanction (STATIC_ANALYSIS.md): the ONE
# background thread in the job allowed to start host collectives.
GRAFTCHECK_SANCTIONED_COLLECTIVE_ENTRIES = {
    "MeshReplica._dispatch_loop": (
        "single-initiator lock-step protocol: this is the only thread "
        "in the whole multi-process job that starts collectives, and "
        "followers answer on their main thread in exactly the order it "
        "broadcasts — the per-process-timing divergence the rule "
        "guards against is structurally absent, and the watchdog "
        "bounds a dead peer with a process exit instead of a hang"
    ),
}


class MeshReplicaError(RuntimeError):
    """Protocol-level failure of the multi-process replica."""


class MeshReplicaClosed(MeshReplicaError):
    """The replica is shut down and accepts no new work."""


class _Watchdog:
    """Bounded detection of a peer that will never arrive at a
    collective. A stuck gloo transfer cannot be interrupted from Python
    — no exception, no timeout knob on this jaxlib — so the only safe
    recovery is to take the whole process down: ``exit_fn`` (default
    ``os._exit``) fires once an armed deadline expires. Injectable for
    tests; ``arm``/``disarm`` are cheap enough to wrap every collective."""

    def __init__(
        self,
        timeout_s: float,
        *,
        registry=None,
        exit_fn=os._exit,
        interval_s: float = 0.25,
    ):
        self.timeout_s = float(timeout_s)
        self._exit_fn = exit_fn
        self._interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._deadline: Optional[float] = None
        self._why = ""
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._c_timeouts = (
            registry.counter("serve.mesh.peer_timeouts")
            if registry is not None
            else None
        )

    def arm(self, why: str) -> None:
        with self._lock:
            self._deadline = time.monotonic() + self.timeout_s
            self._why = why

    def disarm(self) -> None:
        with self._lock:
            self._deadline = None

    def _run(self) -> None:
        while not self._stop.wait(self._interval_s):
            with self._lock:
                deadline, why = self._deadline, self._why
            if deadline is not None and time.monotonic() > deadline:
                log.error(
                    "mesh replica watchdog: no collective progress for "
                    "%.1fs (%s) — a peer process is dead or wedged; "
                    "exiting rc=%d so the router can evict this logical "
                    "replica instead of hanging on it",
                    self.timeout_s, why, PEER_TIMEOUT_RC,
                )
                if self._c_timeouts is not None:
                    self._c_timeouts.inc()
                self._exit_fn(PEER_TIMEOUT_RC)
                return  # injected exit_fn (tests) does not exit

    def start(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="mesh-watchdog", daemon=True
                )
                self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None:
            t.join()


class MeshReplica:
    """The engine-shaped coordinator of one multi-process mesh replica.

    Presents the :class:`InferenceEngine` surface the micro-batcher,
    hot-reload watcher, and HTTP backend already consume (``predict``,
    ``swap_weights``, ``bucket_for``, ``buckets``, ``staging``, ...), so
    the leader's serving stack is byte-for-byte the single-host stack
    with this object in the engine's seat. See the module docstring for
    the protocol."""

    def __init__(
        self,
        engine,
        *,
        timeout_s: float = 60.0,
        heartbeat_s: Optional[float] = None,
        registry=None,
        exit_fn=os._exit,
    ):
        import jax

        self.engine = engine
        self.process_index = int(jax.process_index())
        self.process_count = int(jax.process_count())
        self.is_leader = self.process_index == 0
        self.timeout_s = float(timeout_s)
        # idle leader keep-alive cadence: well under timeout_s so a
        # healthy-but-quiet replica never trips a follower's watchdog
        self.heartbeat_s = (
            float(heartbeat_s)
            if heartbeat_s is not None
            else max(0.5, self.timeout_s / 4.0)
        )
        # a drain (MicroBatcher.close) behind a wedged collective is
        # bounded by the watchdog killing the process; give close() a
        # join bound past that so it can never outwait its own death
        self.drain_timeout_s = 2.0 * self.timeout_s
        self.barrier_generation = 0
        self._seq = 0
        self._queue: queue_lib.Queue = queue_lib.Queue()
        self._lock = threading.Lock()  # closed flag + dispatch handle
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._obs = registry
        reg = registry
        self._c_dispatches = (
            reg.counter("serve.mesh.dispatches") if reg else None
        )
        self._c_swaps = reg.counter("serve.mesh.swaps") if reg else None
        self._c_heartbeats = (
            reg.counter("serve.mesh.heartbeats") if reg else None
        )
        self._h_broadcast = (
            reg.histogram("serve.mesh.broadcast_ms") if reg else None
        )
        if reg is not None:
            reg.gauge("serve.mesh.processes").set(self.process_count)
            reg.gauge("serve.mesh.local_devices").set(
                jax.local_device_count()
            )
        self._exit_fn = exit_fn
        self._watchdog = _Watchdog(
            self.timeout_s, registry=registry, exit_fn=exit_fn
        )
        # follower swap placeholder: zeros at the engine's raw host avals
        # (broadcast_pytree needs a structurally identical tree on every
        # process; the values only matter on the leader)
        host = engine.weights_host()
        self._weight_placeholder = jax.tree_util.tree_map(
            lambda a: np.zeros(np.shape(a), np.asarray(a).dtype), host
        )
        # bootstrap: every process serves the LEADER's weights — whatever
        # each rank loaded from its own disk, the served state is
        # bit-identical by construction (the same broadcast path every
        # later hot reload takes)
        if self.process_count > 1:
            trees = broadcast_pytree(
                host if self.is_leader else self._weight_placeholder
            )
            engine.swap_weights(trees[0], trees[1])
        self.warmup_barrier()
        if self.process_count > 1:
            self._watchdog.start()
        if self.is_leader:
            with self._lock:
                self._thread = threading.Thread(
                    target=self._dispatch_loop,
                    name="mesh-dispatch",
                    daemon=True,
                )
                self._thread.start()
        else:
            # armed from here on: the leader heartbeats while idle, so a
            # silent leader within timeout_s means it is gone
            self._watchdog.arm("waiting for the first leader command")

    # -- distributed warmup barrier ------------------------------------

    def warmup_barrier(self) -> None:
        """Collective rendezvous per bucket before the replica may serve
        (the SERVING.md deferral): every process runs the canonical
        probe batch through its compiled program — the execution itself
        blocks until all peers arrive, so a straggler still compiling or
        importing holds everyone at the barrier — and the leader's
        logits are broadcast and checked bit-identical on every process.
        Weights agree by the bootstrap broadcast; this checks that the
        EXECUTABLES agree (a process that imported a divergent cache
        entry or compiled against different avals fails loudly here,
        before the replica reports healthy). Advances
        ``barrier_generation`` (surfaced via /healthz) on success."""
        eng = self.engine
        if not eng._compiled:
            eng.warmup()
        probe_weights = eng._probe_weights()
        for b in eng.buckets:
            got = eng._run_probe(
                eng._compiled[b], probe_weights, eng._probe_batch(b)
            )
            if self.process_count > 1:
                ref = broadcast_pytree(
                    got if self.is_leader else np.zeros_like(got)
                )
                if not np.array_equal(ref, got):
                    raise MeshReplicaError(
                        f"process {self.process_index} diverges from the "
                        f"leader at bucket {b} during the warmup barrier "
                        f"(max |diff| {np.max(np.abs(ref - got))}): this "
                        f"process must not serve"
                    )
        self.barrier_generation += 1
        if self._obs is not None:
            self._obs.gauge("serve.mesh.barrier_generation").set(
                self.barrier_generation
            )
        trace.instant(
            "serve/mesh_barrier",
            generation=self.barrier_generation,
            processes=self.process_count,
        )

    # -- engine-shaped surface (leader) --------------------------------

    def predict(self, images: np.ndarray) -> np.ndarray:
        """uint8 NHWC batch of any size -> fp32 logits, computed by the
        WHOLE multi-process mesh. Leader only — followers mirror through
        :meth:`follower_loop`."""
        if not self.is_leader:
            raise MeshReplicaError(
                "predict() is leader-only; followers run follower_loop()"
            )
        # chaos injection point, BEFORE anything is broadcast: an
        # injected engine failure fails only this batch and never
        # desyncs the follower protocol (the batcher contains it)
        faults.maybe_raise("serve_error")
        x = np.asarray(images)
        if x.ndim != 4 or x.shape[1:] != self.engine.image_shape:
            raise ValueError(
                f"expected (n, "
                f"{', '.join(map(str, self.engine.image_shape))}) images, "
                f"got {x.shape}"
            )
        return self._submit(OP_BATCH, x).result()

    def swap_weights(self, params, batch_stats) -> int:
        """Atomic fleet-wide weight swap: validates avals on THIS thread
        (a wrong-model checkpoint is rejected before any broadcast),
        then the dispatch loop broadcasts the trees and every process
        swaps the same generation in lock-step."""
        self.engine.check_swap_avals(params, batch_stats)
        return self._submit(OP_SWAP, (params, batch_stats or {})).result()

    def weights_host(self):
        return self.engine.weights_host()

    def bucket_for(self, n: int) -> int:
        return self.engine.bucket_for(n)

    def shard_split(self, n: int):
        return self.engine.shard_split(n)

    def mesh_health(self) -> dict:
        """The topology block /healthz surfaces so a half-joined replica
        is diagnosable from a probe (ISSUE: process span, per-process
        devices, barrier generation)."""
        import jax

        return {
            "process_count": self.process_count,
            "process_index": self.process_index,
            "local_devices": int(jax.local_device_count()),
            "global_devices": int(self.engine.n_devices),
            "barrier_generation": int(self.barrier_generation),
            "timeout_s": self.timeout_s,
            "engine_version": int(self.engine.version),
        }

    # the rest of the engine surface the batcher / backend / watcher /
    # CLI read — plain delegation, so the leader's serving stack needs
    # no mesh-awareness anywhere else
    @property
    def buckets(self):
        return self.engine.buckets

    @property
    def n_devices(self) -> int:
        return self.engine.n_devices

    @property
    def staging(self):
        return self.engine.staging

    @property
    def model_name(self) -> str:
        return self.engine.model_name

    @property
    def num_classes(self) -> int:
        return self.engine.num_classes

    @property
    def image_shape(self):
        return self.engine.image_shape

    @property
    def compile_count(self) -> int:
        return self.engine.compile_count

    @property
    def version(self) -> int:
        return self.engine.version

    @property
    def aot_cache_hits(self) -> int:
        return self.engine.aot_cache_hits

    @property
    def aot_cache_misses(self) -> int:
        return self.engine.aot_cache_misses

    @property
    def cold_start_s(self) -> float:
        return self.engine.cold_start_s

    @property
    def checkpoint_meta(self) -> dict:
        return getattr(self.engine, "checkpoint_meta", {})

    # -- leader dispatch -----------------------------------------------

    def _fatal(self, why: str) -> None:
        """A collective RAISED with peers attached (module docstring,
        failure mode b): the ranks are desynced mid-protocol, so this
        process must leave the fleet rather than zombie-serve. Same exit
        code as the watchdog's hang detection — the launcher and router
        see one failure class either way."""
        log.error(
            "mesh replica: collective failed (%s) — the ranks are "
            "desynced; exiting rc=%d so the router evicts this logical "
            "replica instead of flapping on a zombie", why,
            PEER_TIMEOUT_RC,
        )
        if self._obs is not None:
            self._obs.counter("serve.mesh.peer_timeouts").inc()
        self._exit_fn(PEER_TIMEOUT_RC)

    def _submit(self, op: int, payload) -> Future:
        fut: Future = Future()
        with self._lock:
            if self._closed:
                raise MeshReplicaClosed("mesh replica is shut down")
            self._queue.put((op, payload, fut))
        return fut

    def _cmd(self, op: int, n: int) -> np.ndarray:
        with self._lock:  # _seq is read by mesh_health/tests off-thread
            self._seq += 1
            seq = self._seq
        return np.asarray([op, n, seq, 0], np.int64)

    def _dispatch_loop(self) -> None:
        """The single collective initiator (module docstring; declared
        in GRAFTCHECK_SANCTIONED_COLLECTIVE_ENTRIES). Drains the work
        queue in FIFO order, broadcasting each item to the followers and
        entering the shared bucket program with them; broadcasts a
        heartbeat when idle so follower watchdogs can tell a quiet
        leader from a dead one. Every collective is bracketed by the
        watchdog — a peer that never arrives turns into a bounded
        process exit, not a hang."""
        multi = self.process_count > 1
        while True:
            try:
                op, payload, fut = self._queue.get(
                    timeout=self.heartbeat_s
                )
            except queue_lib.Empty:
                if multi:
                    try:
                        self._watchdog.arm("heartbeat broadcast")
                        broadcast_pytree(self._cmd(OP_HEARTBEAT, 0))
                        self._watchdog.disarm()
                    except Exception as e:
                        self._watchdog.disarm()
                        self._fatal(f"heartbeat broadcast: {e}")
                        return  # injected exit_fn (tests) does not exit
                    if self._c_heartbeats is not None:
                        self._c_heartbeats.inc()
                continue
            if op == OP_SHUTDOWN:
                try:
                    if multi:
                        self._watchdog.arm("shutdown broadcast")
                        broadcast_pytree(self._cmd(OP_SHUTDOWN, 0))
                        self._watchdog.disarm()
                except Exception as e:  # peers already gone: still done
                    self._watchdog.disarm()
                    log.warning("shutdown broadcast failed: %s", e)
                fut.set_result(None)
                return
            if op == OP_SWAP:
                try:
                    self._watchdog.arm("weight-swap broadcast")
                    if multi:
                        broadcast_pytree(self._cmd(OP_SWAP, 0))
                        payload = broadcast_pytree(payload)
                    version = self.engine.swap_weights(
                        payload[0], payload[1]
                    )
                    self._watchdog.disarm()
                    if self._c_swaps is not None:
                        self._c_swaps.inc()
                    fut.set_result(version)
                except Exception as e:
                    self._watchdog.disarm()
                    fut.set_exception(e)
                    if multi:
                        # followers may already have swapped: desynced
                        self._fatal(f"swap broadcast: {e}")
                        return
                continue
            # OP_BATCH: chunk through the largest bucket — one command +
            # payload broadcast + collective bucket call per chunk, the
            # same chunking engine.predict applies
            try:
                x = payload
                cap = self.engine.buckets[-1]
                outs = []
                for off in range(0, x.shape[0], cap):
                    chunk = np.ascontiguousarray(x[off : off + cap])
                    self._watchdog.arm(
                        f"batch broadcast+execute (n={chunk.shape[0]})"
                    )
                    t0 = time.perf_counter()
                    if multi:
                        broadcast_pytree(
                            self._cmd(OP_BATCH, chunk.shape[0])
                        )
                        chunk = broadcast_pytree(chunk)
                        if self._h_broadcast is not None:
                            self._h_broadcast.observe(
                                (time.perf_counter() - t0) * 1e3
                            )
                    outs.append(self.engine._run_bucket(chunk))
                    self._watchdog.disarm()
                if self._c_dispatches is not None:
                    self._c_dispatches.inc()
                fut.set_result(
                    outs[0] if len(outs) == 1 else np.concatenate(outs)
                )
            except Exception as e:
                self._watchdog.disarm()
                fut.set_exception(e)
                if multi:
                    # a command or payload broadcast (or the collective
                    # bucket call) failed with peers attached: fatal —
                    # a local engine error cannot reach here multi-
                    # process, the broadcast is the first thing a chunk
                    # does (and predict() runs its fault injection
                    # BEFORE submitting)
                    self._fatal(f"batch dispatch: {e}")
                    return

    # -- follower ------------------------------------------------------

    def follower_loop(self) -> None:
        """Run on a follower's MAIN thread until the leader broadcasts
        shutdown: block on the next command, mirror it (enter the bucket
        program / swap the broadcast weights / ignore a heartbeat). The
        watchdog is re-armed on every received command, so a leader that
        dies takes this process down within ``timeout_s`` instead of
        leaving it wedged in gloo forever."""
        if self.is_leader:
            raise MeshReplicaError("follower_loop() is follower-only")
        eng = self.engine
        try:
            self._follower_loop_body(eng)
        except Exception as e:  # failure mode (b): desynced, terminal
            self._watchdog.disarm()
            self._fatal(f"follower collective: {e}")
        finally:
            self._watchdog.disarm()
            self._watchdog.stop()
            with self._lock:
                self._closed = True

    def _follower_loop_body(self, eng) -> None:
        while True:
            self._watchdog.arm("waiting for the next leader command")
            cmd = broadcast_pytree(np.zeros(_CMD_LEN, np.int64))
            op, n = int(cmd[0]), int(cmd[1])
            if op == OP_HEARTBEAT:
                if self._c_heartbeats is not None:
                    self._c_heartbeats.inc()
                continue
            if op == OP_SHUTDOWN:
                return
            if op == OP_SWAP:
                trees = broadcast_pytree(self._weight_placeholder)
                eng.swap_weights(trees[0], trees[1])
                if self._c_swaps is not None:
                    self._c_swaps.inc()
                continue
            if op == OP_BATCH:
                x = broadcast_pytree(
                    np.zeros((n, *eng.image_shape), np.uint8)
                )
                eng._run_bucket(x)
                if self._c_dispatches is not None:
                    self._c_dispatches.inc()
                continue
            raise MeshReplicaError(
                f"unknown mesh command op={op} (protocol skew?)"
            )

    # -- lifecycle -----------------------------------------------------

    def close(self) -> None:
        """Leader: drain the dispatch queue's tail, broadcast shutdown
        (followers' loops return), join the dispatch thread and stop the
        watchdog. Idempotent; follower close is a local flag (its loop
        exits on the leader's broadcast)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if not self.is_leader:
            return
        fut: Future = Future()
        self._queue.put((OP_SHUTDOWN, None, fut))
        try:
            fut.result(timeout=self.drain_timeout_s)
        except Exception:  # watchdog will have killed a wedged process
            log.warning("mesh replica shutdown broadcast did not confirm")
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None:
            t.join(timeout=self.drain_timeout_s)
        self._watchdog.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
