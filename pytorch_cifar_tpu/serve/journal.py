"""Durable control-plane journal: the controller that survives its death.

The fleet is elastic (SERVING.md "Elastic fleet") and the edge is
non-blocking, but a control plane that keeps fleet membership, cooldown
clocks, and rollout state only in memory forgets the fleet when it dies
— a restarted controller would mass-respawn replicas that are still
healthy. This module closes that gap (ROADMAP item 5; SERVING.md
"Durable control plane"):

- :class:`ControllerJournal` — an append-only journal with the
  checkpoint layer's durability discipline
  (``train/checkpoint._atomic_write``): every record is CRC-framed,
  written, flushed, and **fsync'd before append() returns**, so the
  actuation it records (spawn, drain, traffic shift) can never outrun
  its own durable evidence. Compaction snapshots reuse the
  tmp+fsync+rename idiom with the commit marker written LAST — a crash
  at any point leaves either the old complete journal or the new
  complete snapshot, never a state the replay trusts wrongly
  (graftcheck's ``journal-write-ordering`` rule checks both shapes
  statically).
- :func:`replay_journal` — tolerant replay: a torn FINAL record (the
  crash landed mid-append) is dropped and reported; a bad record
  anywhere else, or a sequence-number regression, raises
  :class:`JournalCorrupt` (``tools/journal_inspect.py`` exits 2 on it).
- :class:`FleetJournalState` — the pure reducer from a record stream to
  control-plane state: live replica table (idx/pid/url/generation),
  scaling-window + cooldown stamps, rollout generation/phase, and the
  canary vetting ledger. ``serve/fleet.recover_controller`` replays it
  against live ``/healthz`` probes to re-adopt the fleet.
- :class:`JournalFollower` — a declarative membership syncer for a data
  plane operated by a REMOTE controller process: it polls the journal,
  reduces it, and diffs the resulting replica set against a live
  :class:`~pytorch_cifar_tpu.serve.router.Router` (add the missing,
  remove the gone). The journal is the single source of truth for
  membership, so the edge and the controller can die independently.

Pure stdlib on purpose: ``tools/chaos_run.py`` and
``tools/journal_inspect.py`` import this module without pulling in jax.

Telemetry (OBSERVABILITY.md "elastic fleet"):
``serve.fleet.journal_appends`` counts durable appends.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

SNAPSHOT_SUFFIX = ".snapshot"
SNAPSHOT_MARKER_SUFFIX = ".snapshot.json"


class JournalCorrupt(RuntimeError):
    """The journal cannot be replayed: a record BEFORE the final one is
    undecodable, fails its CRC, or the sequence numbers regress. A torn
    final record is NOT corruption (the crash landed mid-append) — it is
    dropped and reported by :func:`replay_journal`."""


def _fsync_dir(dirpath: str) -> None:
    """Durably record a rename/append in its directory (the checkpoint
    layer's discipline). Best-effort: some filesystems reject it."""
    try:
        fd = os.open(dirpath or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + fsync + rename + dir fsync — the exact publish shape
    ``train/checkpoint._atomic_write`` sanctions (duplicated here so the
    journal stays importable without the checkpoint module's jax
    dependency)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


def _canon(rec: dict) -> bytes:
    return json.dumps(
        rec, sort_keys=True, separators=(",", ":")
    ).encode("utf-8")


def _encode_record(rec: dict) -> bytes:
    body = _canon(rec)
    frame = {"crc": zlib.crc32(body) & 0xFFFFFFFF, "rec": rec}
    return (
        json.dumps(frame, sort_keys=True, separators=(",", ":")).encode(
            "utf-8"
        )
        + b"\n"
    )


def _decode_line(line: bytes) -> dict:
    """One framed record back out; raises ValueError on any damage."""
    frame = json.loads(line.decode("utf-8"))
    rec = frame["rec"]
    if not isinstance(rec, dict):
        raise ValueError("record frame is not an object")
    if zlib.crc32(_canon(rec)) & 0xFFFFFFFF != int(frame["crc"]):
        raise ValueError("record crc mismatch")
    return rec


def _read_snapshot(path: str) -> Tuple[List[dict], int]:
    """The committed compaction snapshot for journal ``path``, or
    ``([], 0)`` when there is none. An unverifiable snapshot (torn
    payload, stale marker) is IGNORED, not an error: the live journal is
    only truncated AFTER the marker commits, so whenever the snapshot
    does not verify the full record stream is still in the live file."""
    snap, marker = path + SNAPSHOT_SUFFIX, path + SNAPSHOT_MARKER_SUFFIX
    try:
        with open(marker, "rb") as f:
            meta = json.load(f)
        with open(snap, "rb") as f:
            payload = f.read()
    except (OSError, ValueError):
        return [], 0
    if len(payload) != int(meta.get("size", -1)) or (
        zlib.crc32(payload) & 0xFFFFFFFF != int(meta.get("crc32", -1))
    ):
        return [], 0
    obj = json.loads(payload.decode("utf-8"))
    return list(obj.get("records", ())), int(obj.get("base_seq", 0))


def replay_journal(path: str) -> Tuple[List[dict], bool]:
    """Replay journal ``path`` → ``(records, torn_tail)``.

    Records from a committed compaction snapshot come first, then every
    live record with ``seq > base_seq`` (a crash between the snapshot's
    marker commit and the live-file truncate leaves both on disk — the
    overlap is skipped, never double-applied). A missing journal is an
    empty one. Raises :class:`JournalCorrupt` on a damaged non-final
    record or a sequence regression."""
    records, base_seq = _read_snapshot(path)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return records, False
    lines = raw.split(b"\n")
    torn = False
    if lines and lines[-1] == b"":
        lines.pop()  # the normal trailing newline
    elif lines:
        torn = True  # no final newline: the last append was cut short
    last_seq = None
    for i, line in enumerate(lines):
        final = i == len(lines) - 1
        try:
            rec = _decode_line(line)
            seq = int(rec["seq"])
        except (ValueError, KeyError, TypeError) as e:
            if final:
                return records, True  # torn tail: crash mid-append
            raise JournalCorrupt(
                f"{path}: record {i + 1} is unreadable ({e}) and is not "
                "the final record — the journal is damaged, not torn"
            )
        if final and torn:
            # decodable bytes but no newline: still an incomplete append
            return records, True
        if seq <= base_seq:
            continue  # already summarized by the snapshot
        if last_seq is not None and seq <= last_seq:
            raise JournalCorrupt(
                f"{path}: sequence regressed ({seq} after {last_seq}) — "
                "interleaved writers or a rewound file"
            )
        last_seq = seq
        records.append(rec)
    return records, torn


class ControllerJournal:
    """The append-durable actuation journal (module docstring).

    ``append(op, **fields)`` frames the record, writes it, and fsyncs
    the file BEFORE returning — callers journal the intent first and
    actuate second, so a crash can lose an actuation but never the
    record of one that happened. ``compact(records)`` snapshots a
    caller-reduced record list (payload first, commit marker LAST, both
    via tmp+fsync+rename) and truncates the live file."""

    def __init__(self, path: str, *, registry=None):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        # continue the sequence where the existing journal ends; raises
        # JournalCorrupt loudly rather than appending after damage
        records, _ = replay_journal(path)
        seqs = [int(r["seq"]) for r in records if "seq" in r]
        _, base_seq = _read_snapshot(path)
        self._seq = max([base_seq] + seqs)
        self._lock = threading.Lock()
        self._f = open(path, "ab")
        _fsync_dir(d)  # the journal file's own creation is durable
        self._c_appends = None
        if registry is not None:
            self._c_appends = registry.counter(
                "serve.fleet.journal_appends"
            )

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def append(self, op: str, **fields) -> dict:
        """Durably append one record and return it. The fsync happens
        HERE, before any caller actuation — the whole point."""
        with self._lock:
            self._seq += 1
            rec = {"seq": self._seq, "wall": time.time(), "op": str(op)}
            rec.update(fields)
            self._f.write(_encode_record(rec))
            self._f.flush()
            os.fsync(self._f.fileno())
        if self._c_appends is not None:
            self._c_appends.inc()
        return rec

    def records(self) -> List[dict]:
        """The replayable record stream (torn tail dropped)."""
        return replay_journal(self.path)[0]

    def compact(self, records: List[dict]) -> None:
        """Replace the journal's history with ``records`` (a
        caller-reduced summary that replays to the same state — e.g.
        one ``adopt`` per live replica). Payload first, commit marker
        last, live file truncated only after the marker commits: replay
        stays correct across a crash at ANY point in between."""
        with self._lock:
            payload = json.dumps(
                {"base_seq": self._seq, "records": list(records)},
                sort_keys=True,
            ).encode("utf-8")
            _atomic_write(self.path + SNAPSHOT_SUFFIX, payload)
            marker = {
                "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
                "size": len(payload),
                "base_seq": self._seq,
            }
            _atomic_write(
                self.path + SNAPSHOT_MARKER_SUFFIX,
                json.dumps(marker).encode("utf-8"),
            )
            self._f.close()
            with open(self.path, "wb") as f:
                f.flush()
                os.fsync(f.fileno())
            self._f = open(self.path, "ab")
            _fsync_dir(os.path.dirname(os.path.abspath(self.path)))

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


class FleetJournalState:
    """Pure reducer: record stream → control-plane state. No I/O, no
    clocks — ``recover_controller`` and ``journal_inspect`` both build
    their view of the world from exactly this."""

    def __init__(self):
        # url -> {"idx", "pid", "generation", "compiles", "draining"}
        self.replicas: Dict[str, dict] = {}
        self.next_idx = 0
        self.policy_state: dict = {}
        self.generation: Optional[int] = None
        self.rollout: Optional[dict] = None
        self.vetting: Optional[dict] = None
        self.promotion_generation: Optional[int] = None
        self.spawn_intents: Dict[int, float] = {}
        self.rollouts = 0
        self.rollbacks = 0

    @classmethod
    def from_records(cls, records: List[dict]) -> "FleetJournalState":
        state = cls()
        for rec in records:
            state.apply(rec)
        return state

    def _bump_idx(self, idx) -> None:
        if idx is not None:
            self.next_idx = max(self.next_idx, int(idx) + 1)

    def apply(self, rec: dict) -> None:
        op = rec.get("op")
        idx = rec.get("idx")
        url = rec.get("url")
        if op == "spawn-intent":
            self._bump_idx(idx)
            self.spawn_intents[int(idx)] = rec.get("wall", 0.0)
        elif op == "spawn-failed":
            self.spawn_intents.pop(int(idx), None)
        elif op in ("replica-up", "adopt"):
            self._bump_idx(idx)
            if idx is not None:
                self.spawn_intents.pop(int(idx), None)
            self.replicas[url] = {
                "idx": idx,
                "pid": rec.get("pid"),
                "generation": rec.get("generation"),
                "compiles": rec.get("compiles"),
                "draining": False,
            }
        elif op == "drain-intent":
            if url in self.replicas:
                self.replicas[url]["draining"] = True
        elif op in ("drain-done", "reap"):
            self.replicas.pop(url, None)
        elif op == "policy":
            self.policy_state = {
                k: v
                for k, v in rec.items()
                if k not in ("seq", "wall", "op")
            }
        elif op == "generation":
            g = rec.get("generation")
            self.generation = None if g is None else int(g)
        elif op == "rollout-begin":
            self.rollout = {
                "from_generation": rec.get("from_generation"),
                "to_generation": rec.get("to_generation"),
                "n_start": rec.get("n_start"),
                "phase": "surge",
                "reason": None,
            }
        elif op == "rollout-phase":
            if self.rollout is not None:
                self.rollout["phase"] = rec.get("phase")
        elif op == "rollout-halt":
            if self.rollout is not None:
                self.rollout["phase"] = "rollback"
                self.rollout["reason"] = rec.get("reason")
        elif op == "rollout-done":
            g = rec.get("generation")
            self.generation = None if g is None else int(g)
            self.rollouts += 1
            self.rollout = None
        elif op == "rollout-rollback-done":
            self.rollbacks += 1
            self.rollout = None
        elif op == "vet-begin":
            self.vetting = {
                k: v
                for k, v in rec.items()
                if k not in ("seq", "wall", "op")
            }
        elif op == "vet-verdict":
            self.vetting = None
            if rec.get("verdict") == "promoted":
                g = rec.get("generation")
                if g is not None:
                    self.promotion_generation = int(g)
        # unknown ops are ignored: an older inspector must keep working
        # against a newer controller's journal

    def live_replicas(self) -> Dict[str, dict]:
        """Replicas the journal believes are serving (not mid-drain)."""
        return {
            u: dict(info)
            for u, info in self.replicas.items()
            if not info.get("draining")
        }

    def summary_records(self) -> List[dict]:
        """A minimal record list that replays to this state — what
        ``ControllerJournal.compact`` stores. Seq-less on purpose: the
        reducer never reads seq, and replay orders snapshot records
        before every live record."""
        out: List[dict] = []
        if self.generation is not None:
            out.append({"op": "generation", "generation": self.generation})
        for url, info in sorted(self.replicas.items()):
            out.append(
                {
                    "op": "adopt",
                    "idx": info.get("idx"),
                    "url": url,
                    "pid": info.get("pid"),
                    "generation": info.get("generation"),
                    "compiles": info.get("compiles"),
                }
            )
            if info.get("draining"):
                out.append(
                    {"op": "drain-intent", "idx": info.get("idx"),
                     "url": url}
                )
        if self.policy_state:
            out.append({"op": "policy", **self.policy_state})
        if self.promotion_generation is not None:
            out.append(
                {
                    "op": "vet-verdict",
                    "verdict": "promoted",
                    "generation": self.promotion_generation,
                }
            )
        if self.rollout is not None:
            out.append(
                {
                    "op": "rollout-begin",
                    "from_generation": self.rollout.get("from_generation"),
                    "to_generation": self.rollout.get("to_generation"),
                    "n_start": self.rollout.get("n_start"),
                }
            )
            phase = self.rollout.get("phase")
            if phase == "rollback":
                out.append(
                    {"op": "rollout-halt",
                     "reason": self.rollout.get("reason")}
                )
            elif phase not in (None, "surge"):
                out.append({"op": "rollout-phase", "phase": phase})
        if self.vetting is not None:
            out.append({"op": "vet-begin", **self.vetting})
        return out


class JournalFollower:
    """Membership syncer for a data plane whose controller is a SEPARATE
    process (the durable-control-plane drill): polls the journal,
    reduces it, and declaratively diffs the live replica set against the
    router — add what the journal has and the router lacks, remove what
    the router has and the journal dropped. Idempotent by construction
    (``Router.add_replica`` ignores a known URL), so a poll racing a
    compaction or a torn tail converges on the next sweep; a CORRUPT
    journal holds the last applied membership and logs (the edge must
    keep serving whatever fleet it has)."""

    def __init__(self, path: str, router, *, poll_s: float = 0.2):
        self.path = path
        self.router = router
        self.poll_s = float(poll_s)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self.syncs = 0
        self.corrupt_polls = 0

    def sync_once(self) -> Dict[str, dict]:
        """One poll: returns the journal's live replica view after
        applying the membership diff to the router."""
        try:
            records, _ = replay_journal(self.path)
        except JournalCorrupt as e:
            with self._lock:
                self.corrupt_polls += 1
            log.warning("journal follower holding membership: %s", e)
            return {}
        want = FleetJournalState.from_records(records).live_replicas()
        have = set(self.router.fleet_view().keys())
        for url in want:
            if url not in have:
                self.router.add_replica(url)
        for url in have - set(want):
            self.router.remove_replica(url)
        with self._lock:
            self.syncs += 1
        return want

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.sync_once()
            except Exception:
                log.exception("journal follower sweep failed")

    def start(self) -> "JournalFollower":
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="journal-follower", daemon=True
                )
                self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None:
            t.join()
