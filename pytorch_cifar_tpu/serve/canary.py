"""Canary promotion pipeline: no unvetted checkpoint ever reaches the fleet.

The pre-pipeline publish path was "trainer writes ``ckpt.msgpack``,
watcher swaps it in" — a NaN'd, bit-flipped, or regressed checkpoint goes
live on EVERY replica at the next poll. This module closes the loop
(ROADMAP item 5) by composing training, serving, robustness, and obs into
one continuous train→canary→promote pipeline with an explicit blast
radius of ONE canary replica:

- the trainer publishes into a **staging** dir (``--publish staging``;
  ``train/checkpoint.py``) that no serving watcher will ever load —
  ``serve/reload.py`` refuses staging dirs outright;
- a **canary engine** — a full :class:`InferenceEngine` holding its own
  copy of the weights — loads each staged candidate and shadows a
  configurable slice of live traffic: the router (or
  :class:`ShadowBackend` on a single replica) tees each *answered*
  interactive request to the canary OFF the client response path, so
  clients keep their bits and their deadlines no matter what the
  candidate does;
- the :class:`PromotionController` vets the candidate **exactly, not
  statistically** — the engine's bit-identity guarantees (padding, mesh,
  AOT import, hot reload: SERVING.md) mean the canary's logits for a
  given weight set equal the fleet's bit-for-bit, so "how many golden
  rows changed answer" is a count, not an estimate — against a
  sentinel-style :class:`CanaryBudget`, and either **promotes**
  (atomic republish into the live dir, commit-marker-last per the
  format discipline, promotion generation stamped into the sidecar) or
  **rolls the canary back and quarantines** the candidate (tombstone
  sidecar + ``canary.rejected``; the trainer keeps running and the
  fleet never saw a byte of it).

State machine — one candidate at a time, driven by ``poll_once``::

    staging ──load+golden ok──> shadowing ──shadow budget ok──> promoted
       │  └─corrupt / wrong-model / golden fail──> quarantined     │
       │                   └──shadow budget blown──> quarantined   │
       └──────────────<─────(next staged publish)─────<────────────┘

Budget semantics (every term an exact count — ROBUSTNESS.md "canary
promotion" maps each to the checkpoint failure it catches):

- ``max_nonfinite``: golden rows allowed a non-finite logit (a NaN'd
  checkpoint fails here — the file itself is committed and CRC-clean);
- ``acc_margin``: with labeled golden data, how many accuracy points
  the candidate may trail the incumbent (the principled regression
  gate: a genuinely better checkpoint passes, weight noise does not);
- ``max_flip_frac``: fraction of golden rows whose argmax may differ
  from the incumbent's — the gate for UNLABELED golden data only. A
  flip is evidence of damage only when nothing can prove the flips are
  improvements: early-training candidates legitimately flip most
  answers while accuracy climbs, so with labels present the accuracy
  gate judges and the exact flip counts are recorded as diagnostics;
- ``min_shadow_requests`` / ``max_shadow_errors`` /
  ``max_shadow_flip_frac``: the live-traffic soak — the candidate must
  answer that many shadowed requests with at most that much error and
  divergence before promotion (0 = golden-only gate).

A CRC-corrupt candidate (bitflip, torn pair that settled) never reaches
vetting: the manifest-verified load rejects it and the controller
quarantines on the spot. Rollback is exact: the canary swaps back to the
incumbent's weight trees, so its post-rollback outputs are bit-identical
to pre-candidate — the same guarantee the fleet kept the whole time.

``tools/pipeline_run.py`` wires the whole loop into one process;
``tools/chaos_run.py --mode canary`` is the acceptance drill.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from pytorch_cifar_tpu.obs import MetricsRegistry, trace
from pytorch_cifar_tpu.serve.engine import load_checkpoint_trees
from pytorch_cifar_tpu.train.checkpoint import (
    CKPT_NAME,
    CheckpointCorrupt,
    is_quarantined,
    meta_path,
    publish_checkpoint,
    quarantine_checkpoint,
)

log = logging.getLogger(__name__)

# canary replica states (module docstring); the gauge encodes this order
STAGING = "staging"
SHADOWING = "shadowing"
PROMOTED = "promoted"
QUARANTINED = "quarantined"
_STATE_IDS = {STAGING: 0, SHADOWING: 1, PROMOTED: 2, QUARANTINED: 3}


def _read_meta(dirpath: str, name: str) -> dict:
    try:
        with open(meta_path(dirpath, name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


class GoldenSet:
    """The deterministic vetting batches every candidate answers before
    it may touch live traffic. ``labels`` are optional: with them the
    budget's accuracy gate applies (the principled regression check);
    without them the exact flip-count gate still does."""

    def __init__(self, images, labels=None):
        self.images = np.ascontiguousarray(np.asarray(images, np.uint8))
        if self.images.ndim != 4:
            raise ValueError(
                f"golden images must be (n, h, w, c), got "
                f"{self.images.shape}"
            )
        self.labels = None if labels is None else np.asarray(labels)
        if self.labels is not None and len(self.labels) != len(self.images):
            raise ValueError("golden labels/images length mismatch")

    def __len__(self) -> int:
        return int(self.images.shape[0])

    @classmethod
    def synthetic_eval(
        cls, n_train: int = 2048, n_test: int = 512, seed: int = 0,
        limit: int = 256,
    ) -> "GoldenSet":
        """The synthetic CIFAR eval split a ``--synthetic_data`` trainer
        evaluates on (``data.cifar10.synthetic_cifar10`` is
        (sizes, seed)-deterministic), capped at ``limit`` rows — so the
        golden accuracy gate measures the very quantity the trainer's
        best-checkpoint gate optimizes."""
        from pytorch_cifar_tpu.data.cifar10 import synthetic_cifar10

        _, _, x, y = synthetic_cifar10(
            n_train=n_train, n_test=n_test, seed=seed
        )
        return cls(x[:limit], y[:limit])

    @classmethod
    def labeled_eval(
        cls,
        data_dir: str = "./data",
        *,
        limit: int = 256,
        seed: int = 0,
        download: bool = False,
    ) -> "GoldenSet":
        """The REAL labeled eval split — the same CIFAR-10 test set
        ``tools/accuracy_run.py`` measures the north-star accuracy on —
        as golden data, so a :class:`CanaryBudget`'s accuracy gate
        judges exact labeled accuracy rather than argmax-flip fraction
        (the ROADMAP standing item: per-tenant canary budgets gating on
        real accuracy). Falls back LOUDLY to the deterministic
        synthetic eval split when the archive is absent and
        ``download`` is False (zero-egress build containers: the gate
        semantics are identical, only the labels' provenance differs).
        Per-tenant zoo canaries default to this
        (:meth:`~pytorch_cifar_tpu.serve.tenancy.ModelZooServer.enable_canary`).
        """
        from pytorch_cifar_tpu.data.cifar10 import (
            _find_dataset,
            load_cifar10,
            synthetic_cifar10,
        )

        if _find_dataset(data_dir) is None and not download:
            log.warning(
                "labeled_eval: CIFAR-10 not found under %r (download "
                "disabled); golden accuracy gates run on the SYNTHETIC "
                "eval split — same exact-count semantics, synthetic "
                "labels", data_dir,
            )
            _, _, x, y = synthetic_cifar10(seed=seed)
        else:
            _, _, x, y = load_cifar10(data_dir, synthetic_ok=True)
        return cls(x[:limit], y[:limit])

    @classmethod
    def random(
        cls, n: int = 64, seed: int = 0, image_shape=(32, 32, 3)
    ) -> "GoldenSet":
        """Unlabeled random batches: the finiteness + exact-flip gates
        only (bench and tests)."""
        rs = np.random.RandomState(seed)
        return cls(rs.randint(0, 256, size=(n, *image_shape)).astype(np.uint8))


class CanaryBudget:
    """Sentinel-style promotion budget (module docstring). Every term is
    an exact count over golden/shadowed rows, so a verdict is
    reproducible — rerunning the same candidate against the same
    incumbent yields the same decision, bit for bit."""

    def __init__(
        self,
        *,
        max_nonfinite: int = 0,
        max_flip_frac: float = 0.5,
        acc_margin: float = 1.0,
        min_shadow_requests: int = 0,
        max_shadow_errors: int = 0,
        max_shadow_flip_frac: Optional[float] = None,
    ):
        self.max_nonfinite = int(max_nonfinite)
        self.max_flip_frac = float(max_flip_frac)
        self.acc_margin = float(acc_margin)
        self.min_shadow_requests = int(min_shadow_requests)
        self.max_shadow_errors = int(max_shadow_errors)
        self.max_shadow_flip_frac = (
            float(max_shadow_flip_frac)
            if max_shadow_flip_frac is not None
            else float(max_flip_frac)
        )


class PromotionController:
    """The canary replica's state machine (module docstring).

    ``canary_engine`` must hold the INCUMBENT weights at construction
    (build it from the live dir) — they are snapshotted as the rollback
    target and their golden logits become the exact comparison baseline.
    ``poll_once`` drives one step deterministically (tests and bench);
    ``start``/``stop`` run it on a poll thread plus a shadow worker, both
    joined on stop (no thread leak). Every cross-thread attribute is
    mutated only under ``self._cond`` (graftcheck
    unlocked-shared-mutation passes by construction)."""

    def __init__(
        self,
        canary_engine,
        staging_dir: str,
        live_dir: str,
        *,
        golden: GoldenSet,
        budget: Optional[CanaryBudget] = None,
        name: str = CKPT_NAME,
        poll_s: float = 0.5,
        shadow_fraction: float = 0.25,
        shadow_queue: int = 64,
        registry: Optional[MetricsRegistry] = None,
        journal=None,
    ):
        self.engine = canary_engine
        self.staging_dir = staging_dir
        self.live_dir = live_dir
        # durable control plane: in-flight vetting + the generation
        # counter survive a controller restart via the shared journal
        # (serve/journal.py) — vet-begin is appended before the candidate
        # swap, the verdict before the publish/quarantine actuation
        self.journal = journal
        self.golden = golden
        self.budget = budget if budget is not None else CanaryBudget()
        self.name = name
        self.poll_s = float(poll_s)
        self.shadow_fraction = float(shadow_fraction)
        self.shadow_queue = int(shadow_queue)
        self.obs = registry if registry is not None else MetricsRegistry()
        self._c_candidates = self.obs.counter("canary.candidates")
        self._c_promotions = self.obs.counter("canary.promotions")
        self._c_rejected = self.obs.counter("canary.rejected")
        self._c_shadow_requests = self.obs.counter("canary.shadow_requests")
        self._c_shadow_rows = self.obs.counter("canary.shadow_rows")
        self._c_shadow_flips = self.obs.counter("canary.shadow_flip_rows")
        self._c_shadow_identical = self.obs.counter("canary.shadow_identical")
        self._c_shadow_errors = self.obs.counter("canary.shadow_errors")
        self._c_shadow_dropped = self.obs.counter("canary.shadow_dropped")
        self._h_promote = self.obs.histogram("canary.promote_ms")
        self._h_golden = self.obs.histogram("canary.golden_ms")
        self._h_shadow = self.obs.histogram("canary.shadow_ms")
        self._g_generation = self.obs.gauge("canary.generation")
        self._g_state = self.obs.gauge("canary.state")
        self._g_shadow_remaining = self.obs.gauge(
            "canary.shadow_budget_remaining"
        )
        # ONE condition over every cross-thread field below: the poll
        # thread, the shadow worker, offer() callers (frontend handler
        # threads), and status() readers all take it
        self._cond = threading.Condition()
        self.state = STAGING
        self.generation = 0
        if journal is not None:
            # restart-safety: resume the generation counter from the
            # journal's vetting ledger so a relaunched controller never
            # re-issues an already-served generation number
            from pytorch_cifar_tpu.serve.journal import FleetJournalState

            replayed = FleetJournalState.from_records(journal.records())
            if replayed.promotion_generation is not None:
                self.generation = int(replayed.promotion_generation)
        self.last_rejected: Optional[dict] = None
        self._seen_sig = None
        self._corrupt_sig = None
        self._candidate: Optional[dict] = None
        self._candidate_sig = None
        # monotonically bumped on every verdict: shadow samples carry the
        # token they were offered under, so a result computed against a
        # retired candidate can never pollute the next one's accounting
        self._token = 0
        self._offers = 0
        self._queue: deque = deque()
        self._shadow = self._zero_shadow()
        self._stop = threading.Event()
        self._stopping = False
        self._poll_thread: Optional[threading.Thread] = None
        self._shadow_thread: Optional[threading.Thread] = None
        # incumbent snapshot: rollback target + exact golden baseline
        self._incumbent = canary_engine.weights_host()
        base = self._golden_eval()
        self._incumbent_logits = base["logits"]
        self._incumbent_argmax = base["argmax"]
        self._incumbent_acc = base["acc"]
        self._g_state.set(_STATE_IDS[STAGING])

    @staticmethod
    def _zero_shadow() -> dict:
        return {
            "requests": 0, "rows": 0, "flip_rows": 0, "identical": 0,
            "errors": 0,
        }

    # -- staging signature (same scheme as the reload watcher) ----------

    def _journal(self, op: str, **fields) -> None:
        """Durably append one vetting record BEFORE the actuation it
        describes (no-op without a journal — the pre-durable behavior)."""
        if self.journal is not None:
            # graftcheck: noqa[unlocked-shared-mutation] -- ControllerJournal.append serializes internally (its own mutex) and fsyncs; appending under self._cond would hold the vetting lock across disk I/O
            self.journal.append(op, **fields)

    def _signature(self):
        def stat_of(path):
            try:
                st = os.stat(path)
            except OSError:
                return None
            return (st.st_ino, st.st_mtime_ns, st.st_size)

        payload = stat_of(os.path.join(self.staging_dir, self.name))
        sidecar = stat_of(meta_path(self.staging_dir, self.name))
        if payload is None and sidecar is None:
            return None
        return (payload, sidecar)

    def pending_candidate(self) -> bool:
        """True while a staged publish still awaits a verdict — what a
        pipeline driver polls before declaring the run quiesced."""
        sig = self._signature()
        with self._cond:
            return self.state == SHADOWING or (
                sig is not None and sig != self._seen_sig
            )

    # -- golden vetting (exact) -----------------------------------------

    def _golden_eval(self) -> dict:
        """Exact golden verdict for whatever weights the canary engine
        currently serves: logits, per-row argmax, non-finite row count,
        and (with labels) exact accuracy."""
        t0 = time.perf_counter()
        logits = np.asarray(self.engine.predict(self.golden.images))
        ms = (time.perf_counter() - t0) * 1e3
        self._h_golden.observe(ms)
        am = np.argmax(logits, axis=-1)
        finite_rows = np.isfinite(logits).all(axis=-1)
        acc = None
        if self.golden.labels is not None:
            acc = 100.0 * float(np.mean(am == self.golden.labels))
        return {
            "logits": logits,
            "argmax": am,
            "nonfinite": int(np.sum(~finite_rows)),
            "acc": acc,
            "ms": ms,
        }

    def _golden_failures(self, verdict: dict) -> list:
        """Budget verdict for one candidate's golden eval; also annotates
        ``verdict`` with the exact diff counts vs the incumbent."""
        b = self.budget
        n = len(self.golden)
        with self._cond:
            inc_am = self._incumbent_argmax
            inc_logits = self._incumbent_logits
            inc_acc = self._incumbent_acc
        flips = int(np.sum(verdict["argmax"] != inc_am))
        verdict["flips"] = flips
        verdict["flip_frac"] = flips / max(1, n)
        # the exact-diff measure bit-identity buys us: rows whose logits
        # are IDENTICAL to the incumbent's (same weights -> n identical)
        verdict["identical_rows"] = int(
            np.sum(np.all(verdict["logits"] == inc_logits, axis=-1))
        )
        fails = []
        if verdict["nonfinite"] > b.max_nonfinite:
            fails.append(
                f"nonfinite logits on {verdict['nonfinite']}/{n} golden "
                f"rows (budget {b.max_nonfinite})"
            )
        labeled = verdict["acc"] is not None and inc_acc is not None
        if labeled:
            # the principled regression gate: exact accuracy vs the
            # incumbent on the SAME rows. Flips stay diagnostics here —
            # an early-training candidate flips most answers while
            # accuracy climbs (module docstring).
            if verdict["acc"] < inc_acc - b.acc_margin:
                fails.append(
                    f"golden accuracy {verdict['acc']:.2f}% regressed "
                    f"past incumbent {inc_acc:.2f}% - {b.acc_margin:.2f} "
                    "margin"
                )
        elif verdict["flip_frac"] > b.max_flip_frac:
            fails.append(
                f"golden argmax flipped on {flips}/{n} rows "
                f"({verdict['flip_frac']:.2f} > budget {b.max_flip_frac})"
            )
        return fails

    # -- the state machine ----------------------------------------------

    def poll_once(self) -> Optional[str]:
        """Drive the state machine one step. Returns the state entered on
        a transition (``shadowing``/``promoted``/``quarantined``), None
        when nothing changed. Split out so tests and bench drive the
        pipeline without timing dependence."""
        with self._cond:
            shadowing = self.state == SHADOWING
        if shadowing:
            return self._check_shadow_budget()
        sig = self._signature()
        if sig is None:
            return None
        with self._cond:
            if sig == self._seen_sig:
                return None
        meta = _read_meta(self.staging_dir, self.name)
        if is_quarantined(self.staging_dir, self.name, meta):
            with self._cond:
                self._seen_sig = sig  # already judged: never re-vetted
            return None
        try:
            params, stats, meta = load_checkpoint_trees(
                os.path.join(self.staging_dir, self.name),
                self.engine.model_name,
                num_classes=self.engine.num_classes,
            )
        except (FileNotFoundError, CheckpointCorrupt) as e:
            # one-poll grace: a publish racing this read looks corrupt
            # until its sidecar rename lands (new payload, old manifest).
            # Only the SAME signature failing again — a settled pair that
            # still does not verify — is a genuinely corrupt candidate.
            with self._cond:
                settled = self._corrupt_sig == sig
                self._corrupt_sig = sig
            if not settled:
                return None
            with self._cond:
                self._seen_sig = sig
            self._c_candidates.inc()
            return self._reject(f"corrupt candidate: {e}", meta)
        if self._signature() != sig:
            return None  # republished mid-read; the next poll settles it
        with self._cond:
            self._corrupt_sig = None
        self._c_candidates.inc()
        # in-flight vetting is journaled BEFORE the candidate touches the
        # canary engine: a controller relaunched mid-vet knows exactly
        # which candidate was on the bench (durable control plane)
        self._journal(
            "vet-begin",
            signature=list(sig) if sig is not None else None,
            epoch=meta.get("epoch"),
        )
        try:
            self.engine.swap_weights(params, stats)
        except ValueError as e:
            with self._cond:
                self._seen_sig = sig
            return self._reject(f"wrong-model candidate: {e}", meta)
        with self._cond:
            self._seen_sig = sig
            self._candidate_sig = sig
            self._candidate = {"meta": meta, "params": params, "stats": stats}
            self._shadow = self._zero_shadow()
            self._token += 1
            self.state = SHADOWING
        self._g_state.set(_STATE_IDS[SHADOWING])
        self._g_shadow_remaining.set(self.budget.min_shadow_requests)
        trace.instant(
            "canary/candidate", epoch=meta.get("epoch"),
            best_acc=meta.get("best_acc"),
        )
        verdict = self._golden_eval()
        failures = self._golden_failures(verdict)
        with self._cond:
            if self._candidate is not None:
                self._candidate["golden"] = verdict
        if failures:
            return self._reject("; ".join(failures), meta)
        if self.budget.min_shadow_requests <= 0:
            return self._promote(meta)
        log.info(
            "canary shadowing candidate epoch %s (golden: %d/%d flips, "
            "acc %s): needs %d shadow requests",
            meta.get("epoch"), verdict["flips"], len(self.golden),
            f"{verdict['acc']:.2f}%" if verdict["acc"] is not None else "n/a",
            self.budget.min_shadow_requests,
        )
        return SHADOWING

    def _check_shadow_budget(self) -> Optional[str]:
        b = self.budget
        with self._cond:
            s = dict(self._shadow)
            meta = (self._candidate or {}).get("meta", {})
        if s["errors"] > b.max_shadow_errors:
            return self._reject(
                f"shadow errors {s['errors']} > budget "
                f"{b.max_shadow_errors}", meta,
            )
        if s["requests"] < b.min_shadow_requests:
            return None
        frac = s["flip_rows"] / max(1, s["rows"])
        if frac > b.max_shadow_flip_frac:
            return self._reject(
                f"shadow argmax flipped on {s['flip_rows']}/{s['rows']} "
                f"rows ({frac:.2f} > budget {b.max_shadow_flip_frac})",
                meta,
            )
        return self._promote(meta)

    def _promote(self, meta: dict) -> Optional[str]:
        t0 = time.perf_counter()
        sig = self._signature()
        abandoned = False
        with self._cond:
            if sig != self._candidate_sig:
                # the trainer republished staging AFTER this candidate
                # was vetted: promoting now would publish unvetted bytes.
                # Abandon; the next poll evaluates the new publish.
                log.warning(
                    "staging republished mid-vetting; abandoning the "
                    "vetted candidate (epoch %s) for the newer one",
                    meta.get("epoch"),
                )
                self.state = STAGING
                self._token += 1
                abandoned = True
            else:
                gen = self.generation + 1
                shadow_requests = self._shadow["requests"]
        if abandoned:
            self._journal(
                "vet-verdict", verdict="abandoned", epoch=meta.get("epoch")
            )
            return None
        # the verdict is durable BEFORE the publish actuation: a relaunch
        # between them resumes the generation counter at `gen`, never
        # re-issuing it to a different candidate
        self._journal(
            "vet-verdict",
            verdict="promoted",
            generation=gen,
            epoch=meta.get("epoch"),
        )
        path = publish_checkpoint(
            self.staging_dir, self.live_dir, name=self.name,
            extra_meta={
                "promotion": {
                    "generation": gen,
                    "promoted_at": time.time(),
                    "shadow_requests": shadow_requests,
                }
            },
        )
        ms = (time.perf_counter() - t0) * 1e3
        with self._cond:
            self.generation = gen
            self.state = PROMOTED
            cand = self._candidate or {}
            verdict = cand.get("golden")
            # the candidate IS the incumbent now: its weight trees become
            # the rollback target, its golden logits the exact baseline
            if "params" in cand:
                self._incumbent = (cand["params"], cand["stats"])
            if verdict is not None:
                self._incumbent_logits = verdict["logits"]
                self._incumbent_argmax = verdict["argmax"]
                self._incumbent_acc = verdict["acc"]
            self._token += 1
        self._c_promotions.inc()
        self._h_promote.observe(ms)
        self._g_generation.set(gen)
        self._g_state.set(_STATE_IDS[PROMOTED])
        trace.instant(
            "canary/promoted", generation=gen, epoch=meta.get("epoch"),
            promote_ms=round(ms, 3),
        )
        log.info(
            "canary PROMOTED epoch %s -> %s (generation %d, %.1f ms, "
            "%d shadow requests)",
            meta.get("epoch"), path, gen, ms, shadow_requests,
        )
        return PROMOTED

    def _reject(self, reason: str, meta: dict) -> str:
        self._journal(
            "vet-verdict",
            verdict="quarantined",
            reason=reason,
            epoch=meta.get("epoch"),
        )
        quarantine_checkpoint(
            self.staging_dir, self.name, reason, meta=meta,
            extra={"generation": self.generation},
        )
        with self._cond:
            inc = self._incumbent
            self.state = QUARANTINED
            self.last_rejected = {
                "reason": reason, "epoch": meta.get("epoch"),
            }
            self._token += 1
        # exact rollback: the canary swaps back to the incumbent's weight
        # trees — its post-rollback outputs are bit-identical to
        # pre-candidate (same weights, same compiled programs)
        self.engine.swap_weights(*inc)
        self._c_rejected.inc()
        self._g_state.set(_STATE_IDS[QUARANTINED])
        trace.instant(
            "canary/quarantined", reason=reason, epoch=meta.get("epoch"),
        )
        log.warning(
            "canary QUARANTINED candidate epoch %s: %s (tombstone in %s; "
            "the fleet never served it)",
            meta.get("epoch"), reason, self.staging_dir,
        )
        return QUARANTINED

    # -- shadow tee ------------------------------------------------------

    def offer(self, images, incumbent_logits, priority="interactive") -> bool:
        """Tee one answered live request toward the canary — fire and
        forget. Only interactive traffic is sampled (the tee models
        user-facing risk; bulk rows add volume, not signal), at
        ``shadow_fraction`` via a deterministic counter, into a bounded
        queue (full = drop + count). Never raises, never blocks the
        caller beyond one lock+append — the client's response is already
        sealed in ``incumbent_logits``."""
        try:
            if priority != "interactive" or self.shadow_fraction <= 0:
                return False
            with self._cond:
                if self.state != SHADOWING:
                    return False
                self._offers += 1
                take = int(self._offers * self.shadow_fraction) > int(
                    (self._offers - 1) * self.shadow_fraction
                )
                if not take:
                    return False
                if len(self._queue) >= self.shadow_queue:
                    dropped = True
                else:
                    dropped = False
                    self._queue.append(
                        (
                            self._token,
                            np.array(images, dtype=np.uint8, copy=True),
                            np.array(
                                incumbent_logits, dtype=np.float32,
                                copy=True,
                            ),
                        )
                    )
                    self._cond.notify_all()
            if dropped:
                self._c_shadow_dropped.inc()
            return not dropped
        except Exception:
            # the tee must never become the client's problem
            log.exception("canary shadow offer failed")
            return False

    def _process_shadow(self, item) -> None:
        token, x, inc_logits = item
        t0 = time.perf_counter()
        try:
            out = np.asarray(self.engine.predict(x))
        except Exception as e:
            with self._cond:
                if token == self._token and self.state == SHADOWING:
                    self._shadow["errors"] += 1
            self._c_shadow_errors.inc()
            log.warning("canary shadow predict failed: %s", e)
            return
        ms = (time.perf_counter() - t0) * 1e3
        flips = int(
            np.sum(np.argmax(out, axis=-1) != np.argmax(inc_logits, axis=-1))
        )
        identical = bool(np.array_equal(out, inc_logits))
        with self._cond:
            if token != self._token or self.state != SHADOWING:
                return  # verdict already reached: stale sample
            s = self._shadow
            s["requests"] += 1
            s["rows"] += int(x.shape[0])
            s["flip_rows"] += flips
            s["identical"] += 1 if identical else 0
            remaining = max(
                0, self.budget.min_shadow_requests - s["requests"]
            )
        self._c_shadow_requests.inc()
        self._c_shadow_rows.inc(int(x.shape[0]))
        self._c_shadow_flips.inc(flips)
        if identical:
            self._c_shadow_identical.inc()
        self._h_shadow.observe(ms)
        self._g_shadow_remaining.set(remaining)

    def process_shadow_queue(self) -> int:
        """Drain the shadow queue on the calling thread; returns how many
        samples were processed. Tests drive the tee deterministically
        through this — the background worker uses the same per-item
        path."""
        n = 0
        while True:
            with self._cond:
                if not self._queue:
                    return n
                item = self._queue.popleft()
            self._process_shadow(item)
            n += 1

    # -- status / lifecycle ---------------------------------------------

    def status(self) -> dict:
        """The canary block ``/healthz`` serves (frontend + router)."""
        with self._cond:
            cand_meta = (self._candidate or {}).get("meta", {})
            out = {
                "state": self.state,
                "generation": self.generation,
                "candidate_epoch": cand_meta.get("epoch"),
                "candidate_best_acc": cand_meta.get("best_acc"),
                "shadow": dict(self._shadow),
                "last_rejected": (
                    dict(self.last_rejected)
                    if self.last_rejected is not None
                    else None
                ),
            }
        out["promotions"] = int(self._c_promotions.value)
        out["rejected"] = int(self._c_rejected.value)
        out["shadow_fraction"] = self.shadow_fraction
        return out

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:
                log.exception("canary poll failed; retrying next poll")

    def _shadow_run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopping:
                    self._cond.wait()
                if self._stopping:
                    return  # undrained shadow samples are advisory only
                item = self._queue.popleft()
            self._process_shadow(item)

    def start(self) -> "PromotionController":
        with self._cond:
            self._stopping = False
            if self._poll_thread is None or not self._poll_thread.is_alive():
                self._stop.clear()
                self._poll_thread = threading.Thread(
                    target=self._run, name="canary-poll", daemon=True
                )
                self._poll_thread.start()
            if (
                self._shadow_thread is None
                or not self._shadow_thread.is_alive()
            ):
                self._shadow_thread = threading.Thread(
                    target=self._shadow_run, name="canary-shadow",
                    daemon=True,
                )
                self._shadow_thread.start()
        return self

    def stop(self) -> None:
        """Stop and JOIN both threads (poll + shadow worker); idempotent.
        After stop() returns, no controller thread exists."""
        self._stop.set()
        # take the handles under the lock, join OUTSIDE it (the worker
        # needs the condition to observe _stopping)
        with self._cond:
            self._stopping = True
            t1 = self._poll_thread
            t2 = self._shadow_thread
            self._poll_thread = None
            self._shadow_thread = None
            self._cond.notify_all()
        if t1 is not None:
            t1.join()
        if t2 is not None:
            t2.join()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class ShadowBackend:
    """Single-replica tee: serve through ``backend`` unchanged, offer
    each answered request to the canary controller, and merge the canary
    block into ``/healthz``. The client path gains one lock+append —
    never a canary compute, never a canary error (offer() swallows its
    own failures). The router-side equivalent is
    :meth:`Router.attach_shadow`."""

    def __init__(self, backend, controller: PromotionController):
        self.backend = backend
        self.controller = controller

    def predict(
        self,
        images,
        deadline_ms: Optional[float] = None,
        priority: str = "interactive",
    ):
        out = self.backend.predict(
            images, deadline_ms=deadline_ms, priority=priority
        )
        self.controller.offer(images, out, priority=priority)
        return out

    @property
    def engine_version(self) -> int:
        return int(getattr(self.backend, "engine_version", 0))

    def health(self) -> dict:
        out = dict(self.backend.health())
        out["canary"] = self.controller.status()
        return out
